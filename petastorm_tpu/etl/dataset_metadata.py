"""Dataset materialization & embedded metadata (reference: petastorm/etl/dataset_metadata.py).

Differences from the reference, by design (SURVEY.md §7.1 item 3):

- the writer is **pure pyarrow** — no Spark required (a Spark adapter can layer on top);
- the Unischema is embedded in ``_common_metadata`` as **versioned JSON** under
  ``petastorm_tpu.unischema.v1`` instead of a pickle (the reference acknowledges pickling
  as a fragility: petastorm/etl/dataset_metadata.py:216-218, codecs.py:20-21);
- the rowgroup index JSON stores **per-rowgroup row counts** (not just counts per file) so
  the scheduler can plan work and ``len(reader)`` without touching footers;
- reading datasets written by the *reference* still works: its pickled
  ``dataset-toolkit.unischema.v1`` key is depickled through the restricted shim in
  :mod:`petastorm_tpu.etl.legacy`.
"""

import json
import logging
import os
from contextlib import contextmanager

import pyarrow as pa
import pyarrow.dataset as pads
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.fs_utils import (as_arrow_filesystem,
                                    get_filesystem_and_path_or_paths, path_exists)
from petastorm_tpu.unischema import Unischema, dict_to_encoded_row

logger = logging.getLogger(__name__)

#: JSON-serialized Unischema (this framework's native key)
UNISCHEMA_JSON_KEY = b'petastorm_tpu.unischema.v1'
#: JSON map of {relative file path: [rows per rowgroup]} (native key)
ROW_GROUPS_JSON_KEY = b'petastorm_tpu.row_groups_per_file.v2'

#: Reference-compatibility keys (petastorm/etl/dataset_metadata.py:50-51,223)
LEGACY_UNISCHEMA_PICKLE_KEY = b'dataset-toolkit.unischema.v1'
LEGACY_ROW_GROUPS_KEY = b'dataset-toolkit.num_row_groups_per_file.v1'

DEFAULT_ROW_GROUP_SIZE_MB = 32


class RowGroupIndices(object):
    """The unit of scheduling: one Parquet rowgroup (reference:
    petastorm/etl/dataset_metadata.py:35-46), extended with the fragment's hive partition
    key/values so partition-predicate pruning needs no footer access."""

    __slots__ = ('fragment_index', 'fragment_path', 'row_group_id', 'row_group_num_rows',
                 'partition_keys')

    def __init__(self, fragment_index, fragment_path, row_group_id, row_group_num_rows,
                 partition_keys=None):
        self.fragment_index = fragment_index
        self.fragment_path = fragment_path
        self.row_group_id = row_group_id
        self.row_group_num_rows = row_group_num_rows
        self.partition_keys = partition_keys or {}

    def __repr__(self):
        return ('RowGroupIndices(fragment_index={}, fragment_path={!r}, row_group_id={}, '
                'row_group_num_rows={}, partition_keys={})'
                .format(self.fragment_index, self.fragment_path, self.row_group_id,
                        self.row_group_num_rows, self.partition_keys))

    def __eq__(self, other):
        return (isinstance(other, RowGroupIndices)
                and all(getattr(self, s) == getattr(other, s) for s in self.__slots__))

    def __hash__(self):
        return hash((self.fragment_path, self.row_group_id))


class DatasetHandle(object):
    """An opened Parquet dataset: filesystem + paths + pyarrow dataset object. The analog
    of the reference's ``pq.ParquetDataset`` usage (petastorm/reader.py:422)."""

    def __init__(self, filesystem, path_or_paths, arrow_dataset):
        self.filesystem = filesystem
        self.path_or_paths = path_or_paths
        self.arrow_dataset = arrow_dataset

    @property
    def root_path(self):
        if isinstance(self.path_or_paths, (list, tuple)):
            return os.path.dirname(self.path_or_paths[0])
        return self.path_or_paths

    @property
    def schema(self):
        return self.arrow_dataset.schema

    @property
    def partition_field_names(self):
        partitioning = getattr(self.arrow_dataset, 'partitioning', None)
        if partitioning is None or partitioning.schema is None:
            return []
        data_names = set()
        for fragment in self.arrow_dataset.get_fragments():
            data_names = set(fragment.physical_schema.names)
            break
        return [name for name in partitioning.schema.names if name not in data_names]


def open_dataset(dataset_url_or_urls, storage_options=None, filesystem=None):
    """Resolve URL(s) and open a pyarrow dataset with hive-partition discovery.
    ``_``/``.``-prefixed files (``_common_metadata`` etc.) are excluded by pyarrow's
    default ``ignore_prefixes``."""
    fs, path_or_paths = get_filesystem_and_path_or_paths(
        dataset_url_or_urls, storage_options=storage_options, filesystem=filesystem)
    # The handle's filesystem flows into Arrow C++ (make_fragment in the workers and
    # rowgroup indexing), which requires a real pyarrow filesystem — unwrap any HA
    # failover proxy once here.
    fs = as_arrow_filesystem(fs)
    arrow_dataset = pads.dataset(path_or_paths, filesystem=fs,
                                 format='parquet', partitioning='hive')
    return DatasetHandle(fs, path_or_paths, arrow_dataset)


# ---------------------------------------------------------------------------
# Write path
# ---------------------------------------------------------------------------

def rows_to_arrow_table(schema, rows):
    """Encode a list of row dicts through the schema's codecs into an Arrow table whose
    columns use each field's storage type."""
    encoded = [dict_to_encoded_row(schema, row) for row in rows]
    arrow_schema = schema.as_arrow_schema()
    columns = []
    for field in arrow_schema:
        values = [row[field.name] for row in encoded]
        columns.append(pa.array(values, type=field.type))
    return pa.Table.from_arrays(columns, schema=arrow_schema)


def _estimate_row_bytes(table):
    if table.num_rows == 0:
        return 1
    return max(1, table.nbytes // table.num_rows)


def write_table_files(filesystem, path, arrow_schema, batches,
                      rowgroup_size_mb=DEFAULT_ROW_GROUP_SIZE_MB, rows_per_file=None,
                      compression='snappy', file_prefix='part'):
    """Stream record batches into ``<path>/<prefix>_NNNNN.parquet`` files at bounded
    memory: rowgroups of ~``rowgroup_size_mb`` are flushed through a ``ParquetWriter`` as
    they fill, files roll over at ``rows_per_file`` (None = one file). The single write
    loop behind :func:`write_rows`, the converter, and the copy tool. Returns total rows
    written."""
    state = {'writer': None, 'sink': None, 'file_index': 0, 'file_rows': 0, 'total': 0,
             'pending': [], 'pending_rows': 0, 'row_group_rows': None}

    def _flush_rowgroup():
        if not state['pending']:
            return
        rowgroup = pa.Table.from_batches(state['pending'], schema=arrow_schema)
        if state['writer'] is None:
            file_path = '{}/{}_{:05d}.parquet'.format(path, file_prefix,
                                                      state['file_index'])
            state['sink'] = filesystem.open_output_stream(file_path)
            state['writer'] = pq.ParquetWriter(state['sink'], arrow_schema,
                                               compression=compression)
        state['writer'].write_table(rowgroup, row_group_size=rowgroup.num_rows)
        state['file_rows'] += rowgroup.num_rows
        state['total'] += rowgroup.num_rows
        state['pending'], state['pending_rows'] = [], 0

    def _close_file():
        _flush_rowgroup()
        if state['writer'] is not None:
            state['writer'].close()
            state['sink'].close()
            state['writer'] = state['sink'] = None
            state['file_index'] += 1
            state['file_rows'] = 0

    for batch in batches:
        if batch.num_rows == 0:
            continue
        if state['row_group_rows'] is None:
            per_row = max(1, batch.nbytes // max(1, batch.num_rows))
            state['row_group_rows'] = max(1, (rowgroup_size_mb << 20) // per_row)
        offset = 0
        while offset < batch.num_rows:
            take = min(batch.num_rows - offset,
                       state['row_group_rows'] - state['pending_rows'])
            if rows_per_file is not None:
                take = min(take,
                           rows_per_file - state['file_rows'] - state['pending_rows'])
            state['pending'].append(batch.slice(offset, take))
            state['pending_rows'] += take
            offset += take
            if state['pending_rows'] >= state['row_group_rows']:
                _flush_rowgroup()
            if rows_per_file is not None and \
                    state['file_rows'] + state['pending_rows'] >= rows_per_file:
                _close_file()
    _close_file()
    return state['total']


def write_rows(dataset_url, schema, rows, rowgroup_size_mb=DEFAULT_ROW_GROUP_SIZE_MB,
               rows_per_file=None, n_files=None, storage_options=None, filesystem=None,
               file_prefix='part', compression='snappy'):
    """One-shot materialization: encode ``rows`` (list of dicts) and write a petastorm_tpu
    Parquet store with embedded metadata. The Spark-free equivalent of the reference's
    materialize-with-Spark flow (petastorm/etl/dataset_metadata.py:68-147).
    ``compression`` is any pyarrow Parquet codec ('snappy' default; 'zstd' trades write
    CPU for smaller shipped bytes — the right choice for coefficient-domain image
    stores feeding on-chip decode)."""
    with materialize_dataset(dataset_url, schema, rowgroup_size_mb=rowgroup_size_mb,
                             storage_options=storage_options, filesystem=filesystem):
        fs, path = get_filesystem_and_path_or_paths(dataset_url,
                                                    storage_options=storage_options,
                                                    filesystem=filesystem)
        fs.create_dir(path, recursive=True)
        table = rows_to_arrow_table(schema, rows)
        if rows_per_file is None:
            if n_files is None:
                n_files = 1
            rows_per_file = max(1, (table.num_rows + n_files - 1) // max(1, n_files))
        write_table_files(fs, path, table.schema, table.to_batches(),
                          rowgroup_size_mb=rowgroup_size_mb, rows_per_file=rows_per_file,
                          file_prefix=file_prefix, compression=compression)


@contextmanager
def materialize_dataset(dataset_url, schema, rowgroup_size_mb=DEFAULT_ROW_GROUP_SIZE_MB,
                        storage_options=None, filesystem=None):
    """Context manager around any Parquet-writing code; on exit, embeds the Unischema and
    rowgroup index into ``_common_metadata`` and verifies readability (reference:
    petastorm/etl/dataset_metadata.py:68-147). The body may write files with pyarrow,
    Spark, or :func:`write_rows` above."""
    yield
    fs, path = get_filesystem_and_path_or_paths(dataset_url, storage_options=storage_options,
                                                filesystem=filesystem)
    fs = as_arrow_filesystem(fs)   # handle.filesystem feeds Arrow C++ (see open_dataset)
    arrow_dataset = pads.dataset(path, filesystem=fs,
                                 format='parquet', partitioning='hive')
    handle = DatasetHandle(fs, path, arrow_dataset)
    row_groups_map = _scan_row_groups_per_file(handle)
    metadata = {
        UNISCHEMA_JSON_KEY: json.dumps(schema.to_json_dict()).encode('utf-8'),
        ROW_GROUPS_JSON_KEY: json.dumps(row_groups_map).encode('utf-8'),
        # Reference-readable count-per-file key (same JSON layout the reference writes:
        # etl/dataset_metadata.py:223-235) so its tooling can at least count rowgroups.
        LEGACY_ROW_GROUPS_KEY: json.dumps(
            {rel: len(entry['row_groups'])
             for rel, entry in row_groups_map.items()}).encode('utf-8'),
    }
    write_dataset_metadata(handle, metadata)
    # Verification read (reference: etl/dataset_metadata.py:136-147).
    loaded = load_row_groups(open_dataset(dataset_url, storage_options=storage_options,
                                          filesystem=filesystem))
    if not loaded:
        raise MetadataError('Materialization verification failed: no rowgroups found '
                            'under {!r}'.format(dataset_url))


def _relative_path(root, full_path):
    root = root.rstrip('/')
    if full_path.startswith(root + '/'):
        return full_path[len(root) + 1:]
    return full_path


def _scan_row_groups_per_file(handle):
    """Read every fragment footer and build
    ``{relative path: {'size': file_bytes, 'row_groups': [rows per rowgroup]}}``.
    The file size lets readers detect a stale index with a stat instead of a footer read."""
    result = {}
    root = handle.root_path
    for fragment in sorted(handle.arrow_dataset.get_fragments(), key=lambda f: f.path):
        fragment.ensure_complete_metadata()
        size = handle.filesystem.get_file_info(fragment.path).size
        result[_relative_path(root, fragment.path)] = {
            'size': size,
            'row_groups': [rg.num_rows for rg in fragment.row_groups],
        }
    return result


def common_metadata_path(handle):
    """Path of the dataset's ``_common_metadata`` file under the handle's root."""
    return handle.root_path.rstrip('/') + '/_common_metadata'


def read_metadata_dict(handle):
    """Key-value metadata of ``_common_metadata``, or {} when absent (reference:
    petastorm/utils.py:90-109)."""
    md_path = common_metadata_path(handle)
    if not path_exists(handle.filesystem, md_path):
        return {}
    with handle.filesystem.open_input_file(md_path) as f:
        file_metadata = pq.read_metadata(f)
    return file_metadata.metadata or {}


def write_dataset_metadata(handle, new_keys):
    """Merge ``new_keys`` into ``_common_metadata``'s key-value metadata, preserving
    existing keys (reference: petastorm/utils.py:111-142)."""
    existing = dict(read_metadata_dict(handle))
    existing.update(new_keys)
    base_schema = None
    md_path = common_metadata_path(handle)
    if path_exists(handle.filesystem, md_path):
        with handle.filesystem.open_input_file(md_path) as f:
            base_schema = pq.read_schema(f)
    if base_schema is None:
        base_schema = handle.arrow_dataset.schema
    schema_with_md = base_schema.with_metadata(existing)
    with handle.filesystem.open_output_stream(md_path) as sink:
        pq.write_metadata(schema_with_md, sink)


# ---------------------------------------------------------------------------
# Read path
# ---------------------------------------------------------------------------

def load_row_groups(handle, on_fragment_error=None):
    """List every rowgroup of the dataset in deterministic (path-sorted) order — the
    reproducible-shuffle prerequisite (reference: petastorm/etl/dataset_metadata.py:237-275).
    Prefers the metadata JSON index; silently recomputes from footers when it is absent or
    stale.

    ``on_fragment_error`` — optional ``callback(exc, fragment_path, fragment_index)``
    for the reader's skip-with-quarantine mode (docs/robustness.md): a fragment whose
    footer cannot be read for a PERMANENT reason (truncated/corrupt file) is excluded
    from the enumeration and reported to the callback instead of aborting; transient IO
    failures still raise so the caller's retry policy governs them. Default (None)
    preserves the raise-on-first-error behavior."""
    metadata = read_metadata_dict(handle)
    root = handle.root_path
    index_map = None
    if ROW_GROUPS_JSON_KEY in metadata:
        try:
            index_map = json.loads(metadata[ROW_GROUPS_JSON_KEY].decode('utf-8'))
        except (ValueError, UnicodeDecodeError):
            logger.warning('Could not parse rowgroup index metadata; recomputing from '
                           'footers')
    fragments = sorted(handle.arrow_dataset.get_fragments(), key=lambda f: f.path)
    row_groups = []
    for fragment_index, fragment in enumerate(fragments):
        rel = _relative_path(root, fragment.path)
        partition_keys = _fragment_partition_keys(fragment)
        counts = None
        if index_map is not None and rel in index_map:
            entry = index_map[rel]
            actual_size = handle.filesystem.get_file_info(fragment.path).size
            if entry.get('size') == actual_size:
                counts = entry['row_groups']
            else:
                logger.warning('Rowgroup index for %s is stale (size %s != %s); '
                               'recomputing from footer', rel, entry.get('size'), actual_size)
        if counts is None:
            try:
                fragment.ensure_complete_metadata()
                counts = [rg.num_rows for rg in fragment.row_groups]
            except Exception as exc:  # noqa: BLE001 - policy decides below
                from petastorm_tpu.resilience import is_transient_error
                if on_fragment_error is None or is_transient_error(exc):
                    raise
                logger.warning('Excluding fragment %s from the rowgroup schedule: '
                               'footer unreadable (%s: %s)', fragment.path,
                               type(exc).__name__, exc)
                on_fragment_error(exc, fragment.path, fragment_index)
                continue
        for row_group_id, num_rows in enumerate(counts):
            row_groups.append(RowGroupIndices(fragment_index, fragment.path, row_group_id,
                                              num_rows, partition_keys))
    return row_groups


def _fragment_partition_keys(fragment):
    try:
        from pyarrow.dataset import get_partition_keys
        return get_partition_keys(fragment.partition_expression)
    except Exception:  # pragma: no cover - older pyarrow fallback
        return {}


def get_schema(handle):
    """Load the Unischema embedded in ``_common_metadata`` — native JSON key first, then
    the reference's pickled key through the legacy shim (reference:
    petastorm/etl/dataset_metadata.py:340-373)."""
    metadata = read_metadata_dict(handle)
    if UNISCHEMA_JSON_KEY in metadata:
        return Unischema.from_json_dict(
            json.loads(metadata[UNISCHEMA_JSON_KEY].decode('utf-8')))
    if LEGACY_UNISCHEMA_PICKLE_KEY in metadata:
        from petastorm_tpu.etl.legacy import depickle_legacy_unischema
        return depickle_legacy_unischema(metadata[LEGACY_UNISCHEMA_PICKLE_KEY])
    raise MetadataError(
        'Dataset at {!r} has no unischema metadata (neither {} nor legacy {}). Either it '
        'was not written with materialize_dataset, or metadata was lost. Use '
        'make_batch_reader / schema inference for plain Parquet stores.'
        .format(handle.root_path, UNISCHEMA_JSON_KEY, LEGACY_UNISCHEMA_PICKLE_KEY))


def get_schema_from_dataset_url(dataset_url_or_urls, storage_options=None, filesystem=None):
    """Reference: petastorm/etl/dataset_metadata.py:376-395."""
    return get_schema(open_dataset(dataset_url_or_urls, storage_options=storage_options,
                                   filesystem=filesystem))


def infer_or_load_unischema(handle):
    """Embedded schema when present, else infer from the Arrow schema (reference:
    petastorm/etl/dataset_metadata.py:398-406)."""
    try:
        return get_schema(handle)
    except MetadataError:
        logger.debug('Dataset has no embedded unischema; inferring from Arrow schema')
        return Unischema.from_arrow_schema(handle.schema)
