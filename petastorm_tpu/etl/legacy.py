"""Read-compatibility with datasets written by the reference (petastorm).

The reference embeds its Unischema as a **pickle** under ``dataset-toolkit.unischema.v1``
(petastorm/etl/dataset_metadata.py:209-220). To read those stores without petastorm or
pyspark installed, this module depickles through a *restricted unpickler* (the reference's
own safety posture: petastorm/etl/legacy.py:22-46) whose ``find_class`` maps every
petastorm / pyspark.sql.types global onto shim classes that reconstruct the equivalent
:mod:`petastorm_tpu` objects. Pre-rename package paths (``av.*.dataset_toolkit``) are
byte-substituted first, mirroring the reference's compatibility rule
(petastorm/etl/legacy.py:57-81).
"""

import io
import pickle

import pyarrow as pa

from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec, NdarrayCodec,
                                  ScalarCodec)
from petastorm_tpu.unischema import Unischema, UnischemaField

def _safe_numpy_names():
    import numpy as np
    names = {'dtype', 'ndarray'}
    for name in dir(np):
        obj = getattr(np, name)
        if isinstance(obj, type) and issubclass(obj, np.generic):
            names.add(name)
    return names


#: exact (module-root -> allowed global names). A blanket module allowlist (the reference's
#: approach, petastorm/etl/legacy.py:22-30) still exposes e.g. builtins.eval to a crafted
#: pickle; only data-bearing constructors are permitted here.
_SAFE_GLOBALS = {
    'builtins': {'object', 'tuple', 'list', 'dict', 'set', 'frozenset', 'bytearray',
                 'complex', 'bytes', 'str', 'int', 'float', 'bool'},
    '__builtin__': {'object', 'tuple', 'list', 'dict', 'set', 'frozenset', 'bytearray',
                    'complex', 'bytes', 'str', 'int', 'float', 'bool'},
    'copyreg': {'_reconstructor'},
    'copy_reg': {'_reconstructor'},
    'collections': {'OrderedDict', 'defaultdict'},
    'decimal': {'Decimal'},
    'numpy': _safe_numpy_names(),
    'numpy.core.multiarray': {'_reconstruct', 'scalar'},
    'numpy._core.multiarray': {'_reconstruct', 'scalar'},
}


class _LegacyUnischema(Unischema):
    """Reconstructs our Unischema from a pickled petastorm Unischema's state dict."""

    def __new__(cls, *args, **kwargs):
        return object.__new__(cls)

    def __init__(self, *args, **kwargs):  # state arrives via __setstate__
        if args or kwargs:
            Unischema.__init__(self, *args, **kwargs)

    def __setstate__(self, state):
        fields = [_coerce_field(f) for f in state['_fields'].values()]
        Unischema.__init__(self, state.get('_name', 'legacy'), fields)


class _LegacyFieldTuple(tuple):
    """Stand-in for the reference's UnischemaField namedtuple. Old pickles construct it
    three ways: ``copyreg._reconstructor(cls, tuple, values)`` (protocol 0 — bypasses
    ``cls.__new__``, so the instance stays a plain tuple until :func:`_coerce_field`),
    NEWOBJ with positional args (namedtuple ``__getnewargs__``), or a direct REDUCE call."""

    def __new__(cls, *args):
        if len(args) == 1 and isinstance(args[0], (tuple, list)):
            return tuple.__new__(cls, args[0])
        return tuple.__new__(cls, args)


def _coerce_field(field):
    if isinstance(field, UnischemaField):
        return field
    if isinstance(field, tuple):
        return _convert_field(*field)
    raise pickle.UnpicklingError('Unexpected legacy field representation {!r}'.format(field))


def _convert_field(name, numpy_dtype, shape, codec=None, nullable=False):
    return UnischemaField(name, numpy_dtype, tuple(shape or ()), codec=codec,
                          nullable=bool(nullable))


def _pyspark_restore(name, fields, values):
    """Shim for pyspark.serializers._restore — pyspark's namedtuple-hijack pickles
    namedtuple instances as ``_restore(class_name, field_names, values)``."""
    if name == 'UnischemaField':
        kwargs = dict(zip(fields, values))
        return _convert_field(**kwargs)
    return tuple(values)


class _LegacyScalarCodec(ScalarCodec):
    def __new__(cls, *args, **kwargs):
        return object.__new__(cls)

    def __init__(self, *args, **kwargs):
        if args or kwargs:
            ScalarCodec.__init__(self, *args, **kwargs)

    def __setstate__(self, state):
        spark_type = (state or {}).get('_spark_type')
        ScalarCodec.__init__(self, _spark_type_to_arrow(spark_type))


class _LegacyNdarrayCodec(NdarrayCodec):
    def __setstate__(self, state):
        NdarrayCodec.__init__(self)


class _LegacyCompressedNdarrayCodec(CompressedNdarrayCodec):
    def __setstate__(self, state):
        CompressedNdarrayCodec.__init__(self)


class _LegacyCompressedImageCodec(CompressedImageCodec):
    def __new__(cls, *args, **kwargs):
        return object.__new__(cls)

    def __init__(self, *args, **kwargs):
        if args or kwargs:
            CompressedImageCodec.__init__(self, *args, **kwargs)

    def __setstate__(self, state):
        state = state or {}
        image_codec = state.get('_image_codec', '.png').lstrip('.')
        if image_codec == 'jpg':
            image_codec = 'jpeg'
        CompressedImageCodec.__init__(self, image_codec, state.get('_quality', 80))


class _SparkTypeStub(object):
    """Placeholder standing in for a pyspark.sql.types type instance; carries the class
    name and any state (e.g. DecimalType precision/scale)."""

    type_name = None

    def __init__(self, *args, **kwargs):
        if args:
            # DecimalType(precision, scale) positional form
            self.__dict__['precision'] = args[0]
            if len(args) > 1:
                self.__dict__['scale'] = args[1]
        self.__dict__.update(kwargs)

    def __setstate__(self, state):
        if state:
            self.__dict__.update(state)


_SPARK_TYPE_TO_ARROW = {
    'BooleanType': pa.bool_(),
    'ByteType': pa.int8(),
    'ShortType': pa.int16(),
    'IntegerType': pa.int32(),
    'LongType': pa.int64(),
    'FloatType': pa.float32(),
    'DoubleType': pa.float64(),
    'StringType': pa.string(),
    'BinaryType': pa.binary(),
    'TimestampType': pa.timestamp('ns'),
    'DateType': pa.date32(),
}


def _spark_type_to_arrow(stub):
    if stub is None:
        return None
    name = getattr(stub, 'type_name', type(stub).__name__)
    if name == 'DecimalType':
        precision = getattr(stub, 'precision', 10)
        scale = getattr(stub, 'scale', 0)
        return pa.decimal128(precision, scale)
    if name in _SPARK_TYPE_TO_ARROW:
        return _SPARK_TYPE_TO_ARROW[name]
    raise pickle.UnpicklingError('Unsupported legacy Spark type {!r}'.format(name))


_spark_stub_cache = {}


def _spark_type_stub_class(name):
    if name not in _spark_stub_cache:
        _spark_stub_cache[name] = type(name, (_SparkTypeStub,), {'type_name': name})
    return _spark_stub_cache[name]


_PETASTORM_SHIMS = {
    ('petastorm.unischema', 'Unischema'): _LegacyUnischema,
    ('petastorm.unischema', 'UnischemaField'): _LegacyFieldTuple,
    ('petastorm.codecs', 'ScalarCodec'): _LegacyScalarCodec,
    ('petastorm.codecs', 'NdarrayCodec'): _LegacyNdarrayCodec,
    ('petastorm.codecs', 'CompressedNdarrayCodec'): _LegacyCompressedNdarrayCodec,
    ('petastorm.codecs', 'CompressedImageCodec'): _LegacyCompressedImageCodec,
    ('pyspark.serializers', '_restore'): _pyspark_restore,
}

#: numpy 1.x scalar-type names removed in numpy 2.x, seen in old pickles
_NUMPY_RENAMES = {'string_': 'bytes_', 'unicode_': 'str_', 'int0': 'intp',
                  'uint0': 'uintp', 'float_': 'float64', 'complex_': 'complex128',
                  'object0': 'object_'}


class LegacyUnischemaUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _PETASTORM_SHIMS:
            return _PETASTORM_SHIMS[(module, name)]
        if module == 'pyspark.sql.types':
            return _spark_type_stub_class(name)
        if module.split('.')[0] == 'numpy' and name in _NUMPY_RENAMES:
            name = _NUMPY_RENAMES[name]
        allowed = _SAFE_GLOBALS.get(module, ())
        if name in allowed:
            return super().find_class(module, name)
        raise pickle.UnpicklingError('global {!r}.{!r} is forbidden in legacy unischema '
                                     'pickles'.format(module, name))


#: pre-rename package paths used by petastorm's ancestors (petastorm/etl/legacy.py:66-67)
_LEGACY_PACKAGE_NAMES = ('av.experimental.deepdrive.dataset_toolkit', 'av.ml.dataset_toolkit')
_LEGACY_MODULES = ('codecs', 'unischema', 'sequence')


def _rewrite_prehistoric_names(blob):
    for package in _LEGACY_PACKAGE_NAMES:
        for module in _LEGACY_MODULES:
            old = '\n(c{}.{}\n'.format(package, module).encode('ascii')
            new = '\n(cpetastorm.{}\n'.format(module).encode('ascii')
            blob = blob.replace(old, new)
    return blob


def depickle_legacy_unischema(blob):
    """Depickle a reference-written Unischema blob into a petastorm_tpu Unischema."""
    blob = _rewrite_prehistoric_names(blob)
    result = LegacyUnischemaUnpickler(io.BytesIO(blob)).load()
    if not isinstance(result, Unischema):
        raise pickle.UnpicklingError('Legacy unischema pickle did not contain a Unischema '
                                     '(got {!r})'.format(type(result)))
    return result
