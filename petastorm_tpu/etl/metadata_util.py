"""Print a dataset's schema and rowgroup indexes (reference:
petastorm/etl/metadata_util.py). CLI:
``python -m petastorm_tpu.etl.metadata_util <dataset_url> [--print-values]``.
"""

import argparse
import sys

from petastorm_tpu.etl import dataset_metadata


def main(argv=None):
    """``petastorm-tpu-metadata-util`` console entry: inspect a store's schema and
    rowgroup index (reference: etl/metadata_util.py)."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--skip-schema', action='store_true')
    parser.add_argument('--print-values', action='store_true',
                        help='print every indexed value of every rowgroup index')
    args = parser.parse_args(argv)

    handle = dataset_metadata.open_dataset(args.dataset_url)
    if not args.skip_schema:
        schema = dataset_metadata.infer_or_load_unischema(handle)
        print(schema)
        row_groups = dataset_metadata.load_row_groups(handle)
        print('{} rowgroups, {} rows'.format(
            len(row_groups), sum(rg.row_group_num_rows for rg in row_groups)))
    try:
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
        indexes = get_row_group_indexes(handle)
        for name, indexer in indexes.items():
            print('index {!r} over {}: {} values'.format(name, indexer.column_names,
                                                         len(indexer.indexed_values)))
            if args.print_values:
                for value in indexer.indexed_values:
                    print('  {!r} -> rowgroups {}'.format(
                        value, sorted(indexer.get_row_group_indexes(value))))
    except ValueError:
        print('(no rowgroup indexes)')
    return 0


if __name__ == '__main__':
    sys.exit(main())
