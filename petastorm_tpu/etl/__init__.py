"""ETL layer: dataset materialization, embedded metadata, rowgroup indexing (reference:
petastorm/etl/)."""


class RowGroupIndexerBase(object):
    """Base class for rowgroup indexers (reference: petastorm/etl/__init__.py)."""

    @property
    def index_name(self):
        raise NotImplementedError()

    @property
    def column_names(self):
        raise NotImplementedError()

    @property
    def indexed_values(self):
        raise NotImplementedError()

    def get_row_group_indexes(self, value_key):
        raise NotImplementedError()

    def build_index(self, decoded_rows, piece_index):
        raise NotImplementedError()
