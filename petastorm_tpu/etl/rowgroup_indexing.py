"""Build and load rowgroup indexes embedded in dataset metadata (reference:
petastorm/etl/rowgroup_indexing.py:38-156 — whose compute body is disabled in the
reference snapshot; restored fully here, Spark-free, with JSON storage instead of
pickles)."""

import json
import logging

from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.etl.rowgroup_indexers import indexer_from_json_dict
from petastorm_tpu.unischema import decode_row

logger = logging.getLogger(__name__)

ROWGROUPS_INDEX_KEY = b'petastorm_tpu.rowgroups_index.v1'


def build_rowgroup_index(dataset_url, indexers, storage_options=None, filesystem=None):
    """Scan every rowgroup, feed the requested indexers, and store the resulting indexes
    in ``_common_metadata`` (reference: rowgroup_indexing.py:38-133)."""
    handle = dataset_metadata.open_dataset(dataset_url, storage_options=storage_options,
                                           filesystem=filesystem)
    schema = dataset_metadata.infer_or_load_unischema(handle)
    row_groups = dataset_metadata.load_row_groups(handle)

    columns = sorted({col for indexer in indexers for col in indexer.column_names})
    unknown = [c for c in columns if c not in schema.fields]
    if unknown:
        raise ValueError('Indexed fields {} are not part of the schema'.format(unknown))

    import pyarrow.dataset as pads
    parquet_format = pads.ParquetFileFormat()
    for piece_index, rg in enumerate(row_groups):
        fragment = parquet_format.make_fragment(rg.fragment_path, handle.filesystem,
                                                row_groups=[rg.row_group_id])
        table = fragment.to_table(columns=columns)
        records = table.to_pylist()
        decoded = [decode_row(record, schema) for record in records]
        for indexer in indexers:
            indexer.build_index(decoded, piece_index)

    payload = json.dumps([indexer.to_json_dict() for indexer in indexers]).encode('utf-8')
    dataset_metadata.write_dataset_metadata(handle, {ROWGROUPS_INDEX_KEY: payload})
    return indexers


def get_row_group_indexes(handle):
    """Load stored indexes as {index_name: indexer} (reference:
    rowgroup_indexing.py:136-156)."""
    metadata = dataset_metadata.read_metadata_dict(handle)
    if ROWGROUPS_INDEX_KEY not in metadata:
        raise ValueError('Dataset has no rowgroup index metadata; run '
                         'build_rowgroup_index first')
    entries = json.loads(metadata[ROWGROUPS_INDEX_KEY].decode('utf-8'))
    indexers = [indexer_from_json_dict(entry) for entry in entries]
    return {indexer.index_name: indexer for indexer in indexers}
