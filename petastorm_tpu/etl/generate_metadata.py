"""Regenerate/attach petastorm_tpu metadata on an existing Parquet store (reference:
petastorm/etl/petastorm_generate_metadata.py:48-160). CLI:
``python -m petastorm_tpu.etl.generate_metadata <dataset_url> [--unischema-class path]``.
"""

import argparse
import logging
import sys
from pydoc import locate

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.unischema import Unischema

logger = logging.getLogger(__name__)


def generate_metadata(dataset_url, unischema_class=None, storage_options=None):
    """(Re)write ``_common_metadata`` for an existing store. Schema source priority:
    explicit dotted-path class > already-embedded schema (incl. legacy petastorm pickles,
    which get upgraded to the JSON key) > Arrow-schema inference."""
    if unischema_class:
        schema = locate(unischema_class)
        if schema is None or not isinstance(schema, Unischema):
            raise ValueError('{} does not resolve to a Unischema instance'
                             .format(unischema_class))
    else:
        handle = dataset_metadata.open_dataset(dataset_url,
                                               storage_options=storage_options)
        schema = dataset_metadata.infer_or_load_unischema(handle)
        logger.info('Using %s schema: %s',
                    'embedded' if _has_embedded(handle) else 'inferred', schema.name)
    with dataset_metadata.materialize_dataset(dataset_url, schema,
                                              storage_options=storage_options):
        pass  # data already exists; the context manager writes metadata on exit
    return schema


def _has_embedded(handle):
    try:
        dataset_metadata.get_schema(handle)
        return True
    except MetadataError:
        # precisely the "no embedded unischema" answer this probe exists to
        # give; anything else (IO failures, corrupt footers) should propagate
        return False


def main(argv=None):
    """``petastorm-tpu-generate-metadata`` console entry: (re)write petastorm
    metadata for an existing Parquet store (reference: etl/petastorm_generate_metadata.py)."""
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('dataset_url')
    parser.add_argument('--unischema-class',
                        help='dotted path to a Unischema instance, e.g. '
                             'examples.mnist.schema.MnistSchema')
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    generate_metadata(args.dataset_url, args.unischema_class)
    return 0


if __name__ == '__main__':
    sys.exit(main())
