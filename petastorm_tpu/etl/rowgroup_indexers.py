"""Field-value -> rowgroup-set indexers (reference:
petastorm/etl/rowgroup_indexers.py:21-124)."""

from collections import defaultdict

from petastorm_tpu.etl import RowGroupIndexerBase


class SingleFieldIndexer(RowGroupIndexerBase):
    """Maps every observed value of one field to the set of rowgroup (piece) indexes
    containing it. Mergeable via ``+`` for map-reduce builds (reference:
    rowgroup_indexers.py:21-77)."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._index_field = index_field
        self._index_data = defaultdict(set)

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._index_field]

    @property
    def indexed_values(self):
        return list(self._index_data.keys())

    def get_row_group_indexes(self, value_key):
        return self._index_data.get(_value_token(value_key), set())

    def build_index(self, decoded_rows, piece_index):
        if not decoded_rows:
            raise ValueError('Cannot build index for empty rowgroup')
        for row in decoded_rows:
            value = row[self._index_field]
            if value is not None:
                self._index_data[_value_token(value)].add(piece_index)

    def __add__(self, other):
        if other.column_names != self.column_names:
            raise ValueError('Cannot merge indexers of different fields')
        merged = SingleFieldIndexer(self._index_name, self._index_field)
        for source in (self, other):
            for key, pieces in source._index_data.items():
                merged._index_data[key] |= pieces
        return merged

    # JSON round-trip for the metadata store
    def to_json_dict(self):
        return {'type': 'single_field', 'index_name': self._index_name,
                'index_field': self._index_field,
                'data': {key: sorted(pieces) for key, pieces in self._index_data.items()}}

    @classmethod
    def from_json_dict(cls, d):
        indexer = cls(d['index_name'], d['index_field'])
        for key, pieces in d['data'].items():
            indexer._index_data[key] = set(pieces)
        return indexer


class FieldNotNullIndexer(RowGroupIndexerBase):
    """Indexes rowgroups that contain at least one non-null value of a field (reference:
    rowgroup_indexers.py:80-124)."""

    _NOT_NULL_KEY = '__not_null__'

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._index_field = index_field
        self._pieces = set()

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._index_field]

    @property
    def indexed_values(self):
        return [self._NOT_NULL_KEY]

    def get_row_group_indexes(self, value_key=None):
        return self._pieces

    def build_index(self, decoded_rows, piece_index):
        if not decoded_rows:
            raise ValueError('Cannot build index for empty rowgroup')
        for row in decoded_rows:
            if row[self._index_field] is not None:
                self._pieces.add(piece_index)
                break

    def __add__(self, other):
        if other.column_names != self.column_names:
            raise ValueError('Cannot merge indexers of different fields')
        merged = FieldNotNullIndexer(self._index_name, self._index_field)
        merged._pieces = self._pieces | other._pieces
        return merged

    def to_json_dict(self):
        return {'type': 'field_not_null', 'index_name': self._index_name,
                'index_field': self._index_field, 'data': sorted(self._pieces)}

    @classmethod
    def from_json_dict(cls, d):
        indexer = cls(d['index_name'], d['index_field'])
        indexer._pieces = set(d['data'])
        return indexer


def _value_token(value):
    """Index keys are stored as strings (JSON metadata); lookups tokenize the same way."""
    return str(value)


_INDEXER_TYPES = {'single_field': SingleFieldIndexer, 'field_not_null': FieldNotNullIndexer}


def indexer_from_json_dict(d):
    """Rebuild an indexer from its ``to_json_dict()`` persistence form."""
    return _INDEXER_TYPES[d['type']].from_json_dict(d)
