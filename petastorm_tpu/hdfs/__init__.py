"""HDFS namenode resolution + failover (reference: petastorm/hdfs/)."""
