"""Hadoop-config-driven namenode resolution with HA failover (reference:
petastorm/hdfs/namenode.py:31-316).

``HdfsNamenodeResolver`` parses ``hdfs-site.xml``/``core-site.xml`` found via
``HADOOP_HOME``/``HADOOP_PREFIX``/``HADOOP_INSTALL`` (or an injected configuration dict)
and resolves HA nameservice logical names into concrete namenode URLs.
``HdfsConnector.connect_to_either_namenode`` tries each namenode in order with retries —
the reference's failover contract — over ``pyarrow.fs.HadoopFileSystem``.
"""

import logging
import os
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

_HADOOP_HOME_VARS = ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL')
MAX_NAMENODES = 2


class HdfsConfigError(RuntimeError):
    pass


def _load_hadoop_configuration():
    """Locate and parse hdfs-site.xml + core-site.xml into one {name: value} dict
    (reference: namenode.py:34-65)."""
    config = {}
    for var in _HADOOP_HOME_VARS:
        home = os.environ.get(var)
        if not home:
            continue
        conf_dir = os.path.join(home, 'etc', 'hadoop')
        for file_name in ('core-site.xml', 'hdfs-site.xml'):
            path = os.path.join(conf_dir, file_name)
            if os.path.exists(path):
                config.update(_parse_hadoop_xml(path))
        if config:
            return config
    return config


def _parse_hadoop_xml(path):
    result = {}
    root = ET.parse(path).getroot()
    for prop in root.findall('property'):
        name = prop.findtext('name')
        value = prop.findtext('value')
        if name is not None and value is not None:
            result[name.strip()] = value.strip()
    return result


class HdfsNamenodeResolver(object):
    """Resolve HA nameservice names to namenode host:port lists (reference:
    namenode.py:31-120). An explicit ``configuration`` dict (name -> value, the flattened
    hadoop conf) overrides the environment lookup — the hook the tests use."""

    def __init__(self, configuration=None):
        self._config = configuration if configuration is not None \
            else _load_hadoop_configuration()

    def resolve_default_hdfs_service(self):
        """Return (nameservice, [namenode urls]) for fs.defaultFS (reference:
        namenode.py:110-120)."""
        default_fs = self._config.get('fs.defaultFS', '')
        if not default_fs.startswith('hdfs://'):
            raise HdfsConfigError('fs.defaultFS is not an HDFS URL: {!r}'
                                  .format(default_fs))
        nameservice = default_fs[len('hdfs://'):].split('/')[0]
        return nameservice, self.resolve_hdfs_name_service(nameservice)

    def resolve_hdfs_name_service(self, nameservice):
        """Namenode host:port list for a logical nameservice; a plain host(:port) comes
        back as a single-element list (reference: namenode.py:84-108)."""
        if not nameservice:
            raise HdfsConfigError('Empty nameservice')
        services = self._config.get('dfs.nameservices', '')
        service_names = [s.strip() for s in services.split(',') if s.strip()]
        if nameservice not in service_names:
            # Not a logical service: direct namenode address.
            return [nameservice]
        ha_key = 'dfs.ha.namenodes.{}'.format(nameservice)
        namenode_ids = [s.strip() for s in self._config.get(ha_key, '').split(',')
                        if s.strip()]
        if not namenode_ids:
            raise HdfsConfigError('Nameservice {!r} declared but {} is missing'
                                  .format(nameservice, ha_key))
        if len(namenode_ids) > MAX_NAMENODES:
            logger.warning('Nameservice %r has %d namenodes; only the first %d are used',
                           nameservice, len(namenode_ids), MAX_NAMENODES)
            namenode_ids = namenode_ids[:MAX_NAMENODES]
        addresses = []
        for namenode_id in namenode_ids:
            rpc_key = 'dfs.namenode.rpc-address.{}.{}'.format(nameservice, namenode_id)
            address = self._config.get(rpc_key)
            if not address:
                raise HdfsConfigError('Missing {} for nameservice {!r}'
                                      .format(rpc_key, nameservice))
            addresses.append(address)
        return addresses


class HdfsConnectError(IOError):
    pass


class HdfsConnector(object):
    """Failover connector: try each namenode in order, retrying each (reference:
    namenode.py:123-316)."""

    MAX_ATTEMPTS_PER_NAMENODE = 2

    @classmethod
    def hdfs_connect_namenode(cls, address, user=None):
        """Connect one namenode via pyarrow HadoopFileSystem; override in tests."""
        import pyarrow.fs as pafs
        host, _, port = address.partition(':')
        return pafs.HadoopFileSystem(host, int(port) if port else 8020, user=user)

    @classmethod
    def connect_to_either_namenode(cls, namenode_addresses, user=None):
        """Return the first filesystem that connects; raise HdfsConnectError when every
        namenode fails (reference failover loop)."""
        errors = []
        for address in namenode_addresses:
            for attempt in range(cls.MAX_ATTEMPTS_PER_NAMENODE):
                try:
                    return cls.hdfs_connect_namenode(address, user=user)
                except Exception as exc:  # noqa: BLE001 - collect and fail over
                    errors.append('{} (attempt {}): {}'.format(address, attempt + 1, exc))
                    logger.debug('Namenode connect failed: %s', errors[-1])
        raise HdfsConnectError('Could not connect to any namenode of {}:\n{}'
                               .format(list(namenode_addresses), '\n'.join(errors)))


def namenode_failover(func):
    """Decorator retrying an HDFS operation once after a connection failure (reference:
    petastorm's namenode_failover decorator). If the bound object exposes
    ``reconnect()``, it is invoked between attempts so the retry actually targets the
    standby namenode; otherwise this only covers transient errors on the existing
    connection."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        try:
            return func(*args, **kwargs)
        except OSError:
            reconnect = getattr(args[0], 'reconnect', None) if args else None
            if callable(reconnect):
                logger.warning('HDFS operation %s failed; reconnecting and retrying',
                               func.__name__)
                reconnect()
            else:
                logger.warning('HDFS operation %s failed; retrying once on the same '
                               'connection', func.__name__)
            return func(*args, **kwargs)

    return wrapper
