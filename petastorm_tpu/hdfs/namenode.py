"""Hadoop-config-driven namenode resolution with HA failover (reference:
petastorm/hdfs/namenode.py:31-316).

``HdfsNamenodeResolver`` parses ``hdfs-site.xml``/``core-site.xml`` found via
``HADOOP_HOME``/``HADOOP_PREFIX``/``HADOOP_INSTALL`` (or an injected configuration dict)
and resolves HA nameservice logical names into concrete namenode URLs.
``HdfsConnector.connect_to_either_namenode`` tries each namenode in order with retries —
the reference's failover contract — over ``pyarrow.fs.HadoopFileSystem``.
"""

import logging
import os
import xml.etree.ElementTree as ET

logger = logging.getLogger(__name__)

_HADOOP_HOME_VARS = ('HADOOP_HOME', 'HADOOP_PREFIX', 'HADOOP_INSTALL')
MAX_NAMENODES = 2

# OSError subclasses that describe the *file*, not the connection — a failover retry
# cannot fix these and must not tear down a healthy namenode connection.
_NON_FAILOVER_ERRORS = (FileNotFoundError, PermissionError, FileExistsError,
                        IsADirectoryError, NotADirectoryError)


def _is_failover_error(exc):
    return isinstance(exc, OSError) and not isinstance(exc, _NON_FAILOVER_ERRORS)


class HdfsConfigError(RuntimeError):
    pass


def _load_hadoop_configuration():
    """Locate and parse hdfs-site.xml + core-site.xml into one {name: value} dict
    (reference: namenode.py:34-65). ``HADOOP_CONF_DIR`` (pointing directly at the conf
    directory, per hadoop convention) wins over the ``HADOOP_HOME``-style roots."""
    conf_dirs = []
    conf_dir_env = os.environ.get('HADOOP_CONF_DIR')
    if conf_dir_env:
        conf_dirs.append(conf_dir_env)
    for var in _HADOOP_HOME_VARS:
        home = os.environ.get(var)
        if home:
            conf_dirs.append(os.path.join(home, 'etc', 'hadoop'))
    for conf_dir in conf_dirs:
        config = {}
        for file_name in ('core-site.xml', 'hdfs-site.xml'):
            path = os.path.join(conf_dir, file_name)
            if os.path.exists(path):
                config.update(_parse_hadoop_xml(path))
        if config:
            return config
    return {}


def _parse_hadoop_xml(path):
    result = {}
    root = ET.parse(path).getroot()
    for prop in root.findall('property'):
        name = prop.findtext('name')
        value = prop.findtext('value')
        if name is not None and value is not None:
            result[name.strip()] = value.strip()
    return result


class HdfsNamenodeResolver(object):
    """Resolve HA nameservice names to namenode host:port lists (reference:
    namenode.py:31-120). An explicit ``configuration`` dict (name -> value, the flattened
    hadoop conf) overrides the environment lookup — the hook the tests use."""

    def __init__(self, configuration=None):
        self._config = configuration if configuration is not None \
            else _load_hadoop_configuration()

    @property
    def configured(self):
        """True when any hadoop configuration was found/injected. When False, the
        resolver cannot distinguish a logical HA nameservice from a physical host —
        callers should defer to libhdfs's own config instead of guessing."""
        return bool(self._config)

    def resolve_default_hdfs_service(self):
        """Return (nameservice, [namenode urls]) for fs.defaultFS (reference:
        namenode.py:110-120)."""
        default_fs = self._config.get('fs.defaultFS', '')
        if not default_fs.startswith('hdfs://'):
            raise HdfsConfigError('fs.defaultFS is not an HDFS URL: {!r}'
                                  .format(default_fs))
        nameservice = default_fs[len('hdfs://'):].split('/')[0]
        return nameservice, self.resolve_hdfs_name_service(nameservice)

    def resolve_hdfs_name_service(self, nameservice):
        """Namenode host:port list for a logical nameservice; a plain host(:port) comes
        back as a single-element list (reference: namenode.py:84-108)."""
        if not nameservice:
            raise HdfsConfigError('Empty nameservice')
        services = self._config.get('dfs.nameservices', '')
        service_names = [s.strip() for s in services.split(',') if s.strip()]
        if nameservice not in service_names:
            # Not a logical service: direct namenode address.
            return [nameservice]
        ha_key = 'dfs.ha.namenodes.{}'.format(nameservice)
        namenode_ids = [s.strip() for s in self._config.get(ha_key, '').split(',')
                        if s.strip()]
        if not namenode_ids:
            raise HdfsConfigError('Nameservice {!r} declared but {} is missing'
                                  .format(nameservice, ha_key))
        if len(namenode_ids) > MAX_NAMENODES:
            logger.warning('Nameservice %r has %d namenodes; only the first %d are used',
                           nameservice, len(namenode_ids), MAX_NAMENODES)
            namenode_ids = namenode_ids[:MAX_NAMENODES]
        addresses = []
        for namenode_id in namenode_ids:
            rpc_key = 'dfs.namenode.rpc-address.{}.{}'.format(nameservice, namenode_id)
            address = self._config.get(rpc_key)
            if not address:
                raise HdfsConfigError('Missing {} for nameservice {!r}'
                                      .format(rpc_key, nameservice))
            addresses.append(address)
        return addresses


class HdfsConnectError(IOError):
    pass


class HdfsConnector(object):
    """Failover connector: try each namenode in order, retrying each (reference:
    namenode.py:123-316)."""

    MAX_ATTEMPTS_PER_NAMENODE = 2

    @classmethod
    def hdfs_connect_namenode(cls, address, user=None):
        """Connect one namenode via pyarrow HadoopFileSystem; override in tests."""
        import pyarrow.fs as pafs
        host, _, port = address.partition(':')
        return pafs.HadoopFileSystem(host or 'default', int(port) if port else 8020,
                                     user=user)

    @classmethod
    def connect_to_either_namenode(cls, namenode_addresses, user=None):
        """Return the first filesystem that connects; raise HdfsConnectError when every
        namenode fails (reference failover loop)."""
        errors = []
        for address in namenode_addresses:
            for attempt in range(cls.MAX_ATTEMPTS_PER_NAMENODE):
                try:
                    return cls.hdfs_connect_namenode(address, user=user)
                except Exception as exc:  # noqa: BLE001 - collect and fail over
                    errors.append('{} (attempt {}): {}'.format(address, attempt + 1, exc))
                    logger.debug('Namenode connect failed: %s', errors[-1])
        raise HdfsConnectError('Could not connect to any namenode of {}:\n{}'
                               .format(list(namenode_addresses), '\n'.join(errors)))

    @classmethod
    def connect_ha(cls, namenode_addresses, user=None):
        """Return a picklable :class:`HAHdfsClient` proxy that fails over between the
        given namenodes on every operation (reference: namenode.py:274-286)."""
        if not namenode_addresses:
            raise HdfsConnectError('Must supply at least one namenode address')
        return HAHdfsClient(cls, list(namenode_addresses), user=user)

    @classmethod
    def _try_next_namenode(cls, index_of_nn, namenode_addresses, user=None):
        """Round-robin connect starting after ``index_of_nn``; return
        ``(new_index, filesystem)`` (reference: namenode.py:288-316)."""
        count = len(namenode_addresses)
        for step in range(1, count + 1):
            idx = (index_of_nn + step) % count
            address = namenode_addresses[idx]
            try:
                return idx, cls.hdfs_connect_namenode(address, user=user)
            except Exception as exc:  # noqa: BLE001 - expected for standby namenodes
                logger.debug('Namenode %s connect failed during failover: %s',
                             address, exc)
        raise HdfsConnectError('Unable to connect to any namenode of {}'
                               .format(list(namenode_addresses)))


class HAHdfsClient(object):
    """High-availability proxy over a live ``pyarrow.fs.HadoopFileSystem``.

    The reference subclasses the legacy python ``HadoopFileSystem`` and decorates every
    public method with ``namenode_failover`` (reference: namenode.py:211-238). Modern
    ``pyarrow.fs`` filesystems are C++ extension classes that cannot be subclassed that
    way, so this is a delegating proxy instead: attribute access forwards to the live
    connection, callables are wrapped so an ``OSError`` triggers a round-robin reconnect
    to the next namenode and a single retry. Picklable via ``__reduce__`` — workers
    re-resolve their own connection (reference: namenode.py:231-233).

    Pass :meth:`unwrap` to APIs that require a real pyarrow filesystem instance
    (e.g. ``pyarrow.dataset``); the proxy itself covers metadata-style calls made
    through it.
    """

    def __init__(self, connector_cls, namenode_addresses, user=None):
        self._connector_cls = connector_cls
        self._namenode_addresses = list(namenode_addresses)
        self._user = user
        self._index_of_nn = -1
        self._do_connect()

    def __reduce__(self):
        return self.__class__, (self._connector_cls, self._namenode_addresses, self._user)

    def _do_connect(self):
        self._index_of_nn, self._filesystem = self._connector_cls._try_next_namenode(
            self._index_of_nn, self._namenode_addresses, user=self._user)

    def reconnect(self):
        """Advance to the next namenode; used by :func:`namenode_failover` retries."""
        self._do_connect()

    def unwrap(self):
        """The live ``pyarrow.fs.HadoopFileSystem`` (reconnects if never connected)."""
        return self._filesystem

    def __getattr__(self, name):
        if name.startswith('_'):
            raise AttributeError(name)
        attr = getattr(self._filesystem, name)
        if not callable(attr):
            return attr

        def call_with_failover(*args, **kwargs):
            try:
                return attr(*args, **kwargs)
            except OSError as exc:
                if not _is_failover_error(exc):
                    raise
                logger.warning('HDFS %s failed; failing over to next namenode', name)
                self.reconnect()
                return getattr(self._filesystem, name)(*args, **kwargs)

        call_with_failover.__name__ = name
        return call_with_failover


def namenode_failover(func):
    """Decorator retrying an HDFS operation once after a connection failure (reference:
    petastorm's namenode_failover decorator). If the bound object exposes
    ``reconnect()``, it is invoked between attempts so the retry actually targets the
    standby namenode; otherwise this only covers transient errors on the existing
    connection."""
    import functools

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        try:
            return func(*args, **kwargs)
        except OSError as exc:
            if not _is_failover_error(exc):
                raise
            reconnect = getattr(args[0], 'reconnect', None) if args else None
            if callable(reconnect):
                logger.warning('HDFS operation %s failed; reconnecting and retrying',
                               func.__name__)
                reconnect()
            else:
                logger.warning('HDFS operation %s failed; retrying once on the same '
                               'connection', func.__name__)
            return func(*args, **kwargs)

    return wrapper
