"""Filesystem resolution: dataset URL -> (pyarrow filesystem, path) (reference:
petastorm/fs_utils.py:42-239).

The reference dispatches to pyarrow-legacy / libhdfs / fsspec; here everything funnels into
the modern ``pyarrow.fs`` API: local paths map to ``LocalFileSystem``, ``hdfs://`` to
``HadoopFileSystem``, and every other scheme (s3, gs, abfs, ...) to an fsspec filesystem
wrapped with ``PyFileSystem(FSSpecHandler)`` so Arrow's C++ readers can consume it.
"""

import warnings
from urllib.parse import urlparse

import pyarrow.fs as pafs


def normalize_dataset_url(url):
    """Strip trailing slashes; accept plain paths (reference: petastorm/reader.py:53-59)."""
    if not isinstance(url, str):
        raise ValueError('dataset URL must be a string, got {!r}'.format(url))
    return url.rstrip('/') if url != '/' else url


def normalize_dataset_url_or_urls(url_or_urls):
    """Normalize a URL or a non-empty list of URLs (reference: petastorm/reader.py:53-59)."""
    if isinstance(url_or_urls, (list, tuple)):
        if not url_or_urls:
            raise ValueError('dataset URL list must not be empty')
        return [normalize_dataset_url(url) for url in url_or_urls]
    return normalize_dataset_url(url_or_urls)


def _scheme_of(url):
    scheme = urlparse(url).scheme
    # Windows drive letters / plain paths have empty or 1-char schemes.
    return scheme if len(scheme) > 1 else ''


def _extract_path(url):
    """Filesystem-local path for a URL, independent of how the filesystem object was
    obtained: local paths stay as-is, hdfs drops the authority, object stores keep
    ``bucket/key``."""
    parsed = urlparse(url)
    scheme = _scheme_of(url)
    if scheme == '':
        return url
    if scheme == 'file':
        return parsed.path
    if scheme == 'hdfs':
        return parsed.path
    return parsed.netloc + parsed.path


def check_hdfs_driver(hdfs_driver):
    """Validate the reference-parity ``hdfs_driver`` kwarg (reference threads a
    libhdfs/libhdfs3 choice through every API, petastorm/reader.py:126-127). Modern
    ``pyarrow.fs`` ships only the JVM libhdfs driver — requesting the retired C++
    libhdfs3 is accepted for API compatibility but warns and uses libhdfs."""
    if hdfs_driver not in ('libhdfs', 'libhdfs3'):
        raise ValueError("hdfs_driver must be 'libhdfs' or 'libhdfs3', got {!r}"
                         .format(hdfs_driver))
    if hdfs_driver == 'libhdfs3':
        warnings.warn("hdfs_driver='libhdfs3' is accepted for petastorm API "
                      "compatibility, but pyarrow.fs only provides the JVM libhdfs "
                      "driver — connections will use libhdfs")


def _resolve_filesystem(url, storage_options=None):
    scheme = _scheme_of(url)
    if scheme in ('', 'file'):
        return pafs.LocalFileSystem()
    if scheme == 'hdfs':
        return _resolve_hdfs(url)
    # Everything else goes through fsspec (s3/gs/abfs/...), matching the reference's
    # catch-all branch (fs_utils.py:132-144).
    import fsspec
    fs = fsspec.filesystem(scheme, **(storage_options or {}))
    return pafs.PyFileSystem(pafs.FSSpecHandler(fs))


def _resolve_hdfs(url):
    """Connect an ``hdfs://`` URL, routing hostless and HA-nameservice authorities
    through the hadoop-config namenode resolver with failover (reference:
    petastorm/fs_utils.py:82-130; hdfs/namenode.py:84-120).

    - ``hdfs:///path``: resolve ``fs.defaultFS`` from the hadoop config.
    - ``hdfs://nameservice/path`` where the authority matches a configured
      ``dfs.nameservices`` entry: resolve to its namenode list.
    - ``hdfs://host:port/path``: direct connection; a portless host is still checked
      against the configured nameservices first, as a bare port is what distinguishes a
      physical namenode from a logical service name.
    Multi-namenode resolutions connect via ``HdfsConnector.connect_to_either_namenode``.
    """
    from petastorm_tpu.hdfs.namenode import (
        HdfsConfigError, HdfsConnector, HdfsNamenodeResolver)
    parsed = urlparse(url)
    if parsed.port:
        return pafs.HadoopFileSystem(parsed.hostname, parsed.port)
    resolver = HdfsNamenodeResolver()
    if parsed.hostname and not resolver.configured:
        # No hadoop config found by us at all: the authority may be a logical HA
        # nameservice only libhdfs's own core-site.xml can resolve, so hand it over
        # with port 0 rather than direct-connecting to <authority>:8020.
        return pafs.HadoopFileSystem(parsed.hostname, 0)
    try:
        if not parsed.hostname:
            _, namenodes = resolver.resolve_default_hdfs_service()
        else:
            namenodes = resolver.resolve_hdfs_name_service(parsed.hostname)
    except HdfsConfigError:
        # Config exists but cannot resolve this URL (e.g. fs.defaultFS missing or
        # non-HDFS): defer to libhdfs's own lookup as the last resort.
        return pafs.HadoopFileSystem(parsed.hostname or 'default', parsed.port or 0)
    if len(namenodes) > 1:
        # HA nameservice: return the failover proxy so metadata operations made
        # through this object retry on the standby mid-job. Arrow C++ consumers
        # unwrap it via as_arrow_filesystem().
        return HdfsConnector.connect_ha(namenodes)
    return HdfsConnector.connect_to_either_namenode(namenodes)


def _resolve_single(url, storage_options=None, filesystem=None):
    if filesystem is None:
        filesystem = _resolve_filesystem(url, storage_options)
    return filesystem, _extract_path(url)


def get_filesystem_and_path_or_paths(url_or_urls, storage_options=None, filesystem=None):
    """Resolve a URL (or homogeneous list of URLs) into a single pyarrow filesystem and
    path(s) (reference: petastorm/fs_utils.py:180-219)."""
    urls = url_or_urls if isinstance(url_or_urls, (list, tuple)) else [url_or_urls]
    urls = [normalize_dataset_url(u) for u in urls]
    schemes = {_scheme_of(u) for u in urls}
    netlocs = {urlparse(u).netloc for u in urls}
    if len(schemes) > 1 or len(netlocs) > 1:
        # Name the first offender: with dozens of shard URLs, "schemes {...}"
        # alone sends the user diffing the whole list by hand.
        first_key = (_scheme_of(urls[0]), urlparse(urls[0]).netloc)
        mismatched = next(
            u for u in urls[1:] if (_scheme_of(u), urlparse(u).netloc) != first_key)
        raise ValueError('All dataset URLs must share one filesystem; got schemes {} '
                         'netlocs {}; first mismatch: {!r} does not match {!r}'
                         .format(sorted(schemes), sorted(netlocs), mismatched,
                                 urls[0]))
    if filesystem is None:
        filesystem = _resolve_filesystem(urls[0], storage_options)
    paths = [_extract_path(u) for u in urls]
    if isinstance(url_or_urls, (list, tuple)):
        return filesystem, paths
    return filesystem, paths[0]


def as_arrow_filesystem(filesystem):
    """The real pyarrow filesystem behind ``filesystem`` — unwraps failover proxies
    (``HAHdfsClient``) for APIs that require a C++ ``pyarrow.fs.FileSystem`` instance
    (``pyarrow.dataset`` etc.). Plain filesystems pass through."""
    unwrap = getattr(filesystem, 'unwrap', None)
    return unwrap() if callable(unwrap) else filesystem


def path_exists(filesystem, path):
    """True when the path exists on the filesystem (reference: fs_utils.py:222-230)."""
    info = filesystem.get_file_info(path)
    return info.type != pafs.FileType.NotFound


def delete_path(filesystem, path, recursive=True):
    """Delete a file or directory tree (reference: fs_utils.py:233-239)."""
    info = filesystem.get_file_info(path)
    if info.type == pafs.FileType.Directory:
        filesystem.delete_dir(path) if recursive else filesystem.delete_dir_contents(path)
    elif info.type != pafs.FileType.NotFound:
        filesystem.delete_file(path)


class FilesystemFactory(object):
    """A picklable zero-arg callable re-creating the filesystem — for shipping to worker
    processes (reference: fs_utils.py:166-172).

    With a ``retry_policy`` (:class:`~petastorm_tpu.resilience.RetryPolicy`), transient
    resolution failures — DNS blips, throttled object-store auth, namenode failover
    races — are retried with deterministic backoff before surfacing: workers re-invoke
    this factory whenever they (re)connect, including after a mid-read retry dropped a
    broken connection, so the connect path needs the same resilience as the read path
    (docs/robustness.md)."""

    def __init__(self, url, storage_options=None, retry_policy=None):
        self._url = url
        self._storage_options = storage_options
        self._retry_policy = retry_policy

    def __call__(self):
        # Workers hand this filesystem straight into Arrow C++ (make_fragment) — a
        # python HA proxy is not accepted there, so unwrap. Connect-time namenode
        # failover still applies on each worker's fresh connection.
        def resolve():
            return as_arrow_filesystem(
                _resolve_single(self._url, self._storage_options)[0])
        if self._retry_policy is None:
            return resolve()
        from petastorm_tpu.resilience import run_with_retry
        filesystem, _ = run_with_retry(resolve, self._retry_policy)
        return filesystem


def make_filesystem_factory(url, storage_options=None, retry_policy=None):
    """Picklable zero-arg factory resolving ``url``'s filesystem — what worker
    processes ship instead of a live (unpicklable) filesystem object. ``retry_policy``
    makes the resolution itself retry transient failures."""
    return FilesystemFactory(url, storage_options, retry_policy=retry_policy)
