"""InMemJaxLoader: load a dataset once, then serve seeded epoch batches with no further
host IO — the TPU-native counterpart of the reference's ``InMemBatchedDataLoader``
(petastorm/pytorch.py:368-496: fill ≤ rows_capacity rows once, stop the reader, then
epochs of seeded ``torch.randperm`` batch sampling).

TPU-first design: on a single device (``mesh=None``) the whole dataset lives in HBM and
every batch is produced by one jitted gather — per-epoch permutations are computed with
``jax.random`` on device, so after the fill phase the input pipeline touches the host
zero times (input stall is structurally 0). With a ``mesh``, python iteration keeps the
dataset in host RAM and assembles each sampled batch into a mesh-sharded ``jax.Array``
like :class:`JaxDataLoader` (a GLOBAL per-batch permutation over HBM-resident shards
would force cross-shard gathers); ``scan_epochs`` over a mesh instead uploads the
dataset shard-blocked across device HBM and shuffles SHARD-LOCALLY, which keeps the
gathers collective-free — whole-epoch compilation composed with data parallelism.
"""

import logging
import time
import warnings

import numpy as np

from petastorm_tpu.parallel.loader import (FieldShardings, iter_reader_chunks,
                                           reader_may_be_infinite, resolve_sharding,
                                           sanitize_columns, sharding_for_field)

logger = logging.getLogger(__name__)

_FILL_SAFETY_CAP = 100_000_000
#: scan_epochs keeps this many compiled (step_fn, shuffle) programs before evicting
_SCAN_CACHE_MAX = 8


def _put_with_log(put_fn, upload_bytes, detail):
    """Run an upload and, when INFO logging is enabled, log its TRUE duration
    gated on :func:`petastorm_tpu.utils.value_readback_gate` (the project-wide
    honest-timing convention — ``block_until_ready`` lies through the device
    tunnel, and a transfer log that under-reports on exactly the slow link it
    exists to diagnose would be worse than none). With INFO disabled the
    upload stays fully async: no sync is paid for a discarded measurement."""
    want_log = logger.isEnabledFor(logging.INFO)
    t0 = time.perf_counter()
    data = put_fn()
    if want_log:
        from petastorm_tpu.utils import value_readback_gate
        value_readback_gate(data)
        logger.info('uploaded %s (%.1f MB) in %.2fs', detail,
                    upload_bytes / 2**20, time.perf_counter() - t0)
    return data


class InMemJaxLoader(object):
    """Fill once from ``reader``, then iterate seeded shuffled batches for
    ``num_epochs`` (None = infinite).

    :param reader: petastorm_tpu Reader (row, batched, or NGram). NGram readers fill
        window-major: every "row" in memory is one window, each field
        ``(length, *field_shape)``, so batches are ``(batch, length, ...)`` sequence
        arrays (note overlapping windows are materialized — budget
        ``rows_capacity x length`` memory).
    :param batch_size: rows per batch on this host.
    :param num_epochs: epochs to serve from memory (None = infinite). Independent of the
        reader's own ``num_epochs``, which only governs the fill (use reader
        num_epochs=1).
    :param rows_capacity: stop filling after this many rows (required if the reader is
        infinite). The reader is stopped after the fill, mirroring the reference's
        deadlock avoidance (pytorch.py:420-424).
    :param shuffle: seeded reshuffle every epoch (default True).
    :param seed: base seed; epoch ``e`` uses fold-in of ``e``.
    :param mesh/partition_spec: as in :class:`JaxDataLoader`.
    :param pad_ragged: as in :class:`JaxDataLoader`.
    :param drop_last: drop the final partial batch (static shapes under jit).
    :param device_put: False keeps batches as host numpy (debugging).
    """

    def __init__(self, reader, batch_size, num_epochs=1, rows_capacity=None,
                 shuffle=True, seed=0, mesh=None, partition_spec=None, pad_ragged=None,
                 drop_last=True, device_put=True):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
        if num_epochs is not None and num_epochs < 1:
            raise ValueError('num_epochs must be >= 1 or None')
        if partition_spec is not None and mesh is None:
            raise ValueError('partition_spec requires a mesh')
        if getattr(reader, 'device_decode_fields', None):
            raise ValueError(
                'InMemJaxLoader does not support device_decode_fields (the '
                'fill materializes DECODED host columns); use JaxDataLoader '
                'for the device-resident decode tail, or drop the knob — '
                'docs/performance.md "Device-resident decode tail"')
        self.batch_size = batch_size
        self.num_epochs = num_epochs
        self._shuffle = shuffle
        self._seed = seed
        self._mesh = mesh
        self._partition_spec = partition_spec
        self._pad_ragged = dict(pad_ragged or {})
        self._drop_last = drop_last
        self._device_put = device_put
        self._columns = self._fill(reader, rows_capacity)
        self._num_rows = next(iter(self._columns.values())).shape[0] if self._columns else 0
        if self._num_rows < batch_size and drop_last:
            raise ValueError('Loaded {} rows < batch_size {} with drop_last=True — '
                             'every epoch would be empty'.format(self._num_rows, batch_size))
        self._data = None  # device-resident dataset (single-device path), built lazily
        self._sharded_meta = None  # (usable_rows, num_shards) for the mesh scan path
        self._take = None
        # scan_epochs: compiled-program cache keyed by (step_fn, shuffle) — train and
        # eval variants of the same step stay compiled side by side — plus a persistent
        # epoch cursor so repeated calls keep advancing the permutation sequence
        # instead of replaying epoch 0.
        self._scan_cache = {}
        self._scan_compile_count = 0
        self._scan_cache_warned = False
        self._scan_epoch = 0

    # ------------------------------------------------------------------ fill

    def _fill(self, reader, rows_capacity):
        if rows_capacity is None and reader_may_be_infinite(reader):
            raise ValueError(
                'rows_capacity is required with a (possibly) infinite reader: '
                'num_epochs=None, a wrapper over one, or a custom reader that does not '
                'advertise finiteness. Pass rows_capacity, or give a custom reader a '
                'num_epochs attribute (any non-None value marks it finite).')
        cap = rows_capacity if rows_capacity is not None else _FILL_SAFETY_CAP
        chunks = []
        rows = 0
        try:
            for columns, n, _ in iter_reader_chunks(reader):
                chunks.append(sanitize_columns(columns, self._pad_ragged,
                                               self._device_put))
                rows += n
                if rows >= cap:
                    if rows_capacity is None:
                        warnings.warn(
                            'InMemJaxLoader fill hit the {}-row safety cap without an '
                            'explicit rows_capacity; the dataset is TRUNCATED. Pass '
                            'rows_capacity to make the limit intentional.'
                            .format(_FILL_SAFETY_CAP))
                    break
        finally:
            # Stop regardless: an infinite reader would otherwise keep workers running
            # (reference: pytorch.py:420-424).
            reader.stop()
            reader.join()
        if not chunks:
            return {}
        columns = {name: _concat([c[name] for c in chunks])
                   for name in chunks[0]}
        if rows_capacity is not None:
            columns = {name: col[:rows_capacity] for name, col in columns.items()}
        return columns

    # ------------------------------------------------------------------ iteration

    def __len__(self):
        """Batches per epoch."""
        if self._drop_last:
            return self._num_rows // self.batch_size
        return -(-self._num_rows // self.batch_size)

    @property
    def num_rows(self):
        return self._num_rows

    def __iter__(self):
        if self._num_rows == 0:
            return
        epoch = 0
        while self.num_epochs is None or epoch < self.num_epochs:
            if self._device_put and self._mesh is None:
                yield from self._iter_epoch_on_device(epoch)
            else:
                yield from self._iter_epoch_host(epoch)
            epoch += 1

    # -- single-device: dataset in HBM, jitted gather, device-side permutation --------

    def _ensure_device_data(self):
        import jax
        if self._data is None:
            columns = self._columns
            self._data = _put_with_log(
                lambda: jax.device_put(columns),
                sum(col.nbytes for col in columns.values()),
                '{} rows'.format(self._num_rows))
            # The on-device path never reads the host copy again; holding it would
            # double the dataset's memory footprint.
            self._columns = None

            @jax.jit
            def take(data, idx):
                return {name: col[idx] for name, col in data.items()}

            self._take = take
        return self._data

    def _iter_epoch_on_device(self, epoch):
        import jax
        import jax.numpy as jnp

        from petastorm_tpu.ops.index_shuffle import random_index_shuffle
        data = self._ensure_device_data()
        n = self._num_rows
        if self._shuffle:
            # Materialization-free-in-spirit permutation: jax.random.permutation is a
            # SORT (~50ms at n=50k on a v5e — can rival a small model's whole epoch);
            # the Feistel index cipher evaluates the epoch's index vector in <1ms
            # (ops/index_shuffle.py), once per epoch.
            key = jax.random.fold_in(jax.random.PRNGKey(self._seed), epoch)
            idx_all = random_index_shuffle(jnp.arange(n), key, n)
        else:
            idx_all = jnp.arange(n)
        limit = n - self.batch_size + 1 if self._drop_last else n
        for start in range(0, limit, self.batch_size):
            yield self._take(data, idx_all[start:min(start + self.batch_size, n)])

    # -- mesh-sharded HBM residency for scan_epochs -----------------------------------

    def _batch_axis_name(self):
        """The mesh axis sharding the batch dimension. scan_epochs over a mesh
        supports the default batch-axis layout (first mesh axis) or a single-axis
        ``PartitionSpec``; per-field dict specs have no single batch layout to scan
        over and are rejected."""
        if self._partition_spec is None:
            return self._mesh.axis_names[0]
        try:
            (axis,) = tuple(self._partition_spec)
        except (TypeError, ValueError):
            axis = None
        if isinstance(axis, str) and axis in self._mesh.axis_names:
            return axis
        raise ValueError(
            'scan_epochs over a mesh supports partition_spec=None or a single-axis '
            'PartitionSpec(axis); got {!r}'.format(self._partition_spec))

    def _ensure_sharded_data(self):
        """Upload the dataset shard-blocked: each column reshaped to
        ``(num_shards, rows_per_shard, ...)`` and sharded on dim 0 over the batch
        axis, so every device holds one contiguous row block in its own HBM. Rows
        beyond ``num_shards * rows_per_shard`` are dropped (at most num_shards - 1).

        Returns ``(data, usable_rows, num_shards)``."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        if self._data is None:
            axis = self._batch_axis_name()
            num_shards = self._mesh.shape[axis]
            rows_per_shard = self._num_rows // num_shards
            if rows_per_shard == 0:
                raise ValueError('{} rows cannot be sharded {} ways'
                                 .format(self._num_rows, num_shards))
            usable = num_shards * rows_per_shard
            if usable < self._num_rows:
                warnings.warn('scan_epochs drops {} trailing rows so the dataset '
                              'splits evenly over the {} batch-axis shards'
                              .format(self._num_rows - usable, num_shards))
            sharding = NamedSharding(self._mesh, PartitionSpec(axis))
            blocks = {
                name: col[:usable].reshape(
                    (num_shards, rows_per_shard) + col.shape[1:])
                for name, col in self._columns.items()}
            self._data = _put_with_log(
                lambda: {name: jax.device_put(col, sharding)
                         for name, col in blocks.items()},
                # bytes of what is ACTUALLY uploaded (trailing remainder dropped)
                sum(col.nbytes for col in blocks.values()),
                '{} rows shard-blocked over {} devices'.format(
                    usable, num_shards))
            self._sharded_meta = (usable, num_shards)
            self._columns = None  # single copy: the host arrays are no longer read
        return self._data, self._sharded_meta[0], self._sharded_meta[1]

    def _build_sharded_epoch_program(self, step_fn, shuffle, seed, n, num_shards,
                                     batch_size, batches_per_epoch, index_shuffle):
        """One compiled epoch over the mesh with SHARD-LOCAL shuffling: each shard
        permutes its own rows (Feistel cipher keyed by epoch x shard), each global
        batch takes ``batch_size / num_shards`` rows from every shard, and the gather
        is a vmapped per-shard take whose batch dim is aligned-sharded on operand,
        indices, and output — XLA partitions it with NO collectives in the input
        path. Rows never migrate between shards (the same contract as sharded
        multi-host reading, reference reader.py:570-594: a shard only ever serves its
        own rows); cross-shard mixing comes from the once-at-fill row distribution.
        ``step_fn`` itself runs under plain GSPMD on the reassembled
        ``(batch_size, ...)`` batch (sharded over the batch axis), so model-side
        sharding (TP/FSDP/etc.) composes unchanged."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec
        axis = self._batch_axis_name()
        local_bs = batch_size // num_shards
        rows_per_shard = n // num_shards
        idx_sharding = NamedSharding(self._mesh, PartitionSpec(axis))

        @jax.jit
        def one_epoch(data, carry, epoch_index):
            epoch_key = jax.random.fold_in(jax.random.PRNGKey(seed), epoch_index)
            shard_keys = jax.vmap(lambda s: jax.random.fold_in(epoch_key, s))(
                jnp.arange(num_shards))
            local = jnp.arange(rows_per_shard)
            if shuffle:
                idx_all = jax.vmap(
                    lambda key: index_shuffle(local, key, rows_per_shard))(shard_keys)
            else:
                idx_all = jnp.broadcast_to(local, (num_shards, rows_per_shard))
            # Pin the per-shard index table to the batch axis so the vmapped gather
            # below partitions shard-locally instead of replicating via all-gather.
            idx_all = jax.lax.with_sharding_constraint(idx_all, idx_sharding)

            def body(carry, batch_index):
                idx = jax.lax.dynamic_slice_in_dim(
                    idx_all, batch_index * local_bs, local_bs, axis=1)
                batch = {}
                for name, col in data.items():
                    taken = jax.vmap(lambda c, i: c[i])(col, idx)
                    batch[name] = taken.reshape((batch_size,) + taken.shape[2:])
                return step_fn(carry, batch)

            return jax.lax.scan(body, carry, jnp.arange(batches_per_epoch))

        return one_epoch

    # -- fully-compiled epochs: sampling + training in ONE XLA program ----------------

    def scan_epochs(self, step_fn, carry, num_epochs=1, epoch_offset=None,
                    shuffle=None):
        """Run whole training epochs on device, each as a single compiled program.

        The idiomatic-TPU endpoint of the in-mem design: the per-epoch permutation
        (``jax.random``), the batch gather, and every training step run inside one
        ``lax.scan`` under ``jit`` — one host dispatch per epoch, so input machinery
        adds no per-batch Python overhead at all (at small batch sizes the dispatch
        costs several times the compute; see bench.py). No reference analog: petastorm's
        InMemBatchedDataLoader still crosses into Python per batch
        (petastorm/pytorch.py:464-489).

        Repeated calls with the *same* ``step_fn`` object reuse the compiled program
        and continue the epoch/permutation sequence where the previous call stopped
        (override the start with ``epoch_offset``).

        With a ``mesh``, the dataset is uploaded shard-blocked (each device holds a
        contiguous row block in its own HBM) and shuffling is SHARD-LOCAL: each
        shard permutes its own rows per epoch, every global batch takes
        ``batch_size / num_shards`` rows from each shard, and the gather partitions
        with no collectives in the input path. ``batch_size`` must be divisible by
        the batch mesh axis size; a ``partition_spec`` must be None or a single-axis
        ``PartitionSpec``.

        :param step_fn: ``step_fn(carry, batch) -> (carry, aux)`` with ``batch`` a dict
            of ``(batch_size, ...)`` arrays — a standard ``lax.scan`` body over your
            train step.
        :param carry: initial carry (e.g. ``(params, opt_state)``).
        :param num_epochs: epochs to run; the compiled program is reused across them.
        :param epoch_offset: epoch index of the first epoch (feeds the permutation
            seed fold-in); default continues the loader's internal cursor.
        :param shuffle: override the loader's shuffle setting for this call (e.g.
            ``False`` for deterministic eval epochs over the same resident data).
        :return: ``(carry, aux_per_epoch)`` where ``aux_per_epoch`` is a list of the
            stacked per-batch aux pytrees, one entry per epoch.
        """
        import jax
        import jax.numpy as jnp
        if not self._device_put:
            raise ValueError('scan_epochs requires device_put=True')
        if self._num_rows == 0:
            raise ValueError('scan_epochs on an empty dataset')
        batch_size = self.batch_size
        shuffle = self._shuffle if shuffle is None else shuffle
        seed = self._seed
        # Validate BEFORE any upload: _ensure_*_data drops the host copy, so failing
        # after it would leave the loader unusable (batch_size is fixed at __init__).
        if self._mesh is not None:
            num_shards = self._mesh.shape[self._batch_axis_name()]
            if batch_size % num_shards:
                raise ValueError(
                    'scan_epochs over a mesh needs batch_size ({}) divisible by the '
                    'batch mesh axis size ({})'.format(batch_size, num_shards))
            n = num_shards * (self._num_rows // num_shards)
        else:
            n, num_shards = self._num_rows, 1
        if n // batch_size == 0:
            raise ValueError('batch_size {} > usable dataset rows {}'
                             .format(batch_size, n))
        if not self._drop_last and self._num_rows % batch_size != 0:
            raise ValueError(
                'scan_epochs cannot serve the trailing partial batch ({} rows): '
                'lax.scan needs static batch shapes. Use drop_last=True, a divisible '
                'batch_size, or the python iterator.'.format(self._num_rows % batch_size))
        if self._mesh is not None:
            data, n, num_shards = self._ensure_sharded_data()
        else:
            data = self._ensure_device_data()
        batches_per_epoch = n // batch_size

        cache_key = (step_fn, shuffle)
        if cache_key not in self._scan_cache:
            from petastorm_tpu.ops.index_shuffle import random_index_shuffle

            if self._mesh is not None:
                one_epoch = self._build_sharded_epoch_program(
                    step_fn, shuffle, seed, n, num_shards, batch_size,
                    batches_per_epoch, random_index_shuffle)
            else:
                @jax.jit
                def one_epoch(data, carry, epoch_index):
                    # Shuffling via the Feistel index cipher, not
                    # jax.random.permutation: the sort-based permutation costs ~50ms
                    # at n=50k on a v5e while the cipher evaluates the whole epoch's
                    # indices in <1ms (ops/index_shuffle.py). Evaluated ONCE per epoch
                    # here — hoisting the cipher's cycle-walk while_loop out of the
                    # batch scan keeps the loop body free of data-dependent control
                    # flow.
                    key = jax.random.fold_in(jax.random.PRNGKey(seed), epoch_index)
                    idx_all = (random_index_shuffle(jnp.arange(n), key, n) if shuffle
                               else jnp.arange(n))

                    def body(carry, batch_index):
                        idx = jax.lax.dynamic_slice_in_dim(
                            idx_all, batch_index * batch_size, batch_size)
                        batch = {name: col[idx] for name, col in data.items()}
                        return step_fn(carry, batch)

                    return jax.lax.scan(body, carry, jnp.arange(batches_per_epoch))

            self._scan_compile_count += 1
            if len(self._scan_cache) >= _SCAN_CACHE_MAX:
                # A fresh lambda per call defeats reuse (closures cannot be safely
                # deduplicated) — warn once and evict oldest so the compiled
                # executables and their captured environments cannot accumulate.
                if not self._scan_cache_warned:
                    self._scan_cache_warned = True
                    warnings.warn(
                        'scan_epochs compiled {} distinct (step_fn, shuffle) programs; '
                        'pass a stable step_fn object to reuse compilations'
                        .format(self._scan_compile_count))
                self._scan_cache.pop(next(iter(self._scan_cache)))
            self._scan_cache[cache_key] = one_epoch
        one_epoch = self._scan_cache[cache_key]

        start = self._scan_epoch if epoch_offset is None else epoch_offset
        aux_per_epoch = []
        for epoch in range(start, start + num_epochs):
            carry, aux = one_epoch(data, carry, epoch)
            aux_per_epoch.append(aux)
        if epoch_offset is None:
            # Explicit offsets (replay/eval at a pinned epoch) must not clobber the
            # training cursor, or the next default call would reuse permutations.
            self._scan_epoch = start + num_epochs
        return carry, aux_per_epoch

    # -- mesh / host path: numpy sampling + per-batch sharded assembly ----------------

    def _iter_epoch_host(self, epoch):
        if self._columns is None:
            raise RuntimeError(
                'Python iteration is unavailable after scan_epochs moved the dataset '
                'to device HBM (the host copy is dropped to avoid double residency); '
                'keep using scan_epochs, or build a separate loader for iteration')
        if self._shuffle:
            perm = np.random.RandomState((self._seed + epoch) % (2 ** 31)).permutation(
                self._num_rows)
        else:
            perm = np.arange(self._num_rows)
        sharding = resolve_sharding(self._mesh, self._partition_spec, self._device_put)
        if isinstance(sharding, FieldShardings):
            sharding.check_unused(self._columns.keys())
        limit = (self._num_rows - self.batch_size + 1 if self._drop_last
                 else self._num_rows)
        for start in range(0, limit, self.batch_size):
            idx = perm[start:start + self.batch_size]
            batch = {name: np.ascontiguousarray(col[idx])
                     for name, col in self._columns.items()}
            if self._device_put:
                # __iter__ routes here with device_put only when a mesh is present
                # (single-device device_put takes the HBM-resident path).
                import jax
                batch = {name: jax.make_array_from_process_local_data(
                             sharding_for_field(sharding, name), col)
                         for name, col in batch.items()}
            yield batch

    # ------------------------------------------------------------------ lifecycle

    def stop(self):
        pass

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        pass


def _concat(parts):
    if len(parts) == 1:
        return np.ascontiguousarray(parts[0])
    return np.concatenate(parts, axis=0)
