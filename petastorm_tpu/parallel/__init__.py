"""JAX device layer: mesh construction, sharded batch assembly, double-buffered loaders,
and multi-host shard discovery (the TPU-native replacement for the reference's
pytorch/tf adapter layer + Horovod rank sniffing; SURVEY.md §7.1 item 5)."""

from petastorm_tpu.parallel.device_stage import DeviceTransform  # noqa: F401
from petastorm_tpu.parallel.inmem_loader import InMemJaxLoader  # noqa: F401
from petastorm_tpu.parallel.loader import JaxDataLoader, make_jax_loader  # noqa: F401

#: elastic pod-scale sharding surface (parallel/topology.py) — lazy like
#: TrainingCheckpointer so importing the package stays cheap
_TOPOLOGY_EXPORTS = ('TopologyPolicy', 'resolve_topology_policy',
                     'deal_assignment', 'compose_global_digest',
                     'merge_topology_states', 'policy_from_state',
                     'replay_topology_journal')


def __getattr__(name):  # lazy: orbax import is heavy and optional at runtime
    if name == 'TrainingCheckpointer':
        from petastorm_tpu.parallel.checkpoint import TrainingCheckpointer
        return TrainingCheckpointer
    if name in _TOPOLOGY_EXPORTS:
        from petastorm_tpu.parallel import topology
        return getattr(topology, name)
    raise AttributeError(name)

from petastorm_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding, distributed_shard_info, make_mesh)
from petastorm_tpu.parallel.pipeline import (  # noqa: F401
    make_pipeline, microbatch, stack_stage_params, stage_partition_specs,
    unstack_stage_params)
