"""Device-resident decode tail: the loader-side stage that turns raw-shipped
codec payloads into decoded (and optionally augmented) device batches.

Counterpart of ``make_reader(device_decode_fields=...)`` (docs/performance.md
"Device-resident decode tail"): workers pass codec payloads through undecoded
(``decode_engine`` ship-raw kernels) and this stage finishes the job next to
the chip — DCT coefficient blocks run through
:func:`~petastorm_tpu.ops.image_decode.dct_decode_images_jax` (dequant + IDCT
on the MXU), packed ``.npy`` payloads become typed arrays via
:func:`~petastorm_tpu.ops.raw_decode.bitcast_rows` (static slice + bitcast XLA
fuses away), and stored-block deflate frames inflate on device through the
:func:`~petastorm_tpu.ops.raw_decode.stored_inflate` Pallas gather-copy.
Huffman-coded deflate frames inflate on the loader's producer thread — still
off the contended worker fleet CPU, and the upload stays the packed payload.

Fallback matrix (every cell byte-identical to the host decode path):

- CPU backend, or ``device_put=False``: every device field decodes on the host
  through the same codec math the worker would have used (host mode). Declared
  ``DeviceTransform`` chains still run (same jitted math, post-upload) so a
  fallback run trains on the same data an accelerator run would.
- ``float64`` payloads under x32: per-field host mode (the bitcast cannot
  express the rounding conversion — same gate as the coalesced upload).
- accelerator backends require fully-concrete, non-nullable field shapes
  (XLA static shapes); anything else is rejected at loader construction with
  the fix named.

A small ring (``device_buffer_depth``) bounds how many decode programs may be
dispatched ahead of the train step — double buffering against device memory,
the ``prefetch_to_device`` analog. The loader reports the stage's time as the
``device_decode`` / ``d2d_wait`` telemetry stages.
"""

from __future__ import annotations

import collections
import logging
import os
import time
import zlib
from dataclasses import dataclass
from io import BytesIO
from typing import (Any, Callable, Deque, Dict, FrozenSet, List, Mapping,
                    Optional, Tuple)

import numpy as np

from petastorm_tpu.decode_engine import (RAW_ENC_DEFLATE, RAW_ENC_NPY,
                                         RAW_ENC_SUFFIX, RAW_HW_SUFFIX,
                                         stack_if_uniform)

logger = logging.getLogger(__name__)

#: loader-private column name carrying the per-batch augment RNG key
_RNG_NAME = '__device_rng'
#: suffix of the loader-private stored-deflate segment-table column
_SEGS_SUFFIX = '__segs'


@dataclass(frozen=True)
class DeviceTransform:
    """Declarative on-device augment chain for one raw-shipped image field,
    applied INSIDE the jitted decode program (so augment cost overlaps the
    train step like the decode itself).

    :param crop: ``(h, w)`` random-crop size (``ops.image.random_crop_flip``);
        None disables cropping.
    :param random_flip: seeded random horizontal flip (requires ``crop`` —
        the two share one kernel).
    :param normalize: ``(mean, std)`` per-channel sequences; the output becomes
        ``normalize_dtype`` via ``ops.image.normalize_image``. None keeps uint8.
    :param normalize_dtype: numpy dtype string of the normalized output
        (default ``'float32'``).
    :param seed: base RNG seed; each batch folds in a running counter so
        augmentation differs per batch but replays deterministically.
    """

    crop: Optional[Tuple[int, int]] = None
    random_flip: bool = False
    normalize: Optional[Tuple[Tuple[float, ...], Tuple[float, ...]]] = None
    normalize_dtype: str = 'float32'
    seed: int = 0

    def __post_init__(self) -> None:
        if self.random_flip and self.crop is None:
            raise ValueError('DeviceTransform(random_flip=True) requires crop= '
                             '(flip rides the crop kernel)')
        # coerce sequences to tuples: the transform is part of the compiled
        # program's cache key, so it must be hashable
        if self.crop is not None:
            object.__setattr__(self, 'crop', tuple(self.crop))
        if self.normalize is not None:
            mean, std = self.normalize
            object.__setattr__(self, 'normalize',
                               (tuple(float(m) for m in mean),
                                tuple(float(s) for s in std)))

    @property
    def needs_rng(self) -> bool:
        """True when the chain consumes per-batch randomness."""
        return self.crop is not None

    def apply(self, images: Any, rng: Optional[Any]) -> Any:
        """Run the chain on a decoded uint8 ``[B, H, W, C]`` batch (jit-traceable)."""
        from petastorm_tpu.ops.image import normalize_image, random_crop_flip
        out = images
        if self.crop is not None:
            squeeze = out.ndim == 3
            if squeeze:
                out = out[..., None]
            out = random_crop_flip(rng, out, self.crop, flip=self.random_flip)
            if squeeze:
                out = out[..., 0]
        if self.normalize is not None:
            import jax.numpy as jnp
            mean, std = self.normalize
            out = normalize_image(out, mean, std,
                                  dtype=jnp.dtype(self.normalize_dtype))
        return out


@dataclass(frozen=True)
class _FieldPlan:
    """Static per-field recipe resolved from the reader's schema at loader
    construction: what raw form arrives and how to finish it."""

    name: str
    kind: str                      # 'dct' | 'npy' | 'deflate'
    dtype_str: str                 # payload dtype (npy/deflate) or 'uint8' (dct)
    shape: Tuple[int, ...]         # decoded per-row shape (may hold None dims)
    quality: int = 75              # dct quantization quality
    transform: Optional[DeviceTransform] = None
    host_only: bool = False        # per-field forced host decode (f8 under x32)

    @property
    def aux_names(self) -> Tuple[str, ...]:
        """Auxiliary columns riding alongside this field's raw payload."""
        if self.kind == 'dct':
            return (self.name + RAW_HW_SUFFIX,)
        if self.kind == 'deflate':
            return (self.name + RAW_ENC_SUFFIX,)
        return ()


def _resolve_plans(reader: Any,
                   transforms: Mapping[str, DeviceTransform]) -> Dict[str, _FieldPlan]:
    """Build the per-field recipes from the reader's ``device_decode_fields``
    and schema; rejects transforms on non-image fields."""
    from petastorm_tpu.codecs import CompressedNdarrayCodec, DctImageCodec
    import jax
    x64 = bool(jax.config.jax_enable_x64)
    plans: Dict[str, _FieldPlan] = {}
    for name in sorted(reader.device_decode_fields):
        field = reader.schema.fields[name]
        codec = field.codec
        dtype = np.dtype(field.numpy_dtype)
        if type(codec) is DctImageCodec:
            kind = 'dct'
        elif type(codec) is CompressedNdarrayCodec:
            kind = 'deflate'
        else:
            kind = 'npy'
        transform = transforms.get(name)
        if transform is not None and kind != 'dct':
            raise ValueError('device_transforms[{!r}]: transforms apply to '
                             'DctImageCodec image fields only (this field '
                             'ships as {})'.format(name, kind))
        host_only = dtype.kind == 'f' and dtype.itemsize == 8 and not x64
        plans[name] = _FieldPlan(
            name=name, kind=kind, dtype_str=dtype.str,
            shape=tuple(field.shape),
            quality=int(getattr(codec, 'quality', 75)),
            transform=transform, host_only=host_only)
    unknown = sorted(set(transforms) - set(plans))
    if unknown:
        raise ValueError('device_transforms name fields not in '
                         'device_decode_fields: {}'.format(unknown))
    return plans


def _inflate_frame(frame: Any, enc: int) -> bytes:
    """One raw frame -> its ``.npy`` member bytes (host mirror of the worker's
    stripped container): raw-deflate streams inflate, stored members pass."""
    if enc == RAW_ENC_DEFLATE:
        return zlib.decompressobj(-15).decompress(memoryview(frame))
    if enc == RAW_ENC_NPY:
        return bytes(memoryview(frame))
    raise ValueError('null cell has no payload (enc={})'.format(enc))


class DeviceDecodeStage:
    """The loader's device pipeline stage (one instance per
    :class:`~petastorm_tpu.parallel.loader.JaxDataLoader` whose reader ships
    raw fields). See the module docstring for the decode/fallback matrix."""

    def __init__(self, reader: Any,
                 transforms: Optional[Mapping[str, DeviceTransform]],
                 depth: int, device_put: bool) -> None:
        import jax
        self._plans = _resolve_plans(reader, dict(transforms or {}))
        self._schema_fields = dict(reader.schema.fields)
        self._depth = max(1, int(depth))
        self._x64 = bool(jax.config.jax_enable_x64)
        platform = jax.devices()[0].platform
        #: host mode: every device field decodes on the host, byte-identically
        #: to a reader without the knob (CPU backends, host-batch loaders).
        #: PETASTORM_TPU_DEVICE_DECODE_FORCE=1 forces the device-kernel path
        #: on a CPU backend — a test/debug hook (kernels run via XLA-CPU /
        #: Pallas interpret; DCT decode then differs from the host mirror by
        #: float rounding, which is why it is never the CPU default).
        force = os.environ.get('PETASTORM_TPU_DEVICE_DECODE_FORCE') == '1'
        self.host_mode = (not device_put) or (platform == 'cpu' and not force)
        self.platform = platform
        self._programs: Dict[Tuple[Any, ...], Any] = {}
        self._transform_program: Optional[Any] = None
        self._ring: Deque[Any] = collections.deque()
        self._rng_counter = 0
        self._needs_rng = any(p.transform is not None and p.transform.needs_rng
                              for p in self._plans.values())
        if not self.host_mode:
            bad = sorted(
                name for name, plan in self._plans.items()
                if not plan.host_only
                and (any(d is None for d in plan.shape)
                     or reader.schema.fields[name].nullable))
            if bad:
                raise ValueError(
                    'device_decode_fields {} have wildcard dims or are '
                    'nullable; on-accelerator decode needs static shapes '
                    '(XLA) — make the field shapes concrete/non-nullable or '
                    'drop the fields from device_decode_fields'.format(bad))
        if transforms and self.host_mode and not device_put:
            raise ValueError('device_transforms need device batches; '
                             'construct the loader with device_put=True')

    # ------------------------------------------------------------- surfaces

    @property
    def field_names(self) -> FrozenSet[str]:
        """The raw-shipped field names this stage finishes."""
        return frozenset(self._plans)

    @property
    def passthrough_names(self) -> FrozenSet[str]:
        """Columns ``sanitize_columns`` must pass through untouched: raw
        payload columns still pending device decode, plus their auxiliaries."""
        names: List[str] = []
        for plan in self._plans.values():
            if not (self.host_mode or plan.host_only):
                names.append(plan.name)
                names.extend(plan.aux_names)
        return frozenset(names)

    @property
    def has_transforms(self) -> bool:
        """True when any field declares a device augment chain."""
        return any(p.transform is not None for p in self._plans.values())

    @property
    def depth(self) -> int:
        """Current device-buffer ring depth."""
        return self._depth

    def set_depth(self, depth: int) -> int:
        """Runtime-adjust the ring depth (autotune knob mutator); returns the
        applied value. A shrink drains lazily as the ring is throttled."""
        self._depth = max(1, int(depth))
        return self._depth

    # --------------------------------------------------------- host fallback

    def sanitize_decode(self, columns: Dict[str, Any]) -> Tuple[Dict[str, Any], bool]:
        """The ``_sanitize``-time half: decode the host-mode fields (all of
        them in host mode, only the ``host_only`` ones otherwise) and drop
        their auxiliary columns. Returns ``(columns, any_host_decoded)``."""
        decoded_any = False
        for plan in self._plans.values():
            if not (self.host_mode or plan.host_only):
                continue
            if plan.name in columns:
                columns = self._host_decode_field(columns, plan)
                decoded_any = True
        return columns, decoded_any

    def _host_decode_field(self, columns: Dict[str, Any],
                           plan: _FieldPlan) -> Dict[str, Any]:
        """Decode one raw-shipped field on the host, byte-identically to the
        codec's own decode (the parity contract the tests pin)."""
        out = dict(columns)
        col = out.pop(plan.name)
        values: List[Any]
        if plan.kind == 'dct':
            from petastorm_tpu.ops.image_decode import dct_decode_image
            hw = np.asarray(out.pop(plan.name + RAW_HW_SUFFIX))
            values = [
                None if coeffs is None else dct_decode_image(
                    np.asarray(coeffs), quality=plan.quality,
                    orig_hw=(int(hw[i, 0]), int(hw[i, 1])))
                for i, coeffs in enumerate(col)]
        elif plan.kind == 'npy':
            values = [
                None if blob is None else np.ascontiguousarray(
                    np.load(BytesIO(bytes(memoryview(blob))),
                            allow_pickle=False))
                for blob in col]
        else:
            enc = np.asarray(out.pop(plan.name + RAW_ENC_SUFFIX))
            values = [
                None if frame is None else np.ascontiguousarray(
                    np.load(BytesIO(_inflate_frame(frame, int(enc[i]))),
                            allow_pickle=False))
                for i, frame in enumerate(col)]
        out[plan.name] = stack_if_uniform(values, self._schema_fields.get(plan.name))
        return out

    # --------------------------------------------------------- device decode

    def prepare(self, columns: Dict[str, Any],
                mesh: Any) -> Tuple[Dict[str, Any], Tuple[Any, ...]]:
        """Producer-thread host half of the device path: pack/inflate raw
        payloads into upload-ready numeric arrays and build the static recipe
        the jitted finish program is compiled from. Returns
        ``(upload_columns, recipe)`` — upload them through the loader's
        normal (coalesced/mesh) transfer, then call :meth:`finish`."""
        upload = dict(columns)
        recipe: List[Tuple[Any, ...]] = []
        for plan in self._plans.values():
            if plan.host_only or plan.name not in upload:
                # host_only fields were already decoded by sanitize_decode —
                # the column holds decoded values, not a raw payload
                continue
            if plan.kind == 'dct':
                coeffs = upload[plan.name]
                hw = np.asarray(upload.pop(plan.name + RAW_HW_SUFFIX))
                h = int(hw[0, 0]) if len(hw) else 0
                w = int(hw[0, 1]) if len(hw) else 0
                upload[plan.name] = np.ascontiguousarray(coeffs)
                recipe.append(('dct', plan.name, plan.quality, (h, w),
                               len(plan.shape) == 2, plan.transform))
            elif plan.kind == 'npy':
                matrix = upload[plan.name]
                header_len, dtype_str, row_shape = self._npy_meta(matrix[0])
                recipe.append(('npy', plan.name, header_len, dtype_str,
                               row_shape))
            else:
                frames = upload[plan.name]
                enc = np.asarray(upload.pop(plan.name + RAW_ENC_SUFFIX))
                packed = self._pack_deflate(frames, enc, mesh)
                if packed[0] == 'stored':
                    _, src, segs, n, blob_len, npy_meta = packed
                    upload[plan.name] = src
                    upload[plan.name + _SEGS_SUFFIX] = segs
                    header_len, dtype_str, row_shape = npy_meta
                    recipe.append(('stored', plan.name, int(n), int(blob_len),
                                   header_len, dtype_str, row_shape))
                else:
                    _, matrix = packed
                    upload[plan.name] = matrix
                    header_len, dtype_str, row_shape = self._npy_meta(matrix[0])
                    recipe.append(('npy', plan.name, header_len, dtype_str,
                                   row_shape))
        return upload, tuple(recipe)

    def _stored_header_meta(
            self, frame: Any) -> Optional[Tuple[int, str, Tuple[int, ...]]]:
        """The npy-header metadata of a stored-deflate frame, from a BOUNDED
        inflate of its prefix (the header lives in the first ~128 bytes; a
        full inflate here would duplicate the work the device kernel exists to
        take). None when the prefix does not hold a parseable device-decodable
        header — the caller then uses the host-inflate packed path."""
        try:
            prefix = zlib.decompressobj(-15).decompress(memoryview(frame), 512)
            return self._npy_meta(np.frombuffer(prefix, dtype=np.uint8))
        except (zlib.error, ValueError):
            return None

    @staticmethod
    def _npy_meta(first_blob: Any) -> Tuple[int, str, Tuple[int, ...]]:
        """Shared-header metadata of a packed npy column: (header_len,
        payload dtype string, per-row shape). The ship-raw kernel already
        verified every row shares this header byte-for-byte, so parsing row 0
        describes the whole matrix."""
        from petastorm_tpu.codecs import _parse_npy_header
        parsed = _parse_npy_header(bytes(memoryview(first_blob)))
        if parsed is None:
            raise ValueError('unparseable .npy header in a device-mode batch')
        header_len, shape, fortran, dtype = parsed
        if fortran or dtype.hasobject or dtype.byteorder not in ('=', '|', '<'):
            raise ValueError('npy payload layout is not device-decodable '
                             '(fortran/object/big-endian)')
        return header_len, dtype.str, tuple(int(d) for d in shape)

    #: byte budget for the on-device stored-inflate path on real TPUs: the
    #: kernel stages the whole source + output buffers (see raw_decode's
    #: docstring), so past this total the host-inflate packed path is cheaper
    #: than blowing VMEM. Interpreted backends have no such staging limit.
    _STORED_DEVICE_BYTES_MAX = 4 * 1024 * 1024

    def _pack_deflate(self, frames: List[Any], enc: np.ndarray,
                      mesh: Any) -> Tuple[Any, ...]:
        """Choose the deflate upload form for this batch: ``('stored', src,
        segs, n, blob_len, npy_meta)`` when every frame is a stored-block
        stream (the Pallas kernel inflates on device; single-device only — the
        flat source has no batch dim to shard), else ``('packed', matrix)`` —
        host inflate into a ``(n, blob_len)`` npy matrix."""
        from petastorm_tpu.ops.raw_decode import plan_stored_batch
        n = len(frames)
        if mesh is None and n and (enc == RAW_ENC_DEFLATE).all():
            plan = plan_stored_batch([memoryview(f) for f in frames])
            if plan is not None:
                segs, frame_lengths = plan
                # dense (n, len) view needs truly uniform payloads — a total
                # divisible by n does not imply it
                src_len = sum(len(memoryview(f)) for f in frames)
                out_len = sum(frame_lengths)
                fits = (self.platform != 'tpu'
                        or src_len + out_len <= self._STORED_DEVICE_BYTES_MAX)
                npy_meta = (self._stored_header_meta(frames[0])
                            if len(set(frame_lengths)) == 1 and frame_lengths[0]
                            and fits else None)
                if npy_meta is not None:
                    src = np.concatenate([np.asarray(f, dtype=np.uint8)
                                          for f in frames])
                    # pad the flat source and the segment table to power-of-two
                    # buckets: compressed sizes differ per batch, and without
                    # bucketing every batch would carry a fresh array layout —
                    # a fresh coalesced-unpack compile + Pallas grid per batch.
                    # Zero-length pad segments are no-op RMWs in the kernel.
                    src_pad = 1 << (len(src) - 1).bit_length()
                    src = np.pad(src, (0, src_pad - len(src)))
                    seg_pad = 1 << max(0, (len(segs) - 1).bit_length())
                    segs = np.pad(segs, ((0, seg_pad - len(segs)), (0, 0)))
                    return ('stored', src, segs, n, frame_lengths[0],
                            npy_meta)
        blobs = [_inflate_frame(f, int(enc[i])) for i, f in enumerate(frames)]
        blob_len = len(blobs[0]) if blobs else 0
        matrix = np.empty((n, blob_len), dtype=np.uint8)
        for i, blob in enumerate(blobs):
            if len(blob) != blob_len:
                raise ValueError('non-uniform inflated payload lengths in a '
                                 'device-mode batch ({} vs {})'
                                 .format(len(blob), blob_len))
            matrix[i] = np.frombuffer(blob, dtype=np.uint8)
        return 'packed', matrix

    def finish(self, device_columns: Dict[str, Any],
               recipe: Tuple[Any, ...]) -> Dict[str, Any]:
        """Consumer half of the device path: run the (cached, jitted) decode +
        augment program over the uploaded columns and return the final batch
        pytree. Dispatch is async — the train step synchronizes."""
        if self._needs_rng:
            # the batch counter enters HERE, not the upload dict: the mesh
            # upload path would batch-shard it; as a scalar jit argument it is
            # transferred/replicated correctly by jax itself. Each transform
            # folds the counter into ITS OWN seed inside the program, so
            # differently-seeded transforms decorrelate and replays are
            # deterministic.
            device_columns = dict(device_columns)
            device_columns[_RNG_NAME] = np.uint32(self._rng_counter)
            self._rng_counter += 1
        program = self._programs.get(recipe)
        if program is None:
            program = self._build_program(recipe)
            self._programs[recipe] = program
        return program(device_columns)

    def apply_transforms(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Host-mode counterpart of finish()'s augment half: the declared
        chains run as the SAME jitted math over the already-uploaded decoded
        batch, so a CPU run and an accelerator run train on identical data
        (shapes, dtypes, augmentation sequence) — transforms are never
        silently dropped on a fallback backend."""
        import jax
        program = self._transform_program
        if program is None:
            entries = [(p.name, p.transform) for p in self._plans.values()
                       if p.transform is not None]

            def run(dev: Dict[str, Any], counter: Any) -> Dict[str, Any]:
                out = dict(dev)
                for name, transform in entries:
                    rng = None
                    if transform.needs_rng:
                        rng = jax.random.fold_in(
                            jax.random.PRNGKey(transform.seed), counter)
                    out[name] = transform.apply(dev[name], rng)
                return out

            program = jax.jit(run)
            self._transform_program = program
        counter = np.uint32(self._rng_counter)
        self._rng_counter += 1
        return program(batch, counter)

    def _build_program(self, recipe: Tuple[Any, ...]) -> Any:
        """Compile the jitted finish program for one static recipe. Stored
        deflate columns pre-inflate through the Pallas kernel OUTSIDE the jit
        (``pallas_call`` dispatches eagerly), then everything else is one
        fused program."""
        import jax
        from petastorm_tpu.ops.image_decode import dct_decode_images_jax
        from petastorm_tpu.ops.raw_decode import bitcast_rows, stored_inflate
        x64 = self._x64
        stored_entries = [e for e in recipe if e[0] == 'stored']
        jit_entries = [e for e in recipe if e[0] != 'stored']

        def run(dev: Dict[str, Any]) -> Dict[str, Any]:
            out = {name: col for name, col in dev.items()
                   if name != _RNG_NAME and not name.endswith(_SEGS_SUFFIX)}
            counter = dev.get(_RNG_NAME)
            for entry in jit_entries:
                if entry[0] == 'dct':
                    _, name, quality, (h, w), squeeze, transform = entry
                    images = dct_decode_images_jax(dev[name], quality=quality)
                    images = images[:, :h, :w]
                    if squeeze:
                        images = images[..., 0]
                    if transform is not None:
                        rng = None
                        if transform.needs_rng:
                            # per-field key: the transform's OWN seed folded
                            # with the per-batch counter (deterministic
                            # replay; distinct seeds decorrelate)
                            rng = jax.random.fold_in(
                                jax.random.PRNGKey(transform.seed), counter)
                        images = transform.apply(images, rng)
                    out[name] = images
                else:
                    _, name, header_len, dtype_str, row_shape = entry
                    out[name] = bitcast_rows(dev[name][:, header_len:],
                                             dtype_str, row_shape, x64=x64)
            return out

        jitted = jax.jit(run)

        if not stored_entries:
            return jitted

        def with_stored(dev: Dict[str, Any]) -> Dict[str, Any]:
            dev = dict(dev)
            for entry in stored_entries:
                _, name, n, blob_len, header_len, dtype_str, row_shape = entry
                flat = stored_inflate(dev[name], dev.pop(name + _SEGS_SUFFIX),
                                      n * blob_len)
                matrix = flat.reshape(n, blob_len)
                dev[name] = bitcast_rows(matrix[:, header_len:], dtype_str,
                                         row_shape, x64=x64)
            return jitted(dev)

        return with_stored

    # ----------------------------------------------------------------- ring

    def throttle(self, batch: Any) -> float:
        """Bound dispatched-ahead decode work: append this batch to the ring
        and, past the configured depth, block until the OLDEST dispatched
        batch is ready. Returns the seconds spent blocked (the loader reports
        them as the ``d2d_wait`` stage)."""
        import jax
        leaves = jax.tree_util.tree_leaves(batch)
        if not leaves:
            return 0.0
        self._ring.append(leaves[0])
        waited = 0.0
        while len(self._ring) > self._depth:
            oldest = self._ring.popleft()
            start = time.perf_counter()
            jax.block_until_ready(oldest)
            waited += time.perf_counter() - start
        return waited
