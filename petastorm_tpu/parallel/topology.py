"""Elastic pod-scale topology plane: negotiated per-host rowgroup shards,
a durable CRC-framed membership journal, and host-failure resharding whose
determinism is provable from composed lineage digests (docs/robustness.md
"Elastic pod-scale sharding").

Static ``cur_shard``/``shard_count`` sharding freezes the host set at
construction: a host lost mid-epoch on a pod either wedges the epoch or
silently changes every sibling's sample stream. This module replaces the
static pair with a *negotiated* shard map keyed on the process topology
(``jax.process_index()`` / ``jax.process_count()``, env-overridable with
``PETASTORM_TPU_PROCESS_INDEX`` / ``PETASTORM_TPU_PROCESS_COUNT`` so CPU
tests simulate pods as plain processes), recorded in a membership journal
on shared storage with the exact durability discipline of the dispatcher
token ledger (``service/ledger.py``): length+CRC32 framed JSON records,
one ``flush()`` per append, torn-tail-tolerant replay that stops at the
first bad frame and counts it, and atomic snapshot compaction.

The journal differs from the single-writer token ledger in one deliberate
way: every host appends to the same file, so in-place rotation (which
re-points the inode under concurrent writers) is unsafe. Compaction
therefore happens only at :meth:`MembershipJournal.open` — a natural
synchronization barrier, since hosts (re)open at epoch start — where the
replayed state is collapsed into one ``epoch`` snapshot record via
tempfile + fsync + ``os.replace``.

Determinism is proven, not promised: each host's lineage manifest header
carries the negotiated topology (count / index / shard map / reshard
generation), and :func:`compose_global_digest` folds the per-host item
streams into a single *topology-invariant* global digest — identical for
the same seed at 1, 2 or 4 hosts, and across a mid-epoch reshard, because
item identities are global and each rowgroup is delivered exactly once
per epoch regardless of which host carried it.

On host leave/lease-expiry the survivors re-deal ONLY undelivered
rowgroups, in ventilation order (the PR 15 service reshard contract at
host scale): :func:`undelivered_items` subtracts journaled ``progress``
records from the epoch's global item set, and
:func:`reshard_assignments` round-robins the remainder over the
surviving members in enumeration order. Cross-topology checkpoint
restore (save on 4 hosts, resume on 2) goes through
:func:`merge_topology_states` — never through raw ``state_dict`` swaps.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import tempfile
import time
import zlib
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, FrozenSet, List, Optional, Sequence,
                    Tuple)

logger = logging.getLogger(__name__)

#: env overrides for the process identity — lets CPU tests (and torn-off
#: launchers) simulate a pod as plain processes without a jax distributed
#: runtime (mesh.distributed_shard_info consults the same pair)
PROCESS_INDEX_ENV = 'PETASTORM_TPU_PROCESS_INDEX'
PROCESS_COUNT_ENV = 'PETASTORM_TPU_PROCESS_COUNT'

#: membership journal sidecar basename (lives in the dataset's local state
#: home next to the cost ledger / lineage manifest sidecars)
TOPOLOGY_JOURNAL_BASENAME = '_petastorm_tpu_topology_journal.bin'

#: every record kind the journal writes / the replay folds — the two-sided
#: registry pipecheck's protocol-conformance rule checks writer and replay
#: against (docs/static-analysis.md), mirroring LEDGER_RECORD_KINDS:
#: ``epoch``     — journal generation bump / compaction snapshot
#: ``join``      — a host announced itself with its process identity
#: ``leave``     — a host departed cleanly (reader stop)
#: ``lease``     — a host's liveness heartbeat (expiry => presumed dead)
#: ``progress``  — one globally-indexed item was delivered on some host
#: ``reshard``   — survivors re-dealt the undelivered remainder
TOPOLOGY_RECORD_KINDS = ('epoch', 'join', 'leave', 'lease', 'progress',
                         'reshard')

#: journal frame header: payload length + CRC32(payload) — identical wire
#: discipline to the dispatcher token ledger (service/ledger.py)
_FRAME_HEADER = struct.Struct('>II')

#: compact-at-open threshold (same default as the token ledger)
DEFAULT_ROTATE_BYTES = 4 << 20

#: membership lease duration: a host silent for longer is presumed dead
#: and its undelivered shard becomes re-dealable
DEFAULT_LEASE_S = 30.0

#: renew the lease after this fraction of the lease window has elapsed
_LEASE_RENEW_FRACTION = 0.5


def resolve_process_identity(process_index: Optional[int] = None,
                             process_count: Optional[int] = None
                             ) -> Tuple[int, int]:
    """The (process_index, process_count) identity this host negotiates
    with, resolved in precedence order: explicit pair > the
    ``PETASTORM_TPU_PROCESS_INDEX/_COUNT`` env pair > a multi-process jax
    runtime > single-host ``(0, 1)``. Either source must supply BOTH
    values — a half-specified identity is a config error, not a guess."""
    if (process_index is None) != (process_count is None):
        raise ValueError(
            'process_index and process_count must be passed together, got '
            'process_index={!r} process_count={!r}'.format(
                process_index, process_count))
    if process_index is None:
        env_index = os.environ.get(PROCESS_INDEX_ENV)
        env_count = os.environ.get(PROCESS_COUNT_ENV)
        if (env_index is None) != (env_count is None):
            raise ValueError(
                '{} and {} must be set together, got index={!r} count={!r}'
                .format(PROCESS_INDEX_ENV, PROCESS_COUNT_ENV,
                        env_index, env_count))
        if env_index is not None and env_count is not None:
            process_index, process_count = int(env_index), int(env_count)
    if process_index is None or process_count is None:
        try:
            import jax
            if jax.process_count() > 1:
                process_index = int(jax.process_index())
                process_count = int(jax.process_count())
        except Exception:  # noqa: BLE001 - no/unconfigured jax = single host
            pass
    if process_index is None or process_count is None:
        return 0, 1
    if process_count < 1:
        raise ValueError('process_count must be >= 1, got {!r}'
                         .format(process_count))
    if not 0 <= process_index < process_count:
        raise ValueError('process_index must be in [0, {}), got {!r}'
                         .format(process_count, process_index))
    return process_index, process_count


@dataclass(frozen=True)
class TopologyPolicy:
    """The ``topology=`` kwarg contract of ``make_reader`` (``True`` means
    this default policy). ``journal_path`` overrides the membership journal
    location (default: the dataset's local-state-home sidecar — required
    explicitly for remote stores with no cache). ``process_index`` /
    ``process_count`` pin the identity (default: negotiated — env pair,
    then jax). ``host_id`` names this member in the journal (default:
    ``host-<process_index>``). ``assignment`` pins an explicit global
    rowgroup-index shard (the recovery path after a reshard); with
    ``generation`` > 0 the reader records itself as a reshard survivor."""

    journal_path: Optional[str] = None
    process_index: Optional[int] = None
    process_count: Optional[int] = None
    host_id: Optional[str] = None
    lease_s: float = DEFAULT_LEASE_S
    assignment: Optional[Tuple[int, ...]] = None
    generation: int = 0

    def __post_init__(self) -> None:
        """Validate bounds at construction time (frozen-policy idiom)."""
        if (self.process_index is None) != (self.process_count is None):
            raise ValueError(
                'process_index and process_count must be set together, got '
                'process_index={!r} process_count={!r}'.format(
                    self.process_index, self.process_count))
        if self.process_count is not None:
            if self.process_count < 1:
                raise ValueError('process_count must be >= 1, got {!r}'
                                 .format(self.process_count))
            if (self.process_index is None
                    or not 0 <= self.process_index < self.process_count):
                raise ValueError(
                    'process_index must be in [0, {}), got {!r}'.format(
                        self.process_count, self.process_index))
        if self.lease_s <= 0:
            raise ValueError('lease_s must be > 0, got {!r}'
                             .format(self.lease_s))
        if self.generation < 0:
            raise ValueError('generation must be >= 0, got {!r}'
                             .format(self.generation))
        if self.assignment is not None:
            object.__setattr__(self, 'assignment',
                               tuple(int(i) for i in self.assignment))


def resolve_topology_policy(value: Any) -> Optional[TopologyPolicy]:
    """Accept ``None``/``False`` (static sharding, byte-identical seed
    path), ``True`` (default policy), a journal path string, or a
    :class:`TopologyPolicy` — the ``topology=`` kwarg contract."""
    if value is None or value is False:
        return None
    if value is True:
        return TopologyPolicy()
    if isinstance(value, str):
        return TopologyPolicy(journal_path=value)
    if isinstance(value, TopologyPolicy):
        return value
    raise TypeError('topology= accepts None/False, True, a journal path, '
                    'or a TopologyPolicy; got {!r}'.format(value))


def default_topology_journal_path(dataset_url_or_path: str,
                                  cache_location: Optional[str] = None
                                  ) -> Optional[str]:
    """Where the membership journal lives for one dataset:
    ``local_state_home(...)/_petastorm_tpu_topology_journal.bin``, or None
    when the dataset has no local state home (remote store, no cache) —
    the caller must then pass ``TopologyPolicy(journal_path=...)``."""
    from petastorm_tpu.dataset_state import sidecar_path
    return sidecar_path(dataset_url_or_path, TOPOLOGY_JOURNAL_BASENAME,
                        cache_location)


def deal_assignment(process_index: int, process_count: int,
                    num_rowgroups: int) -> Tuple[int, ...]:
    """The initial (generation-0) deal: global rowgroup indices
    ``i % process_count == process_index`` — exactly the static modulo
    split ``Reader._partition_row_groups`` applies, so an undisturbed
    topology-armed pod reads the same per-host streams as static
    ``cur_shard``/``shard_count`` and the composed digest matches the
    single-host run by construction."""
    return tuple(range(process_index, num_rowgroups, process_count))


# --------------------------------------------------------------- replay


@dataclass
class TopologyReplay:
    """Everything a journal replay reconstructs: the membership roster with
    lease expiries, the globally-indexed delivered set, the current shard
    map and reshard generation, and how the replay itself went (``result``
    is ``absent`` / ``ok`` / ``corrupt``; ``frames_dropped`` counts frames
    rejected by CRC/framing — a torn tail is ONE dropped frame and a
    healthy journal)."""

    result: str = 'absent'
    generation: int = 0
    members: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    delivered: FrozenSet[Tuple[int, int, int]] = frozenset()
    shard_map: Dict[str, Tuple[int, ...]] = field(default_factory=dict)
    resharded: int = 0
    frames_dropped: int = 0
    records: int = 0

    def apply(self, record: Dict[str, Any]) -> None:
        """Fold one journal record (replay side of the two-sided record-kind
        registry — every arm here names a TOPOLOGY_RECORD_KINDS member)."""
        kind = record.get('kind')
        delivered = set(self.delivered)
        if kind == 'epoch':
            self.generation = int(record.get('generation', self.generation))
        elif kind == 'join':
            host = str(record.get('host'))
            self.members[host] = {
                'process_index': record.get('process_index'),
                'process_count': record.get('process_count'),
                'expiry': float(record.get('expiry', 0.0)),
                'alive': True,
            }
        elif kind == 'leave':
            host = str(record.get('host'))
            if host in self.members:
                self.members[host]['alive'] = False
        elif kind == 'lease':
            host = str(record.get('host'))
            if host in self.members:
                self.members[host]['expiry'] = float(
                    record.get('expiry', 0.0))
        elif kind == 'progress':
            delivered.add((int(record.get('epoch', 0)),
                           int(record.get('index', -1)),
                           int(record.get('drop', 0))))
            self.delivered = frozenset(delivered)
        elif kind == 'reshard':
            self.resharded += 1
            self.generation = int(record.get('generation', self.generation))
            assignments = record.get('assignments') or {}
            self.shard_map = {
                str(host): tuple(int(i) for i in indices)
                for host, indices in assignments.items()}
        self.records += 1

    def stale_leases(self, now: float) -> List[str]:
        """Hosts still marked alive whose lease expired before ``now`` —
        presumed dead; their undelivered shard is re-dealable."""
        return sorted(host for host, info in self.members.items()
                      if info.get('alive') and float(
                          info.get('expiry', 0.0)) < now)

    def as_dict(self) -> Dict[str, Any]:
        """JSON-ready summary for diagnostics / the doctor report."""
        return {'result': self.result, 'generation': self.generation,
                'members': {host: dict(info)
                            for host, info in sorted(self.members.items())},
                'delivered': len(self.delivered),
                'resharded': self.resharded,
                'frames_dropped': self.frames_dropped,
                'records': self.records}


def read_frames(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Decode journal frames until the first bad one (short header, short
    payload, CRC mismatch, non-JSON) — a torn tail from a crashed append
    truncates the replay, never corrupts it. Returns (records,
    dropped_count); dropped is 1 when a trailing frame was rejected."""
    records: List[Dict[str, Any]] = []
    dropped = 0
    with open(path, 'rb') as stream:
        while True:
            header = stream.read(_FRAME_HEADER.size)
            if not header:
                break
            if len(header) < _FRAME_HEADER.size:
                dropped += 1
                break
            length, crc = _FRAME_HEADER.unpack(header)
            payload = stream.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                dropped += 1
                break
            try:
                record = json.loads(payload.decode('utf-8'))
            except (UnicodeDecodeError, json.JSONDecodeError):
                dropped += 1
                break
            records.append(record)
    return records, dropped


def replay_topology_journal(path: str) -> TopologyReplay:
    """Replay a membership journal into a :class:`TopologyReplay`.
    ``result`` is ``absent`` (no journal — a fresh pod), ``ok`` (every
    frame decoded) or ``corrupt`` (replay stopped at a bad frame; the
    prefix before it still replayed — degraded loudly, never silently)."""
    replay = TopologyReplay()
    if not os.path.exists(path):
        return replay
    records, dropped = read_frames(path)
    replay.frames_dropped = dropped
    for record in records:
        replay.apply(record)
    replay.result = 'corrupt' if dropped else 'ok'
    return replay


# --------------------------------------------------------------- journal


class MembershipJournal:
    """Durable multi-writer membership journal (module doc): the token
    ledger's frame/flush/replay discipline with compact-at-open instead of
    in-place rotation. All topology record kinds are journaled through the
    typed ``note_*`` wrappers below so the writer-side kind literals live
    in exactly one module — the side pipecheck's protocol-conformance rule
    audits against TOPOLOGY_RECORD_KINDS."""

    def __init__(self, path: str, rotate_bytes: int = DEFAULT_ROTATE_BYTES,
                 clock: Callable[[], float] = time.time) -> None:
        self.path = path
        self.rotate_bytes = rotate_bytes
        self._clock = clock
        self._file: Optional[Any] = None
        self._appended = 0
        self.last_replay: Optional[TopologyReplay] = None

    def open(self) -> TopologyReplay:
        """Replay the journal (tolerating a torn tail), compact it into one
        snapshot record when it outgrew ``rotate_bytes``, then open for
        appending. Returns the replay so the caller can seed its shard map
        and surface ``frames_dropped`` loudly."""
        replay = replay_topology_journal(self.path)
        self.last_replay = replay
        if (os.path.exists(self.path)
                and os.path.getsize(self.path) >= self.rotate_bytes):
            self._compact(replay)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._file = open(self.path, 'ab')
        self.append_record('epoch', generation=replay.generation)
        return replay

    def append_record(self, kind: str, **fields: Any) -> None:
        """Append one framed record and flush — each append is durable on
        its own, so a crash between appends loses at most the torn tail
        the replay already tolerates. IO errors are logged, not raised: a
        full shared disk degrades membership, it must not kill the read."""
        if self._file is None:
            return
        record = dict(fields, kind=kind)
        payload = json.dumps(record, sort_keys=True).encode('utf-8')
        frame = _FRAME_HEADER.pack(len(payload),
                                   zlib.crc32(payload)) + payload
        try:
            self._file.write(frame)
            self._file.flush()
            self._appended += 1
        except OSError:
            logger.exception('topology journal append failed (%s); '
                             'membership continues undurably', self.path)

    # Typed writer surface: callers journal through these so topology kind
    # literals never leak into reader.py / chaos.py (the protocol rule
    # audits append_record literals per module).

    def note_join(self, host: str, process_index: int, process_count: int,
                  generation: int, lease_s: float) -> None:
        """Announce ``host`` with its negotiated identity and first lease."""
        self.append_record('join', host=host, process_index=process_index,
                           process_count=process_count,
                           generation=generation,
                           expiry=self._clock() + lease_s)

    def note_leave(self, host: str) -> None:
        """Record a clean departure (reader stop)."""
        self.append_record('leave', host=host)

    def note_lease(self, host: str, lease_s: float) -> None:
        """Renew ``host``'s liveness lease."""
        self.append_record('lease', host=host,
                           expiry=self._clock() + lease_s)

    def note_progress(self, host: str, epoch: int, index: int,
                      drop: int) -> None:
        """Record delivery of one globally-indexed item — the undelivered
        set a reshard re-deals is everything NOT journaled here."""
        self.append_record('progress', host=host, epoch=epoch, index=index,
                           drop=drop)

    def note_reshard(self, generation: int,
                     assignments: Dict[str, Sequence[int]],
                     reason: str) -> None:
        """Record a re-deal of the undelivered remainder over survivors."""
        self.append_record('reshard', generation=generation,
                           assignments={host: list(indices) for host, indices
                                        in sorted(assignments.items())},
                           reason=reason)

    def _compact(self, replay: TopologyReplay) -> None:
        """Collapse the journal into one ``epoch`` snapshot record,
        atomically (tempfile + fsync + ``os.replace``) — only ever called
        from :meth:`open`, the multi-writer synchronization barrier."""
        record = {'kind': 'epoch', 'generation': replay.generation,
                  'compacted': replay.records}
        payload = json.dumps(record, sort_keys=True).encode('utf-8')
        frame = _FRAME_HEADER.pack(len(payload),
                                   zlib.crc32(payload)) + payload
        parent = os.path.dirname(self.path) or '.'
        handle, temp_path = tempfile.mkstemp(dir=parent,
                                             prefix='.topology-compact-')
        try:
            with os.fdopen(handle, 'wb') as stream:
                stream.write(frame)
                stream.flush()
                os.fsync(stream.fileno())
            os.replace(temp_path, self.path)
        except OSError:
            logger.exception('topology journal compaction failed (%s); '
                             'continuing with the uncompacted journal',
                             self.path)
        finally:
            # no-op after a successful os.replace; on ANY failure path
            # (OSError or not) the orphaned temp file is removed
            try:
                os.unlink(temp_path)
            except OSError:
                pass

    def state(self) -> Dict[str, Any]:
        """Diagnostics block (ledger-state idiom): armed flag, path, append
        count, plus the last replay's result/drops when one ran."""
        block: Dict[str, Any] = {'armed': self._file is not None,
                                 'path': self.path,
                                 'appended': self._appended}
        if self.last_replay is not None:
            block['last_replay'] = self.last_replay.result
            block['frames_dropped'] = self.last_replay.frames_dropped
            block['records_replayed'] = self.last_replay.records
            block['generation'] = self.last_replay.generation
        return block

    def close(self) -> None:
        """Flush and close with NO terminal record — a clean stop and a
        crash replay identically (the ledger's crash-equivalence rule)."""
        if self._file is None:
            return
        try:
            self._file.flush()
            self._file.close()
        except OSError:
            logger.exception('topology journal close failed (%s)', self.path)
        self._file = None


# --------------------------------------------------------------- reshard


def undelivered_items(num_rowgroups: int, epoch: int,
                      delivered: FrozenSet[Tuple[int, int, int]],
                      drop_partitions: int = 1) -> List[Tuple[int, int]]:
    """The re-dealable remainder of ``epoch``: every (global_index, drop)
    item NOT journaled as progress, in ventilation order (ascending global
    index, then drop) — the order the reshard contract preserves."""
    remainder = []
    for index in range(num_rowgroups):
        for drop in range(drop_partitions):
            if (epoch, index, drop) not in delivered:
                remainder.append((index, drop))
    return remainder


def reshard_assignments(undelivered: Sequence[Tuple[int, int]],
                        survivors: Sequence[str]
                        ) -> Dict[str, Tuple[int, ...]]:
    """Round-robin the undelivered remainder over ``survivors`` in
    enumeration order — deterministic given the same remainder and roster,
    so every survivor computes the identical deal from its own replay.
    Returns global rowgroup indices per host (deduplicated, ordered)."""
    if not survivors:
        raise ValueError('cannot reshard over an empty survivor set')
    dealt: Dict[str, List[int]] = {host: [] for host in survivors}
    for position, (index, _drop) in enumerate(undelivered):
        host = survivors[position % len(survivors)]
        if index not in dealt[host]:
            dealt[host].append(index)
    return {host: tuple(indices) for host, indices in dealt.items()}


# ----------------------------------------------------------- composition


def compose_global_digest(manifest_paths: Sequence[str]) -> Dict[str, Any]:
    """Fold per-host lineage manifests into ONE topology-invariant global
    digest: collect every delivered item row from each manifest's newest
    segment, require a shared dataset token, sort the union canonically by
    item identity, and fold from the genesis digest — the same chain rule
    ``lineage verify`` applies to a single host. Identical for any host
    count and across a mid-epoch reshard, because item identities are
    global (epoch, fragment, rowgroup, row range, drop) and each is
    delivered exactly once per epoch. Duplicate identities (a rowgroup
    delivered twice — a broken reshard) are counted, never masked."""
    from petastorm_tpu.telemetry.lineage import (fold_digest, genesis_digest,
                                                 load_manifest,
                                                 manifest_items)
    dataset_token: Optional[str] = None
    items: List[Tuple[List[Any], int]] = []
    for path in manifest_paths:
        segments = load_manifest(path)
        if not segments:
            raise ValueError('lineage manifest {!r} has no segments'
                             .format(path))
        segment = segments[-1]
        token = segment['header'].get('dataset_token')
        if dataset_token is None:
            dataset_token = token
        elif token != dataset_token:
            raise ValueError(
                'manifest {!r} belongs to dataset token {!r}, expected '
                '{!r} — digests of different datasets do not compose'
                .format(path, token, dataset_token))
        for item in manifest_items(segment):
            identity = [item[0], item[1], item[2], item[3], item[4]]
            items.append((identity, int(item[5])))
    if dataset_token is None:
        raise ValueError('no manifests to compose')
    keys = [json.dumps(identity, sort_keys=True)
            for identity, _rows in items]
    duplicates = sorted(key for key in set(keys) if keys.count(key) > 1)
    order = sorted(range(len(items)), key=lambda i: keys[i])
    digest = genesis_digest(dataset_token)
    total_rows = 0
    for position in order:
        identity, rows = items[position]
        digest = fold_digest(digest, identity, rows)
        total_rows += rows
    return {'digest': digest, 'items': len(items), 'rows': total_rows,
            'duplicates': duplicates, 'hosts': len(manifest_paths),
            'dataset_token': dataset_token}


# ------------------------------------------------- cross-topology restore


def merge_topology_states(states: Sequence[Dict[str, Any]],
                          new_count: int) -> List[Dict[str, Any]]:
    """Re-deal a full pod's saved reader states onto a DIFFERENT host
    count (save on 4 hosts, resume on 2): map every host's consumed
    (piece, drop) pairs to global rowgroup indices through its saved
    assignment, then cut generation-0 deals for ``new_count`` hosts and
    project the global consumed set back into each new host's local piece
    space. The merged states carry a ``topology`` block naming the new
    deal; feed each to ``make_reader(topology=policy_from_state(state),
    resume_state=state)``. Refuses mid-batch cursors and mismatched
    epochs — only a batch-aligned, pod-consistent save resumes exactly."""
    if new_count < 1:
        raise ValueError('new_count must be >= 1, got {!r}'
                         .format(new_count))
    if not states:
        raise ValueError('no states to merge')
    epochs: List[int] = []
    global_rowgroups: Optional[int] = None
    consumed_global: Dict[int, set] = {}
    for state in states:
        topo = state.get('topology')
        if not topo:
            raise ValueError(
                'state_dict was not saved by a topology-armed reader — '
                'cross-topology restore requires the negotiated path '
                '(make_reader(topology=...))')
        if state.get('row_cursor') is not None:
            raise ValueError(
                'cannot merge a mid-batch state (row_cursor is set); '
                'save on a batch boundary')
        epochs.append(int(state.get('epochs_consumed', 0)))
        rowgroups = int(topo['global_rowgroups'])
        if global_rowgroups is None:
            global_rowgroups = rowgroups
        elif rowgroups != global_rowgroups:
            raise ValueError(
                'states disagree on the global rowgroup count: {} vs {}'
                .format(global_rowgroups, rowgroups))
        assignment = [int(i) for i in topo['assignment']]
        for epoch_key, pairs in (state.get('consumed_by_epoch')
                                 or {}).items():
            bucket = consumed_global.setdefault(int(epoch_key), set())
            for piece, drop in pairs:
                bucket.add((assignment[int(piece)], int(drop)))
    if len(set(epochs)) > 1:
        raise ValueError(
            'states disagree on epochs_consumed ({}) — save the whole pod '
            'at one barrier before restoring across topologies'
            .format(sorted(set(epochs))))
    assert global_rowgroups is not None
    merged: List[Dict[str, Any]] = []
    for new_index in range(new_count):
        assignment = deal_assignment(new_index, new_count, global_rowgroups)
        reverse = {global_index: piece
                   for piece, global_index in enumerate(assignment)}
        consumed_local = {
            str(epoch): sorted(
                [reverse[index], drop]
                for index, drop in pairs if index in reverse)
            for epoch, pairs in sorted(consumed_global.items())}
        merged.append({
            'version': states[0].get('version'),
            'items_per_epoch': len(assignment),
            'epochs_consumed': epochs[0],
            'consumed_by_epoch': {epoch: pairs for epoch, pairs
                                  in consumed_local.items() if pairs},
            'row_cursor': None,
            'topology': {'process_index': new_index,
                         'process_count': new_count,
                         'generation': 0,
                         'assignment': list(assignment),
                         'global_rowgroups': global_rowgroups},
        })
    return merged


def policy_from_state(state: Dict[str, Any],
                      journal_path: Optional[str] = None) -> TopologyPolicy:
    """The :class:`TopologyPolicy` that resumes one merged state on its
    new host: pinned identity + explicit assignment, so the resumed reader
    shards exactly as the merge dealt regardless of the live environment."""
    topo = state.get('topology')
    if not topo:
        raise ValueError('state has no topology block — it was not saved '
                         'by a topology-armed reader')
    return TopologyPolicy(journal_path=journal_path,
                          process_index=int(topo['process_index']),
                          process_count=int(topo['process_count']),
                          assignment=tuple(int(i)
                                           for i in topo['assignment']),
                          generation=int(topo.get('generation', 0)))


# ------------------------------------------------------------- per-host


class HostTopology:
    """One reader's live view of the negotiated topology: identity, shard
    assignment, journal membership and progress. Constructed by ``Reader``
    when ``topology=`` is armed; ``clock`` is injectable so lease tests
    never sleep."""

    def __init__(self, policy: TopologyPolicy, journal_path: str,
                 num_rowgroups: int, registry: Optional[Any] = None,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.policy = policy
        self.num_rowgroups = num_rowgroups
        self._registry = registry
        self._clock = clock or time.time
        self.process_index, self.process_count = resolve_process_identity(
            policy.process_index, policy.process_count)
        self.host_id = policy.host_id or 'host-{}'.format(self.process_index)
        self.generation = policy.generation
        self.journal = MembershipJournal(journal_path, clock=self._clock)
        replay = self.journal.open()
        self.frames_dropped = replay.frames_dropped
        if self.frames_dropped:
            logger.warning(
                'topology journal %s dropped %d frame(s) on replay — a '
                'past append was torn or a byte flipped; membership '
                'resumed from the intact prefix', journal_path,
                self.frames_dropped)
            self._inc('topology_frames_dropped', self.frames_dropped)
        if policy.assignment is not None:
            self.assignment: Tuple[int, ...] = policy.assignment
        else:
            self.assignment = deal_assignment(
                self.process_index, self.process_count, num_rowgroups)
        self.journal.note_join(self.host_id, self.process_index,
                               self.process_count, self.generation,
                               policy.lease_s)
        self._lease_renewed_at = self._clock()
        if self.generation > 0:
            self._inc('host_reshard')
            self._trace_instant('host_reshard')

    def _inc(self, name: str, n: int = 1) -> None:
        if self._registry is not None:
            self._registry.inc(name, n)

    @staticmethod
    def _trace_instant(name: str) -> None:
        from petastorm_tpu.telemetry.tracing import trace_instant
        trace_instant(name)

    def note_progress(self, epoch: int, piece: int, drop: int) -> None:
        """Journal delivery of local piece ``piece`` as its GLOBAL rowgroup
        index (the identity a reshard subtracts), renewing the membership
        lease when half the window has elapsed."""
        if piece < 0 or piece >= len(self.assignment):
            return
        self.journal.note_progress(self.host_id, epoch,
                                   self.assignment[piece], drop)
        now = self._clock()
        if (now - self._lease_renewed_at
                >= self.policy.lease_s * _LEASE_RENEW_FRACTION):
            self.journal.note_lease(self.host_id, self.policy.lease_s)
            self._lease_renewed_at = now

    def header(self) -> Dict[str, Any]:
        """The lineage-manifest topology header: the negotiated identity
        and shard map that ``lineage diff`` attributes divergences to.
        Deliberately minimal and deterministic — an undisturbed survivor's
        header must byte-match its same-seed baseline."""
        return {'process_count': self.process_count,
                'process_index': self.process_index,
                'generation': self.generation,
                'shard_map': list(self.assignment)}

    def state_block(self) -> Dict[str, Any]:
        """The ``state_dict()['topology']`` block cross-topology restore
        merges on: identity + explicit global assignment."""
        return {'process_index': self.process_index,
                'process_count': self.process_count,
                'generation': self.generation,
                'assignment': list(self.assignment),
                'global_rowgroups': self.num_rowgroups}

    def report(self) -> Dict[str, Any]:
        """Diagnostics block: identity, assignment size, journal state and
        any stale leases visible at report time."""
        block = {'host_id': self.host_id,
                 'process_index': self.process_index,
                 'process_count': self.process_count,
                 'generation': self.generation,
                 'assignment': list(self.assignment),
                 'journal': self.journal.state()}
        replay = self.journal.last_replay
        if replay is not None:
            block['stale_leases'] = replay.stale_leases(self._clock())
        return block

    def close(self) -> None:
        """Journal a clean leave and close (idempotent)."""
        if self.journal is not None and self.journal._file is not None:
            self.journal.note_leave(self.host_id)
            self.journal.close()

    def abandon(self) -> None:
        """Close the journal WITHOUT a leave record — the crash simulation
        hook. To every later replay this host simply stops journaling, which
        is exactly what a SIGKILL'd or partitioned host looks like; survivors
        must detect it by lease expiry, not by a polite goodbye."""
        if self.journal is not None and self.journal._file is not None:
            self.journal.close()
