"""GPipe-style pipeline parallelism (pp) over a ``'stage'`` mesh axis.

The reference framework scales torch consumers with data-parallel sharding only; the
TPU-native parallelism families here add pipeline parallelism the XLA way — one jitted
SPMD program, no per-stage processes or hand-written schedules:

- **Stacked stage parameters.** Per-stage parameter pytrees are stacked along a leading
  stages axis (:func:`stack_stage_params`) and sharded ``PartitionSpec('stage', ...)``
  (:func:`stage_partition_specs`); inside ``shard_map`` each device holds exactly its
  stage's slice.
- **ppermute schedule.** Microbatches stream through a ``lax.scan`` of
  ``n_micro + n_stages - 1`` ticks (the classic GPipe schedule, bubble ``n_stages-1``);
  every tick applies the local stage and shifts activations to the next stage with
  ``lax.ppermute`` over ICI.
- **Differentiable end to end.** ``scan`` and ``ppermute`` have exact transposes, so
  ``jax.grad`` through the pipeline yields the pipeline-parallel backward pass — no
  manual backward schedule, matching how XLA wants pipelines expressed.

``stage_fn`` must be shape- and dtype-preserving (activations circulate through a fixed
buffer), which transformer blocks are. Inputs are replicated over the stage axis and may
be sharded over other mesh axes (e.g. ``xs_spec=P(None, 'data')`` for dp+pp); GPipe
holds all microbatches resident anyway, so the replication does not change the memory
order.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from petastorm_tpu.parallel.mesh import shard_map_compat


def stack_stage_params(stage_params_list):
    """Stack a list of per-stage parameter pytrees into one pytree whose leaves carry
    a leading stages axis. All stages must share a structure (uniform stages — the
    usual pipeline shape)."""
    if not stage_params_list:
        raise ValueError('need at least one stage')
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *stage_params_list)


def unstack_stage_params(stacked, stage):
    """The inverse view: stage ``i``'s parameter pytree from the stacked tree."""
    return jax.tree.map(lambda leaf: leaf[stage], stacked)


def stage_partition_specs(stacked, stage_axis='stage'):
    """PartitionSpecs sharding every leaf's leading (stages) axis over
    ``stage_axis``; pair with ``NamedSharding`` to place stacked params."""
    return jax.tree.map(lambda leaf: P(stage_axis, *([None] * (leaf.ndim - 1))),
                        stacked)


def make_pipeline(stage_fn, mesh, stage_axis='stage', xs_spec=P(), out_spec=P(),
                  params_spec=None):
    """Build ``fn(stacked_params, xs) -> ys`` running ``stage_fn`` as a pipeline.

    :param stage_fn: ``(stage_params, microbatch) -> microbatch`` — one stage's
        computation; must preserve shape and dtype. It runs inside ``shard_map``, so
        it may use collectives over the mesh's OTHER axes (e.g.
        ``ops.sharded_moe.expert_alltoall_ffn`` over an ``'expert'`` axis — pipeline
        and expert parallelism in one program).
    :param mesh: mesh containing ``stage_axis``; other axes pass through (shard
        ``xs``'s non-microbatch dims over them via ``xs_spec``).
    :param xs_spec: PartitionSpec of ``xs`` (``[n_micro, ...microbatch...]``); dim 0
        is the microbatch stream and must NOT be sharded over ``stage_axis``.
    :param out_spec: PartitionSpec of the output (same layout as ``xs``).
    :param params_spec: in_spec (pytree prefix) for the stacked params; default
        ``P(stage_axis)`` shards only the leading stages axis and replicates the
        rest. Pass per-leaf specs like ``P('stage', 'expert', None, None)`` to ALSO
        shard stage weights over other mesh axes; every leaf's dim 0 must still be
        sharded over ``stage_axis`` (each device holds exactly its stage's slice).
    :returns: a function usable under ``jit``: feeds microbatch ``m`` to stage 0 at
        tick ``m``, collects stage ``n-1`` outputs, returns them replicated over the
        stage axis (other axes per ``out_spec``).
    """
    if stage_axis not in mesh.shape:
        raise ValueError('mesh has no axis {!r} (axes: {})'
                         .format(stage_axis, dict(mesh.shape)))
    if params_spec is None:
        params_spec = P(stage_axis)
    # None-preserving traversal: a None leaf is the conventional 'replicated'
    # spelling and MUST be rejected too — shard_map would replicate the stacked
    # params over the stage axis and leaf[0] would silently serve stage 0's
    # weights on every stage.
    specs = jax.tree.leaves(params_spec,
                            is_leaf=lambda leaf: leaf is None or isinstance(leaf, P))
    for spec in specs:
        if spec is None or not spec or spec[0] != stage_axis:
            raise ValueError('params_spec leaf {} must shard dim 0 over {!r} '
                             '(each device holds its own stage)'
                             .format(spec, stage_axis))
    n_stages = mesh.shape[stage_axis]
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def local_fn(stacked_local, xs):
        # P(stage_axis) shards each leaf's leading dim to length 1: this stage's params.
        params = jax.tree.map(lambda leaf: leaf[0], stacked_local)
        idx = lax.axis_index(stage_axis)
        n_micro = xs.shape[0]
        state = jnp.zeros(xs.shape[1:], xs.dtype)
        outputs = jnp.zeros_like(xs)

        def tick(carry, t):
            state, outputs = carry
            feed = lax.dynamic_index_in_dim(xs, jnp.minimum(t, n_micro - 1), 0,
                                            keepdims=False)
            inp = jnp.where(idx == 0, feed, state)
            out = stage_fn(params, inp)
            if out.shape != inp.shape or out.dtype != inp.dtype:
                raise ValueError(
                    'pipeline stage_fn must preserve shape/dtype: {} {} -> {} {}'
                    .format(inp.shape, inp.dtype, out.shape, out.dtype))
            done = t - (n_stages - 1)
            updated = lax.dynamic_update_index_in_dim(outputs, out,
                                                      jnp.maximum(done, 0), 0)
            is_last = idx == n_stages - 1
            outputs = jnp.where(jnp.logical_and(is_last, done >= 0), updated, outputs)
            state = lax.ppermute(out, stage_axis, perm)
            return (state, outputs), None

        steps = n_micro + n_stages - 1
        (_, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(steps))
        # The buffer is authoritative only on the last stage; the masked psum makes
        # every stage agree so the result is truly replicated over the stage axis.
        is_last = lax.axis_index(stage_axis) == n_stages - 1
        return lax.psum(jnp.where(is_last, outputs, jnp.zeros_like(outputs)),
                        stage_axis)

    return shard_map_compat(local_fn, mesh, (params_spec, xs_spec), out_spec)


def microbatch(batch, n_micro):
    """Split ``[batch, ...]`` into ``[n_micro, batch/n_micro, ...]`` (the pipeline's
    input layout). Batch must divide evenly — pad upstream (the loaders' pad-and-mask
    path) rather than here."""
    leading = batch.shape[0]
    if leading % n_micro != 0:
        raise ValueError('batch {} not divisible into {} microbatches'
                         .format(leading, n_micro))
    return batch.reshape((n_micro, leading // n_micro) + batch.shape[1:])
