"""Mesh + shard-discovery helpers.

The reference discovers the data-parallel shard from Horovod/MPI environment variables
(petastorm/spark/spark_dataset_converter.py:116-129); the TPU-native contract is the JAX
runtime itself: ``jax.process_index()/process_count()`` over an initialized
``jax.distributed`` backend, with manual ``cur_shard/shard_count`` kwargs kept as
overrides.
"""

import os

import numpy as np


def shard_map_compat(fn, mesh, in_specs, out_specs, check=False):
    """``jax.shard_map`` across jax versions (the kwarg was renamed check_rep ->
    check_vma in 0.8, and the function moved out of jax.experimental)."""
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=check)
    except TypeError:
        return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_rep=check)


def make_mesh(axis_names=('data',), axis_sizes=None, devices=None):
    """Build a :class:`jax.sharding.Mesh` over the available devices.

    :param axis_names: mesh axis names, e.g. ``('data',)`` or ``('data', 'model')``.
    :param axis_sizes: sizes per axis; None infers a single axis over all devices, or
        factors the device count with the leading axis taking the remainder.
    :param devices: explicit device list (default ``jax.devices()``).
    """
    import jax
    from jax.sharding import Mesh
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axis_sizes is None:
        if len(axis_names) == 1:
            axis_sizes = (n,)
        else:
            axis_sizes = (n,) + (1,) * (len(axis_names) - 1)
    axis_sizes = tuple(axis_sizes)
    if int(np.prod(axis_sizes)) != n:
        raise ValueError('axis_sizes {} do not multiply to device count {}'
                         .format(axis_sizes, n))
    device_array = np.asarray(devices).reshape(axis_sizes)
    return Mesh(device_array, axis_names)


def batch_sharding(mesh, partition_spec=None, batch_axis='data'):
    """NamedSharding for batches: by default batch dim sharded over ``batch_axis``; any
    ``PartitionSpec`` is accepted so the loader can feed TP/PP/SP-sharded consumers, not
    only batch-axis DP (SURVEY.md §2.8 obligation)."""
    from jax.sharding import NamedSharding, PartitionSpec
    if partition_spec is None:
        partition_spec = PartitionSpec(batch_axis)
    return NamedSharding(mesh, partition_spec)


def distributed_shard_info(cur_shard=None, shard_count=None):
    """Resolve this process's (cur_shard, shard_count) for reader construction.

    Priority: explicit kwargs > PETASTORM_TPU_PROCESS_INDEX/_COUNT env pair (the
    topology plane's CPU-test override — parallel/topology.py) > initialized JAX
    distributed runtime > single process. Legacy Horovod/MPI env vars are honored as a
    compatibility fallback, mirroring the reference's detection
    (spark_dataset_converter.py:116-129)."""
    if cur_shard is not None or shard_count is not None:
        if (cur_shard is None) != (shard_count is None):
            raise ValueError('cur_shard and shard_count must be given together')
        return cur_shard, shard_count
    from petastorm_tpu.parallel.topology import (PROCESS_COUNT_ENV,
                                                 PROCESS_INDEX_ENV)
    if PROCESS_INDEX_ENV in os.environ and PROCESS_COUNT_ENV in os.environ:
        return (int(os.environ[PROCESS_INDEX_ENV]),
                int(os.environ[PROCESS_COUNT_ENV]))
    import jax
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    for rank_var, size_var in (('HOROVOD_RANK', 'HOROVOD_SIZE'),
                               ('OMPI_COMM_WORLD_RANK', 'OMPI_COMM_WORLD_SIZE'),
                               ('PMI_RANK', 'PMI_SIZE')):
        if rank_var in os.environ and size_var in os.environ:
            return int(os.environ[rank_var]), int(os.environ[size_var])
    return None, None


def initialize_distributed(coordinator_address=None, num_processes=None, process_id=None):
    """Thin gate over ``jax.distributed.initialize`` (multi-host DCN coordination). Safe
    to call when already initialized."""
    import jax
    try:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes, process_id=process_id)
    except RuntimeError:
        pass  # already initialized
