"""JaxDataLoader: reader -> mesh-sharded ``jax.Array`` batches with double-buffered
host->device transfer and input-stall instrumentation.

This is the TPU-native flagship adapter (the role petastorm/pytorch.py:126-496 plays for
torch), designed per SURVEY.md §7.1 item 5:

- batches are assembled columnar on the host (numpy), optionally through a seeded
  shuffling buffer (the reference's shuffling-queue semantics, pytorch.py:178-186);
- each batch becomes a pytree of globally-sharded ``jax.Array`` via
  ``jax.make_array_from_process_local_data`` over an arbitrary ``PartitionSpec`` — batch
  axis DP by default, but any TP/SP layout is accepted (SURVEY.md §2.8);
- a background producer thread keeps ``prefetch`` batches in flight so host IO/decode and
  H2D transfer overlap device compute (double buffering);
- ``stats.input_stall_fraction`` measures the time the consumer blocked waiting on the
  input pipeline — the BASELINE.md north-star metric — from inside the loader, where
  async dispatch can't hide it.
"""

import collections
import contextlib
import queue
import sys
import threading
import time
import warnings

import numpy as np

from petastorm_tpu.telemetry import tracing as _flight
from petastorm_tpu.parallel.shuffling_buffer import (NoopShufflingBuffer,
                                                     RandomShufflingBuffer)

_END = object()
#: scan_stream keeps this many compiled (step_fn, chunk-shape) programs per loader
_SCAN_STREAM_CACHE_MAX = 8
#: coalesced-upload unpack programs kept per loader (layouts are stable per stream;
#: the cap only guards pathological consumers feeding ever-changing schemas)
_UNPACK_CACHE_MAX = 8


try:
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except ImportError:  # pragma: no cover - jax is a hard dep in practice
    _TraceAnnotation = None


def _trace_span(name):
    """jax.profiler annotation so loader stages show up in device traces next to the
    XLA ops they feed (SURVEY.md §5.1: the TPU-native replacement for the reference's
    per-thread cProfile); a no-op nullcontext when jax is absent."""
    if _TraceAnnotation is None:
        return contextlib.nullcontext()
    return _TraceAnnotation(name)


class LoaderStats(object):
    """Thread-safe loader counters (batches/rows, wait vs total time); the input
    stall fraction ``wait_time_s / total_time_s`` is the bench's efficiency
    metric. Mutation happens through :meth:`add` (deltas) and :meth:`mirror`
    (absolute values) under one internal lock — the loader writes from BOTH its
    consumer thread (per-batch accounting) and its producer thread (reader-stat
    mirroring), so bare ``stats.field += 1`` would lose updates under the race.
    ``as_dict`` snapshots every field under the same lock (one consistent view).
    The upload-mode counters make the H2D path observable in captured
    bench lines: a hardware capture can PROVE whether the coalesced
    single-transfer path engaged (``coalesced_uploads``) or each field shipped
    separately (``per_field_uploads`` — also counts mesh-path uploads).

    ``io_retries`` / ``rowgroups_quarantined`` mirror the reader's resilience
    counters (docs/robustness.md) into the loader's own stats surface: a training
    job that only watches ``LoaderStats`` still sees degradation — a non-zero
    quarantine count means the epoch silently served fewer rowgroups.

    The zero-copy data-plane counters mirror the same way (docs/performance.md):
    ``cache_hits``/``cache_misses`` (decoded-rowgroup cache; a warm epoch should be
    all hits), ``shm_batches``/``shm_fallback_batches`` (which transport the process
    pool's results actually took) and ``wire_bytes_copied_per_batch`` (bytes
    materialized into new host memory per result batch — the number the shm ring
    exists to shrink; a true running mean from the pool's ``wire_bytes_copied``
    histogram, so multi-pool and mixed-transport runs report the stream-wide
    mean, not the last pool's last value).

    Device-resident decode tail (docs/performance.md): ``device_decode_batches``
    counts batches whose raw-shipped fields decoded as device kernels;
    ``device_fallback_batches`` counts chunks whose device fields decoded on
    the host instead (CPU backend, ``device_put=False``, or a per-field
    fallback) — a capture can PROVE which path ran. ``unpack_cache_evictions``
    counts compiled coalesced-upload unpack programs evicted from the
    per-loader LRU: non-zero means the consumer feeds more distinct batch
    layouts than the cache holds, and uploads are paying re-trace cost."""

    _FIELDS = ('batches', 'rows', 'wait_time_s', 'total_time_s',
               'coalesced_uploads', 'per_field_uploads', 'io_retries',
               'rowgroups_quarantined', 'cache_hits', 'cache_misses',
               'shm_batches', 'shm_fallback_batches',
               'wire_bytes_copied_per_batch', 'device_decode_batches',
               'device_fallback_batches', 'unpack_cache_evictions')

    def __init__(self):
        self._lock = threading.Lock()
        self.batches = 0
        self.rows = 0
        self.wait_time_s = 0.0
        self.total_time_s = 0.0
        self.coalesced_uploads = 0
        self.per_field_uploads = 0
        self.io_retries = 0
        self.rowgroups_quarantined = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.shm_batches = 0
        self.shm_fallback_batches = 0
        self.wire_bytes_copied_per_batch = 0.0
        self.device_decode_batches = 0
        self.device_fallback_batches = 0
        self.unpack_cache_evictions = 0

    def add(self, **deltas):
        """Add keyword deltas to counter fields atomically (one lock hold)."""
        with self._lock:
            for name, delta in deltas.items():
                if name not in self._FIELDS:
                    raise AttributeError('unknown LoaderStats field {!r}'
                                         .format(name))
                setattr(self, name, getattr(self, name) + delta)

    def mirror(self, **values):
        """Set absolute values for mirrored counters atomically (reader/pool
        counters copied into the loader surface)."""
        with self._lock:
            for name, value in values.items():
                if name not in self._FIELDS:
                    raise AttributeError('unknown LoaderStats field {!r}'
                                         .format(name))
                setattr(self, name, value)

    @property
    def input_stall_fraction(self):
        with self._lock:
            if self.total_time_s <= 0:
                return 0.0
            return min(1.0, self.wait_time_s / self.total_time_s)

    def as_dict(self):
        with self._lock:
            snapshot = {name: getattr(self, name) for name in self._FIELDS}
        stall = (min(1.0, snapshot['wait_time_s'] / snapshot['total_time_s'])
                 if snapshot['total_time_s'] > 0 else 0.0)
        snapshot['wait_time_s'] = round(snapshot['wait_time_s'], 4)
        snapshot['total_time_s'] = round(snapshot['total_time_s'], 4)
        snapshot['input_stall_fraction'] = round(stall, 4)
        return snapshot


class JaxDataLoader(object):
    """Iterates pytrees (dicts) of device-sharded arrays assembled from a Reader.

    :param reader: a petastorm_tpu Reader (row, batched, or NGram). An NGram reader
        yields sequence batches: each window field arrives as
        ``(batch, ngram.length, *field_shape)`` (windows are the batch axis — shuffle
        buffer, padding and sharding all operate on windows), ready for
        ``partition_spec={'field': PartitionSpec('data', 'seq')}`` sequence sharding.
        Delivery accounting counts windows, so ``state_dict`` checkpoints NGram
        streams exactly like row streams (VERDICT r3 item 4).
    :param batch_size: rows per emitted batch **on this host**. With a multi-host mesh the
        global batch is ``batch_size * jax.process_count()``.
    :param mesh: optional ``jax.sharding.Mesh``; None = single default device.
    :param partition_spec: ``PartitionSpec`` for every batch array (default: batch axis
        over the mesh's first axis), or a dict ``{field: PartitionSpec}`` — named fields
        get their spec, the rest the batch-axis default. Accepts any layout for TP/SP
        consumers (e.g. ``{'tokens': P('data', 'seq')}`` for sequence-sharded batches).
    :param shuffling_queue_capacity: >0 enables a RandomShufflingBuffer of that capacity.
    :param min_after_retrieve: decorrelation floor (default capacity//2).
    :param pad_ragged: {field: padded_shape_tuple} — ragged fields are zero-padded to the
        given per-row shape and an ``<field>_len`` int32 column is emitted. Required for
        any variable-shape field reaching the device (XLA static shapes;
        SURVEY.md §7.3 pad-and-mask).
    :param prefetch: device batches kept in flight (2 = double buffering).
    :param drop_last: drop the final partial batch (keeps shapes static under jit).
    :param device_put: False returns host numpy batches (debugging / CPU consumers).
    :param coalesce_fields: pack every field of a batch into ONE host buffer and
        issue ONE host->device transfer per batch, unpacking on device inside a
        cached jitted program (slice + bitcast — fused view-level work). On a
        tunneled/high-RTT link each transfer pays a dispatch round trip, so a
        3-field batch costs 3 RTTs per batch without this (VERDICT r4 item 2:
        "coalesce device_put across fields"). Default ``None`` = auto: enabled
        on accelerator backends, disabled on CPU, where ``device_put`` is a
        near-free buffer share and the on-device unpack would be a pure host
        memcpy tax (measured ~8x per-batch overhead). Applies on the
        single-device path (``mesh=None``) when every field has a native-endian
        numeric dtype; anything else silently uses the per-field path. JAX
        exposes no user pinned-host-memory control, so a pinned staging buffer
        is not available to us — the packed buffer is the closest equivalent
        (one contiguous region, reused layout).
    :param device_transforms: ``{field: DeviceTransform}`` on-device augment
        chains (crop/flip/normalize) for raw-shipped image fields — requires a
        reader built with ``device_decode_fields`` (docs/performance.md
        "Device-resident decode tail").
    :param device_buffer_depth: device batches the decode tail may dispatch
        ahead of the train step (the prefetch-to-device ring; only meaningful
        with ``device_decode_fields``).
    :param metrics_port: attach a live scrape endpoint over
        :meth:`telemetry_snapshot` (``/metrics`` Prometheus text with the SLO
        gauges refreshed per scrape, ``/healthz``, ``/vars``); ``0`` binds an
        ephemeral port (``metrics_url`` names it), None (default) serves
        nothing — docs/observability.md "Live metrics plane".
    :param slo_policy: the input-efficiency SLO evaluated by
        :meth:`efficiency_report` (an
        :class:`~petastorm_tpu.telemetry.slo.SloPolicy`, a float target, or
        None = the default 0.9 target).
    :param incidents: arm the incident autopsy plane at the loader layer
        (``True`` or an
        :class:`~petastorm_tpu.telemetry.incident.IncidentPolicy`) — an SLO
        breach of the WHOLE pipeline (training loop starved) or a breaker
        trip captures a black-box bundle over the merged loader+reader
        telemetry; when the reader already carries its own recorder
        (``make_reader(incidents=...)``) the loader reuses it instead of
        building a second one — docs/observability.md "Incident autopsy
        plane".
    :param history: arm the longitudinal observatory at the loader layer
        (docs/observability.md "Longitudinal observatory"): one ``owner:
        'loader'`` run record (whole-pipeline rows/s, efficiency, stage
        shares) is appended at :meth:`stop`, and a live regression sentinel
        watches the training loop's own rows/s + wait-share series, firing a
        ``perf_regression`` incident on a mid-run collapse. ``True``
        (default policy), a store path string, or a
        :class:`~petastorm_tpu.telemetry.history.HistoryPolicy`. With no
        explicit path the loader records into the reader's store
        (``make_reader(history=...)``); ``True`` with an unarmed reader
        warns and disables (the loader has no dataset home of its own).
    """

    def __init__(self, reader, batch_size, mesh=None, partition_spec=None,
                 shuffling_queue_capacity=0, min_after_retrieve=None, seed=None,
                 pad_ragged=None, prefetch=2, drop_last=True, device_put=True,
                 coalesce_fields=None, device_transforms=None,
                 device_buffer_depth=2, metrics_port=None, slo_policy=None,
                 incidents=None, history=None):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
        self.reader = reader
        self.batch_size = batch_size
        self.stats = LoaderStats()
        # Loader-side stage telemetry (docs/observability.md): shuffle_wait /
        # collate / h2d histograms; telemetry_snapshot() merges in the reader's
        # cross-process view. PETASTORM_TPU_TELEMETRY_JSONL streams periodic
        # snapshots from the consumer loop.
        from petastorm_tpu.telemetry import MetricsRegistry
        from petastorm_tpu.telemetry.export import logger_from_env
        self.telemetry = MetricsRegistry()
        self._telemetry_jsonl = logger_from_env()
        # Input-efficiency SLO over the whole pipeline (docs/observability.md
        # "Efficiency SLOs"): shuffle_wait is the loader's primary starvation
        # stage; breach events are edge-triggered inside the tracker and ride
        # the loader's JSONL log when one is armed.
        from petastorm_tpu.telemetry.slo import (SloTracker,
                                                 resolve_slo_policy, slo_clock)
        self._started_at = slo_clock()
        self._slo = SloTracker(resolve_slo_policy(slo_policy),
                               jsonl=self._telemetry_jsonl)
        self._metrics_server = None
        self._mesh = mesh
        self._partition_spec = partition_spec
        self._pad_ragged = dict(pad_ragged or {})
        self._prefetch = max(1, prefetch)
        self._drop_last = drop_last
        self._device_put = device_put
        self._seed = seed
        self._shuffling_queue_capacity = shuffling_queue_capacity
        self._min_after_retrieve = min_after_retrieve
        self._sharding = None
        self._in_iter = False
        self._error = None
        self._queue = None
        self._producer = None
        self._stop_event = threading.Event()
        # Delivery-exact checkpoint accounting: the producer appends
        # [item_id, rows_pending] per reader chunk (FIFO order == emission order for the
        # no-shuffle path); the consumer decrements as batches are yielded and marks an
        # item delivered only when every one of its rows reached the training loop.
        self._delivery_fifo = collections.deque()
        self._fifo_lock = threading.Lock()
        self._delivery_supported = None
        self._epochs_delivered = 0
        self._delivered_by_epoch = {}
        self._spec_keys_checked = False
        self._scan_stream_used = False
        # Sample-lineage step stamping (docs/observability.md "Sample
        # lineage"): the reader's recorder (when armed) learns which
        # training step each manifest record lands under — cumulative across
        # re-iterations, like stats.batches.
        self._lineage_steps = 0
        self._scan_stream_programs = {}
        self._scan_stream_cache_warned = False
        self._coalesce_fields = coalesce_fields
        self._unpack_programs = collections.OrderedDict()
        # Device-resident decode tail (docs/performance.md): when the reader
        # ships raw codec payloads, this stage finishes decode (and augment)
        # as jitted device kernels after the upload; on CPU backends it
        # decodes on the host byte-identically.
        self._device_buffer_depth = max(1, int(device_buffer_depth))
        device_fields = frozenset(getattr(reader, 'device_decode_fields', None)
                                  or ())
        if device_fields:
            from petastorm_tpu.parallel.device_stage import DeviceDecodeStage
            self._device_stage = DeviceDecodeStage(reader, device_transforms,
                                                   device_buffer_depth,
                                                   device_put)
        else:
            if device_transforms:
                raise ValueError('device_transforms requires a reader built '
                                 'with device_decode_fields')
            self._device_stage = None
        # Closed-loop autotuning (docs/autotuning.md): when the reader carries
        # a controller (make_reader(autotune=...)), contribute the loader's
        # own knob — the shuffle-buffer fill threshold — to its catalog so the
        # one controller tunes the whole pipeline. _active_buffer hands the
        # live buffer to the knob's apply.
        self._active_buffer = None
        controller = getattr(reader, '_autotune', None)
        if controller is not None:
            from petastorm_tpu.autotune.knobs import build_loader_knobs
            for knob in build_loader_knobs(self):
                controller.catalog.add(knob)
        # Incident autopsy plane (docs/observability.md "Incident autopsy
        # plane"): a reader-owned recorder is reused (the loader's SLO edge
        # joins its triggers); otherwise incidents= builds a loader-owned one
        # over the merged whole-pipeline evidence.
        from petastorm_tpu.telemetry.incident import resolve_incident_policy
        self._incidents = getattr(reader, '_incidents', None)
        self._owns_incidents = False
        incident_policy = resolve_incident_policy(incidents)
        if incident_policy is not None and self._incidents is None:
            from petastorm_tpu.resilience import default_board
            from petastorm_tpu.telemetry.incident import (
                IncidentRecorder, default_incident_home)
            self._incidents = IncidentRecorder(
                default_incident_home(None), incident_policy,
                registry=self.telemetry)
            self._owns_incidents = True
            self._incidents.add_source('metrics', self.telemetry_snapshot)
            self._incidents.add_source(
                'slo', lambda: self._evaluate_slo(self.telemetry_snapshot()))
            self._incidents.add_source(
                'config', lambda: {'batch_size': self.batch_size,
                                   'prefetch': self._prefetch,
                                   'drop_last': self._drop_last,
                                   'reader': type(reader).__name__})
            default_board().observe_transitions(
                self._incidents.on_breaker_transition)
        if self._incidents is not None:
            self._slo.observe_breaches(self._on_slo_breach)
        # Longitudinal observatory at the loader layer (docs/observability.md
        # "Longitudinal observatory"): an owner='loader' run record of the
        # WHOLE pipeline at stop(), plus a loader-side regression sentinel
        # over the training loop's own goodput series. With no explicit path
        # the record lands in the reader's store (same journal, two owners).
        from petastorm_tpu.telemetry.history import resolve_history_policy
        self._history = None
        self._history_policy = resolve_history_policy(history)
        self._history_written = False
        self._sentinel = None
        if self._history_policy is not None:
            from petastorm_tpu.telemetry.history import RunHistorian
            from petastorm_tpu.telemetry.sentinel import (
                RegressionSentinel, resolve_sentinel_policy)
            history_path = self._history_policy.path
            if history_path is None:
                reader_history = getattr(reader, '_history', None)
                history_path = getattr(reader_history, 'path', None)
            if history_path is not None:
                self._history = RunHistorian(history_path,
                                             self._history_policy,
                                             registry=self.telemetry)
            else:
                warnings.warn(
                    'JaxDataLoader(history=...) has no store path: pass a '
                    'path/HistoryPolicy(path=...), or arm the reader with '
                    'make_reader(history=...) so the loader can record into '
                    'its store — recording disabled for this run')
            sentinel_policy = resolve_sentinel_policy(
                self._history_policy.sentinel)
            if sentinel_policy is not None:
                self._sentinel = RegressionSentinel(
                    sentinel_policy, owner='loader',
                    registry=self.telemetry, incidents=self._incidents,
                    dataset_token=getattr(reader, 'dataset_token', None))
                if (self._incidents is not None
                        and getattr(reader, '_sentinel', None) is None):
                    # the bundle's 'sentinel' evidence slot belongs to the
                    # reader's sentinel when one is armed there
                    self._incidents.add_source('sentinel',
                                               self._sentinel.report)
        # Live metrics plane (docs/observability.md): one scrape endpoint
        # over the whole-pipeline snapshot; closed by stop(). Started LAST —
        # a constructor raise after binding would leak the port and serve a
        # half-built loader (same ordering contract as Reader.__init__).
        if metrics_port is not None:
            from petastorm_tpu.telemetry.http_exporter import MetricsHttpServer
            self._metrics_server = MetricsHttpServer(
                snapshot_fn=self._scrape_snapshot,
                health_fn=lambda: {'batches': self.stats.batches,
                                   'rows': self.stats.rows},
                port=int(metrics_port))
            self._metrics_server.start()

    # ------------------------------------------------------------------ sharding

    def _resolve_sharding(self):
        return resolve_sharding(self._mesh, self._partition_spec, self._device_put)

    # ------------------------------------------------------------------ iteration

    def __iter__(self):
        if self._in_iter:
            raise RuntimeError('Concurrent iteration of a JaxDataLoader is not allowed '
                               '(reference semantics: pytorch.py:98-123)')
        if self._producer is not None and self._producer.is_alive():
            # Previous iteration broken off early: stop and JOIN the old producer before
            # touching queue/stop state, or it would write stale batches into the new
            # iteration's queue.
            self._stop_event.set()
            self._drain_queue()
            self._producer.join(timeout=30)
            if self._producer.is_alive():
                raise RuntimeError('Previous producer thread did not stop')
        if self.stats.batches and getattr(self.reader, 'last_row_consumed', False):
            # Re-iteration after full consumption: reset the reader like the reference's
            # LoaderBase (pytorch.py:104-123).
            self.reader.reset()
        self._in_iter = True
        self._error = None
        # Fresh Event per iteration: a (joined or straggling) old producer keeps its own
        # already-set event and can never interfere with the new run.
        self._stop_event = threading.Event()
        self._queue = queue.Queue(self._prefetch)
        self._sharding = self._resolve_sharding()
        # Stale pending entries from an abandoned previous iteration reference a dead
        # stream; dropping them leaves their items undelivered, so a resume re-serves
        # those rows instead of losing them.
        self._delivery_fifo.clear()
        self._producer = threading.Thread(target=self._produce,
                                          args=(self._queue, self._stop_event),
                                          daemon=True,
                                          name='petastorm-tpu-loader-producer')
        self._producer.start()
        try:
            last_emit = time.monotonic()
            while True:
                wait_start = time.monotonic()
                with _trace_span('petastorm_tpu.loader.wait_input'):
                    item = self._queue.get()
                now = time.monotonic()
                if item is _END:
                    if self._error is not None:
                        raise self._error
                    self._mark_delivered(None)  # drop_last / buffer-drain leftovers
                    return
                batch, local_rows = item
                self.stats.add(wait_time_s=now - wait_start,
                               total_time_s=now - last_emit,
                               batches=1, rows=local_rows)
                # shuffle_wait: time the training loop sat blocked on the input
                # pipeline for this batch — the stage the stall fraction sums
                # (clocked on monotonic, so the timeline leg back-dates)
                self.observe_traced('shuffle_wait', now - wait_start)
                if self._telemetry_jsonl is not None and self._telemetry_jsonl.due():
                    # one snapshot serves both legs: the periodic interval
                    # line AND the SLO evaluation (whose ok->breach
                    # transition appends its own slo_breach line; the
                    # regression sentinel rides the same evaluation)
                    snapshot = self.telemetry_snapshot()
                    self._evaluate_slo(snapshot)
                    self._telemetry_jsonl.emit(snapshot,
                                               event='loader_interval')
                elif self._sentinel is not None:
                    # no JSONL armed: the sentinel still needs its windows —
                    # one float compare per batch between them
                    from petastorm_tpu.telemetry.slo import slo_clock
                    if self._sentinel.due(slo_clock() - self._started_at):
                        self._evaluate_slo(self.telemetry_snapshot())
                last_emit = now
                self._mark_delivered(local_rows)
                self._lineage_steps += 1
                lineage = getattr(self.reader, '_lineage', None)
                if lineage is not None:
                    # step-stamp the audit plane: manifest records written
                    # from here on carry this training step
                    lineage.stamp_step(self._lineage_steps)
                yield batch
        finally:
            self._stop_event.set()
            self._in_iter = False
            self._drain_queue()

    def _drain_queue(self, _empty=queue.Empty, _is_finalizing=sys.is_finalizing):
        # Bound at definition time and guarded: this runs from generator finalizers,
        # which can fire during interpreter shutdown after module globals (ours AND
        # the stdlib queue module's Empty) are cleared — `raise Empty` inside
        # queue.get then raises TypeError. Draining is pointless at shutdown anyway.
        if self._queue is None or _is_finalizing():
            return
        try:
            while True:
                self._queue.get_nowait()
        except _empty:
            pass

    # ------------------------------------------------------------------ producer

    def _make_buffer(self):
        if self._shuffling_queue_capacity and self._shuffling_queue_capacity > 0:
            min_after = self._min_after_retrieve
            if min_after is None:
                min_after = self._shuffling_queue_capacity // 2
            return RandomShufflingBuffer(self._shuffling_queue_capacity, min_after,
                                         seed=self._seed)
        return NoopShufflingBuffer()

    def _produce(self, out_queue, stop_event):
        try:
            buffer = self._make_buffer()
            self._active_buffer = buffer
            for columns in self._reader_chunks():
                # Feed the buffer in batch_size slices so a whole-rowgroup chunk (the
                # iter_columnar fast path) cannot blow past the shuffling buffer's
                # configured capacity; slices of ndarrays are views, so this is cheap.
                for part in _iter_column_slices(columns, self.batch_size):
                    buffer.add_many(part)
                    while buffer.can_retrieve(self.batch_size):
                        if stop_event.is_set():
                            return
                        self._emit(buffer.retrieve(self.batch_size), out_queue, stop_event)
                if stop_event.is_set():
                    return
            buffer.finish()
            while buffer.can_retrieve(self.batch_size) and not stop_event.is_set():
                batch = buffer.retrieve(self.batch_size)
                if self._batch_cols_rows(batch) < self.batch_size and self._drop_last:
                    break
                self._emit(batch, out_queue, stop_event)
        except Exception as exc:  # noqa: BLE001 - surface in consumer
            if not stop_event.is_set():
                self._error = exc
        finally:
            self._put(_END, out_queue, stop_event)

    @staticmethod
    def _batch_cols_rows(columns):
        from petastorm_tpu.workers.serializers import _columns_num_rows
        return _columns_num_rows(columns)

    def _reader_chunks(self):
        """Yield sanitized columnar chunks from the reader, tracking delivery when the
        columnar fast path provides item identity."""
        try:
            for columns, num_rows, item_id in iter_reader_chunks(
                    self.reader, accum_rows=self.batch_size, include_empty=True):
                if item_id is None:
                    self._delivery_supported = False
                else:
                    self._delivery_supported = self._delivery_supported is not False
                    with self._fifo_lock:
                        self._delivery_fifo.append([item_id, num_rows])
                if num_rows:
                    yield self._sanitize(columns)
        finally:
            self._sync_resilience_stats()

    def _sync_resilience_stats(self):
        """Mirror the reader's retry/quarantine counters — and the zero-copy
        data-plane counters (cache hits, shm transport, wire bytes copied) — into
        LoaderStats so training jobs watching only the loader still see input
        degradation (docs/robustness.md, docs/performance.md)."""
        mirrored = {}
        retries = getattr(self.reader, 'io_retries', None)
        if retries is not None:
            mirrored['io_retries'] = retries
        ledger = getattr(self.reader, 'quarantine', None)
        if ledger is not None:
            mirrored['rowgroups_quarantined'] = len(ledger)
        try:
            diag = getattr(self.reader, 'diagnostics', None)
        except Exception:  # noqa: BLE001 - wrapper readers may not expose it
            diag = None
        if isinstance(diag, dict):
            for key in ('cache_hits', 'cache_misses', 'shm_batches',
                        'shm_fallback_batches'):
                if key in diag:
                    mirrored[key] = diag[key]
            # wire_bytes_copied_per_batch: a TRUE running mean over the whole
            # stream, from the pool's wire_bytes_copied histogram (sum/count) —
            # the diagnostics scalar is a last-writer value that misreports
            # multi-pool / mixed-transport runs.
            hist = (diag.get('telemetry', {}).get('histograms', {})
                    .get('wire_bytes_copied'))
            if hist and hist.get('count'):
                mirrored['wire_bytes_copied_per_batch'] = round(
                    float(hist['sum']) / int(hist['count']), 1)
            elif 'wire_bytes_copied_per_batch' in diag:
                mirrored['wire_bytes_copied_per_batch'] = \
                    diag['wire_bytes_copied_per_batch']
        if mirrored:
            self.stats.mirror(**mirrored)

    def _sanitize(self, columns):
        # collate stage: host batch assembly — dtype sanitization + ragged padding
        collate_start = time.perf_counter()
        passthrough = frozenset()
        stage = self._device_stage
        if stage is not None:
            # host-mode device fields decode HERE (before sanitize, so
            # pad_ragged still applies to them); device-mode fields pass
            # through sanitize raw and decode on chip in _emit
            dd_start = time.perf_counter()
            columns, decoded_any = stage.sanitize_decode(columns)
            if decoded_any:
                self.stats.add(device_fallback_batches=1)
                self.observe_traced('device_decode',
                                    time.perf_counter() - dd_start,
                                    start_pc=dd_start)
            passthrough = stage.passthrough_names
        out = sanitize_columns(columns, self._pad_ragged, self._device_put,
                               passthrough=passthrough)
        self.observe_traced('collate', time.perf_counter() - collate_start,
                            start_pc=collate_start)
        return out

    def _emit(self, columns, out_queue, stop_event):
        local_rows = self._batch_cols_rows(columns)
        if self._device_put:
            import jax
            stage = self._device_stage
            recipe = None
            prepare_s = 0.0
            if stage is not None and not stage.host_mode:
                # device decode tail, host half: pack/inflate raw payloads
                # into upload-ready arrays + the static program recipe
                prep_start = time.perf_counter()
                columns, recipe = stage.prepare(columns, self._mesh)
                prepare_s = time.perf_counter() - prep_start
            sharding = self._sharding
            if isinstance(sharding, FieldShardings) and not self._spec_keys_checked:
                self._spec_keys_checked = True
                sharding.check_unused(columns.keys())
            h2d_start = time.perf_counter()
            with _trace_span('petastorm_tpu.loader.h2d'):
                if self._mesh is not None:
                    batch = {name: jax.make_array_from_process_local_data(
                                 sharding_for_field(sharding, name), col)
                             for name, col in columns.items()}
                    self.stats.add(per_field_uploads=1)
                elif (self._coalesce_enabled()
                      and (layout := coalescible_layout(columns)) is not None):
                    batch = self._put_coalesced(columns, sharding, layout)
                    self.stats.add(coalesced_uploads=1)
                else:
                    batch = jax.device_put(columns, sharding)
                    self.stats.add(per_field_uploads=1)
            self.observe_traced('h2d', time.perf_counter() - h2d_start,
                                start_pc=h2d_start)
            if recipe:
                # device half: the jitted decode+augment program (async
                # dispatch — the train step synchronizes), then the
                # prefetch-to-device ring bound. An EMPTY recipe (every
                # device field host_only, already decoded in _sanitize) must
                # not count as a device decode — the stats contract is that a
                # capture can prove which path ran.
                finish_start = time.perf_counter()
                with _trace_span('petastorm_tpu.loader.device_decode'):
                    batch = stage.finish(batch, recipe)
                self.stats.add(device_decode_batches=1)
                self.observe_traced(
                    'device_decode',
                    prepare_s + time.perf_counter() - finish_start)
                waited = stage.throttle(batch)
                if waited:
                    self.observe_traced('d2d_wait', waited)
            elif (stage is not None and stage.host_mode
                  and stage.has_transforms):
                # host-mode backends still apply the declared augment chains
                # (same jitted math, post-upload) — a CPU fallback run must
                # train on the same data an accelerator run would
                t_start = time.perf_counter()
                batch = stage.apply_transforms(batch)
                self.observe_traced('device_decode',
                                    time.perf_counter() - t_start)
        else:
            batch = columns
        # Host-local row count travels alongside: with a multi-process mesh the device
        # array's leading dim is the GLOBAL batch, but stats and delivery accounting are
        # per-host.
        self._put((batch, local_rows), out_queue, stop_event)

    def _coalesce_enabled(self):
        """Resolve the auto default once: coalescing pays on accelerators
        (fewer link round trips) and costs on CPU (pure memcpy tax)."""
        if self._coalesce_fields is None:
            import jax
            self._coalesce_fields = jax.devices()[0].platform != 'cpu'
        return self._coalesce_fields

    def _put_coalesced(self, columns, sharding, layout):
        """ONE H2D transfer for the whole batch: pack every field's bytes into a
        single uint8 buffer, upload it, and unpack on device through a cached
        jitted slice+bitcast program (see the ``coalesce_fields`` docstring).
        ``layout`` is the caller's ``coalescible_layout`` guard result."""
        import jax
        names = [name for name, _, _ in layout]
        parts = [columns[name].view(np.uint8).ravel() for name in names]
        buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
        dev_buf = jax.device_put(buf, sharding)
        # Small LRU: layouts are stable per stream, but a long-lived loader
        # iterating readers with varying field sets must not grow this without
        # bound. A hit moves the program to the MRU end; evictions are counted
        # in LoaderStats so layout churn is observable, never silent.
        programs = self._unpack_programs
        x64 = bool(jax.config.jax_enable_x64)
        key = (layout, x64)
        program = programs.get(key)
        if program is None:
            if len(programs) >= _UNPACK_CACHE_MAX:
                programs.popitem(last=False)
                self.stats.add(unpack_cache_evictions=1)
            program = jax.jit(_make_unpack(layout, x64))
            programs[key] = program
        else:
            programs.move_to_end(key)
        return program(dev_buf)

    def _put(self, item, out_queue, stop_event):
        while not stop_event.is_set():
            try:
                out_queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue
        if item is _END:
            try:
                out_queue.put_nowait(_END)
            except queue.Full:
                pass

    # ------------------------------------------------------------ compiled streaming

    def scan_stream(self, step_fn, carry, chunk_batches=32, seed=None):
        """Stream the reader through compiled chunk programs: accumulate
        ``chunk_batches`` batches of host rows, upload them as ONE transfer, and run
        every train step of the chunk inside ONE ``lax.scan`` dispatch.

        The dispatch-bound streaming configuration for larger-than-HBM datasets: the
        per-batch Python dispatch + small-transfer overhead of ``__iter__`` (which
        dominates small-model streaming, docs/performance.md) collapses to one
        host->device transfer and one XLA program launch per ``chunk_batches``
        batches, while memory stays bounded at one chunk (vs
        ``InMemJaxLoader.scan_epochs``, which needs the whole dataset resident).
        No reference analog (petastorm crosses into Python per batch everywhere).

        Rows are shuffled within each chunk (seeded numpy permutation on the host;
        combine with ``shuffle_row_groups``/``shuffle_rows`` on the reader for
        cross-chunk decorrelation). The trailing partial chunk runs through a
        smaller program of the same structure (one extra compile); the final
        sub-batch-size remainder is dropped (static shapes).

        With a ``mesh`` the chunk uploads as a globally-sharded array — each batch
        inside the scan keeps the loader's ``partition_spec`` sharding (the scan
        axis is replicated), so the compiled chunk program trains dp/sp-sharded
        exactly like the ``__iter__`` path, minus the per-batch dispatch. Run it
        under ``with mesh:`` (or pre-shard the carry) so the carry's shardings
        resolve. ``batch_size`` stays the HOST-local row count with a
        multi-process mesh, matching ``__iter__``.

        :param step_fn: ``step_fn(carry, batch) -> (carry, aux)`` — standard
            ``lax.scan`` body over dicts of ``(batch_size, ...)`` arrays.
        :param carry: initial carry pytree.
        :param chunk_batches: batches per compiled chunk (chunk rows =
            ``chunk_batches * batch_size``).
        :param seed: within-chunk shuffle seed; None disables the in-chunk shuffle.
        :return: ``(carry, aux_chunks)`` — aux stacked per chunk, in stream order.
        """
        import jax
        if self._shuffling_queue_capacity:
            raise ValueError('scan_stream has its own in-chunk shuffle; construct '
                             'the loader with shuffling_queue_capacity=0')
        if chunk_batches < 1:
            raise ValueError('chunk_batches must be >= 1')
        if not self._device_put:
            raise ValueError('scan_stream compiles device programs; it does not '
                             'support device_put=False (use __iter__ for host batches)')
        if not self._drop_last:
            raise ValueError('scan_stream always drops the sub-batch-size remainder '
                             '(static shapes); construct the loader with '
                             'drop_last=True to make that explicit, or use __iter__ '
                             'to see every row')
        if reader_may_be_infinite(self.reader):
            raise ValueError('scan_stream runs to stream end and cannot consume an '
                             'infinite reader (num_epochs=None); give the reader a '
                             'finite num_epochs and call scan_stream per pass')
        if self._device_stage is not None and (
                not self._device_stage.host_mode
                or self._device_stage.has_transforms):
            raise ValueError('scan_stream does not support on-accelerator '
                             'device_decode_fields (raw payloads cannot pack '
                             'into chunk programs) or device_transforms (the '
                             'chunk path has no augment stage — silently '
                             'training un-augmented would be worse than '
                             'refusing); use __iter__, or on a CPU backend '
                             'drop the transforms')
        if self._in_iter:
            raise RuntimeError('scan_stream cannot run while __iter__ is active: '
                               'both would consume the same reader')
        if self._producer is not None and self._producer.is_alive():
            # An abandoned __iter__ left its producer prefetching from the reader:
            # stop and join it, exactly like a fresh __iter__ would, so the stream
            # has one consumer.
            self._stop_event.set()
            self._drain_queue()
            self._producer.join(timeout=30)
            if self._producer.is_alive():
                raise RuntimeError('Previous producer thread did not stop')
        if getattr(self.reader, 'last_row_consumed', False):
            # Mirror __iter__'s re-iteration contract: a fully consumed reader resets
            # for the next pass — without this, a second scan_stream call would
            # silently return (carry, []) with zero training done.
            self.reader.reset()
        # Chunk arrays carry a leading scan axis: replicate it (PartitionSpec
        # (None, *batch_spec)) so each scan step's batch keeps the loader's batch
        # sharding while every device sees every step of its shard.
        sharding = _chunk_sharding(self._resolve_sharding())
        self._scan_stream_used = True  # bypasses delivery accounting: see state_dict
        batch_size = self.batch_size
        # Program cache on the instance: a fresh per-call dict would re-trace and
        # re-compile the chunk program every call (one call per epoch is the intended
        # pattern), silently billing full XLA compiles to every epoch. Keyed on
        # step_fn IDENTITY — pass a stable function object; fresh closures per call
        # recompile, and past the cap the oldest program is evicted (warned once).
        programs = self._scan_stream_programs

        def run_chunk(carry, columns, n_batches, chunk_index):
            usable = n_batches * batch_size
            if seed is not None:
                perm = np.random.RandomState(
                    (seed + chunk_index) % (2 ** 31)).permutation(usable)
                columns = {name: col[:usable][perm] for name, col in columns.items()}
            else:
                columns = {name: col[:usable] for name, col in columns.items()}
            chunk = {name: np.ascontiguousarray(
                         col.reshape((n_batches, batch_size) + col.shape[1:]))
                     for name, col in columns.items()}
            h2d_start = time.perf_counter()
            with _trace_span('petastorm_tpu.loader.scan_stream.h2d'):
                if self._mesh is not None:
                    # Same upload contract as __iter__'s mesh path: host-local
                    # chunk rows assemble into the global sharded chunk array
                    # (single- and multi-process meshes alike).
                    chunk = {name: jax.make_array_from_process_local_data(
                                 sharding_for_field(sharding, name), col)
                             for name, col in chunk.items()}
                elif (self._coalesce_enabled()
                      and (layout := coalescible_layout(chunk)) is not None):
                    # one transfer per chunk instead of one per field
                    chunk = self._put_coalesced(chunk, sharding, layout)
                else:
                    chunk = jax.device_put(chunk, sharding)
            self.observe_traced('h2d', time.perf_counter() - h2d_start,
                                start_pc=h2d_start)
            key = (step_fn, n_batches)
            if key not in programs:
                @jax.jit
                def chunk_program(carry, chunk):
                    return jax.lax.scan(step_fn, carry, chunk)
                if len(programs) >= _SCAN_STREAM_CACHE_MAX:
                    # Unbounded growth would pin every evicted closure's captured
                    # scope + compiled executable for the loader's lifetime.
                    if not self._scan_stream_cache_warned:
                        self._scan_stream_cache_warned = True
                        import warnings
                        warnings.warn(
                            'scan_stream compiled more than {} distinct (step_fn, '
                            'chunk-shape) programs; pass a stable step_fn object to '
                            'reuse compilations'.format(_SCAN_STREAM_CACHE_MAX))
                    programs.pop(next(iter(programs)))
                programs[key] = chunk_program
            return programs[key](carry, chunk)

        pending = []
        pending_rows = 0
        chunk_rows = chunk_batches * batch_size
        chunk_index = 0
        aux_chunks = []
        for columns in map(self._sanitize,
                           (c for c, n, _ in iter_reader_chunks(
                                self.reader, accum_rows=batch_size,
                                include_empty=False) if n)):
            pending.append(columns)
            pending_rows += self._batch_cols_rows(columns)
            while pending_rows >= chunk_rows:
                merged = _concat_column_chunks(pending)
                head = {name: col[:chunk_rows] for name, col in merged.items()}
                tail = {name: col[chunk_rows:] for name, col in merged.items()}
                carry, aux = run_chunk(carry, head, chunk_batches, chunk_index)
                aux_chunks.append(aux)
                chunk_index += 1
                pending = [tail]
                pending_rows -= chunk_rows
        if pending_rows >= batch_size:
            merged = _concat_column_chunks(pending)
            carry, aux = run_chunk(carry, merged, pending_rows // batch_size,
                                   chunk_index)
            aux_chunks.append(aux)
        self._sync_resilience_stats()
        return carry, aux_chunks

    # ------------------------------------------------------------------ checkpoint

    def _mark_delivered(self, n_rows):
        """Consumer-thread half of delivery accounting: retire ``n_rows`` from the FIFO
        (``None`` = end of stream: everything still pending was dropped by ``drop_last``
        or drained out of the buffer and will never be served in this run)."""
        fifo = self._delivery_fifo
        remaining = n_rows
        while True:
            with self._fifo_lock:
                if not fifo:
                    break
                head = fifo[0]
                if n_rows is None:
                    take = head[1]
                else:
                    if head[1] > 0 and remaining <= 0:
                        break
                    take = min(head[1], remaining)
                head[1] -= take
                if n_rows is not None:
                    remaining -= take
                if head[1] > 0:
                    break
                fifo.popleft()
            self._note_delivered(head[0])

    def _note_delivered(self, item_id):
        epoch, piece, drop = item_id
        self._delivered_by_epoch.setdefault(epoch, set()).add((piece, drop))
        items_per_epoch = getattr(self.reader, 'items_per_epoch', None)
        if not items_per_epoch:
            return
        while (len(self._delivered_by_epoch.get(self._epochs_delivered, ()))
               >= items_per_epoch):
            del self._delivered_by_epoch[self._epochs_delivered]
            self._epochs_delivered += 1

    def state_dict(self):
        """Delivery-exact resumable read position: an item (rowgroup x drop-partition)
        counts as consumed only once every one of its rows was handed to the training
        loop — rows still inside the prefetch queue, the producer, or a drained buffer
        are NOT counted and will be re-served on resume (at-least-once; a partially
        delivered item is re-read whole). Rebuild the reader with the same arguments
        plus ``resume_state=state`` and wrap it in a fresh loader to continue.

        With a shuffling buffer, emission order differs from ingest order, so per-item
        attribution is only trustworthy when nothing is pending — checkpoint at a stream
        boundary (after the iterator is exhausted) in that case."""
        if self._delivery_supported is False:
            raise ValueError('state_dict requires a Reader with the columnar fast path '
                             '(iter_columnar)')
        if self._scan_stream_used:
            raise ValueError('state_dict is not supported after scan_stream (it '
                             'consumes the reader outside the delivery accounting); '
                             'checkpoint with the __iter__ path instead')
        with self._fifo_lock:
            pending = any(entry[1] > 0 for entry in self._delivery_fifo)
        if pending and self._shuffling_queue_capacity:
            raise ValueError('With a shuffling buffer the loader cannot attribute '
                             'in-flight rows to work items; checkpoint after the '
                             'iterator is exhausted (epoch boundary) instead')
        items_per_epoch = getattr(self.reader, 'items_per_epoch', None)
        if items_per_epoch is None:
            raise ValueError('Reader does not support checkpointing')
        return {
            'version': 1,
            'items_per_epoch': items_per_epoch,
            'epochs_consumed': self._epochs_delivered,
            'consumed_by_epoch': {
                epoch - self._epochs_delivered: sorted(ids)
                for epoch, ids in self._delivered_by_epoch.items()},
        }

    # -------------------------------------------------------------- runtime knobs

    def set_prefetch(self, depth):
        """Runtime-adjust the prefetch queue depth (the autotune knob surface,
        docs/autotuning.md): applied to the LIVE queue — ``maxsize`` moves
        under the queue's own mutex and parked producers are woken, so a raise
        takes effect immediately and a shrink drains as the consumer pops.
        Returns the applied value."""
        depth = max(1, int(depth))
        self._prefetch = depth
        out_queue = self._queue
        if out_queue is not None:
            with out_queue.mutex:
                out_queue.maxsize = depth
                out_queue.not_full.notify_all()
        return depth

    @property
    def prefetch(self):
        """The current prefetch queue depth."""
        return self._prefetch

    def set_device_buffer_depth(self, depth):
        """Runtime-adjust the device decode tail's prefetch-to-device ring
        depth (autotune knob; no-op clamp when the loader has no device
        stage). Returns the applied value."""
        stage = self._device_stage
        if stage is None:
            return max(1, int(depth))
        return stage.set_depth(depth)

    @property
    def device_buffer_depth(self):
        """The device decode tail's ring depth (construction value when no
        stage exists)."""
        stage = self._device_stage
        if stage is None:
            return self._device_buffer_depth
        return stage.depth

    # ------------------------------------------------------------------ telemetry

    def observe_traced(self, stage, dur_s, start_pc=None):
        """One loader-stage measurement, both legs: the loader's registry
        histogram and (when the flight recorder is armed) a timeline span.
        ``start_pc`` is the ``perf_counter`` start; None back-dates by the
        measured duration (for stages clocked on a different timebase, e.g.
        the monotonic-clocked ``shuffle_wait``). The stage name is validated
        against the spans.py catalog by pipecheck's telemetry-names rule."""
        self.telemetry.observe(stage, dur_s)
        if _flight.trace_enabled():
            if start_pc is None:
                start_pc = time.perf_counter() - dur_s
            _flight.trace_complete(stage, start_pc, dur_s)

    def telemetry_snapshot(self):
        """One JSON-safe telemetry snapshot covering the WHOLE pipeline: the
        loader's own stages (shuffle_wait/collate/h2d) merged with the reader's
        cross-process snapshot (worker stages + pool registry). Feed it to
        ``petastorm_tpu.telemetry.analyze.attribute_bottleneck`` (or the
        ``petastorm-tpu-throughput analyze`` CLI) for the bottleneck report."""
        from petastorm_tpu.telemetry import merge_snapshots
        reader_snapshot_fn = getattr(self.reader, 'telemetry_snapshot', None)
        if reader_snapshot_fn is None:
            return self.telemetry.snapshot()
        return merge_snapshots(self.telemetry.snapshot(), reader_snapshot_fn())

    def _evaluate_slo(self, snapshot):
        from petastorm_tpu.telemetry.slo import slo_clock
        report = self._slo.evaluate(snapshot, slo_clock() - self._started_at,
                                    rows=self.stats.rows,
                                    registry=self.telemetry)
        if self._sentinel is not None:
            # loader-side drift watch over the same cumulative series the
            # SLO report carries (min_window_s enforced by the sentinel)
            self._sentinel.observe(report)
            self._sentinel.export_gauges()
        return report

    def efficiency_report(self):
        """One input-efficiency SLO evaluation over this loader's lifetime
        (docs/observability.md "Efficiency SLOs"): efficiency in [0, 1]
        derived from ``shuffle_wait`` (+ ``d2d_wait``) — the seconds the
        training loop actually sat starved — with goodput-vs-ideal rows/s
        and edge-triggered breach accounting. Evaluated automatically at
        every JSONL interval when ``PETASTORM_TPU_TELEMETRY_JSONL`` is armed,
        and on every ``/metrics`` scrape when ``metrics_port`` is set."""
        return self._evaluate_slo(self.telemetry_snapshot())

    def _scrape_snapshot(self):
        """Per-scrape snapshot: built ONCE, SLO-evaluated, fresh ``slo_*``
        gauges spliced in (same one-snapshot contract as the reader's)."""
        snapshot = self.telemetry_snapshot()
        report = self._evaluate_slo(snapshot)
        gauges = snapshot.setdefault('gauges', {})
        if report['efficiency'] is not None:
            gauges['slo_efficiency'] = report['efficiency']
        gauges['slo_target_efficiency'] = report['target_efficiency']
        if self._sentinel is not None:
            gauges.update(self._sentinel.gauges())
        # the SLO tracker's trailing ring buffer rides the /vars document
        # (a list, not a gauge — the text scrape ignores it)
        snapshot['slo_history'] = report.get('history', [])
        return snapshot

    def _on_slo_breach(self, report):
        """Loader SLO ok→breach edge → one ``slo_breach`` incident (the
        training loop itself sat starved past the target)."""
        if self._incidents is not None:
            self._incidents.trigger(
                'slo_breach',
                args={'efficiency': report.get('efficiency'),
                      'target': report.get('target_efficiency'),
                      'wait_seconds': report.get('wait_seconds'),
                      'layer': 'loader'})

    def incident_report(self):
        """The attached incident recorder's summary (loader-owned or the
        reader's — docs/observability.md "Incident autopsy plane"); None
        when neither layer armed ``incidents``."""
        if self._incidents is None:
            return None
        return self._incidents.report()

    @property
    def metrics_url(self):
        """The live scrape endpoint base URL, or None without
        ``metrics_port`` (docs/observability.md)."""
        if self._metrics_server is None:
            return None
        return self._metrics_server.url

    # ------------------------------------------------------------------ lifecycle

    def _write_history_record(self):
        """Append the loader-layer run record (owner='loader': whole-pipeline
        rows/s + shuffle_wait shares) to the longitudinal store. Idempotent;
        advisory — a run that delivered its batches must not fail over its
        memory."""
        if self._history is None or self._history_written:
            return
        self._history_written = True
        from petastorm_tpu.telemetry.history import (
            build_run_record, fingerprint as _history_fingerprint)
        from petastorm_tpu.telemetry.slo import (efficiency_from_snapshot,
                                                 slo_clock)
        try:
            elapsed = slo_clock() - self._started_at
            snapshot = self.telemetry_snapshot()
            rows = self.stats.rows
            slo_report = efficiency_from_snapshot(snapshot, elapsed,
                                                  rows=rows)
            reader_record = None
            build_reader_record = getattr(self.reader, 'build_history_record',
                                          None)
            if build_reader_record is not None:
                reader_record = build_reader_record()
            fingerprints = dict((reader_record or {}).get('fingerprints', {}))
            fingerprints['loader'] = _history_fingerprint({
                'batch_size': self.batch_size,
                'prefetch': self._prefetch,
                'drop_last': self._drop_last,
                'shuffling_queue_capacity': self._shuffling_queue_capacity,
                'device_stage': self._device_stage is not None})
            record = build_run_record(
                'loader',
                str(getattr(self.reader, 'dataset_token', 'unknown')),
                elapsed, rows, snapshot=snapshot, slo_report=slo_report,
                fingerprints=fingerprints,
                knobs=dict((reader_record or {}).get('knobs', {})),
                incidents=self.incident_report(),
                quarantined=(reader_record or {}).get('quarantined', 0))
            self._history.append(record)
        except Exception:  # noqa: BLE001 - the historian is advisory
            import logging
            logging.getLogger(__name__).warning(
                'could not record this run in the history store',
                exc_info=True)

    def history_report(self):
        """The loader-layer historian's store status; None when built
        without ``history`` (docs/observability.md "Longitudinal
        observatory")."""
        if self._history is None:
            return None
        return self._history.state()

    def stop(self):
        if self._metrics_server is not None:
            self._metrics_server.stop()
        # the loader's run record first: the reader's own stop() below
        # appends its reader-layer record to the same store
        self._write_history_record()
        if self._owns_incidents and self._incidents is not None:
            # reader-owned recorders are the reader's to close
            self._incidents.close()
        self._stop_event.set()
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()


def iter_reader_chunks(reader, accum_rows=4096, include_empty=False):
    """Yield ``(columns_dict, num_rows, item_id_or_None)`` from any reader: the columnar
    fast path when available (item identity preserved for delivery accounting), else
    batched-namedtuple or per-row accumulation (``accum_rows`` per chunk). The single
    reader-dispatch used by both JaxDataLoader and InMemJaxLoader."""
    iter_columnar = getattr(reader, 'iter_columnar', None)
    if iter_columnar is not None:
        # NGram readers ride the same path: iter_columnar yields window-major batches
        # ({field: (num_windows, length, ...)}) carrying the piece's item_id, so
        # delivery accounting counts windows exactly like rows.
        for batch in iter_columnar(include_empty=include_empty):
            yield dict(batch.columns), batch.num_rows, batch.item_id
    elif getattr(reader, 'is_batched_reader', False):
        for batch in reader:
            columns = batch._asdict()
            num_rows = len(next(iter(columns.values()))) if columns else 0
            yield columns, num_rows, None
    else:
        pending = []
        for row in reader:
            pending.append(row._asdict())
            if len(pending) >= accum_rows:
                yield _rows_to_columns(pending), len(pending), None
                pending = []
        if pending:
            yield _rows_to_columns(pending), len(pending), None


def reader_may_be_infinite(reader):
    """Conservative infinite-stream detection: ``num_epochs is None`` on the reader or,
    for wrapper readers exposing ``_readers``/``readers``, on any wrapped reader;
    unknown shapes count as infinite (callers should then demand an explicit cap)."""
    if hasattr(reader, 'num_epochs'):
        return reader.num_epochs is None
    inner = getattr(reader, 'readers', None) or getattr(reader, '_readers', None)
    if inner:
        return any(reader_may_be_infinite(r) for r in inner)
    return True


class FieldShardings(object):
    """Per-field sharding table: fields named in the ``partition_spec`` dict get their
    spec, everything else the batch-axis default (rank-1 label columns can ride along
    with a rank-2 sequence-sharded tokens column)."""

    def __init__(self, per_field, default):
        self._per_field = per_field
        self._default = default

    def for_field(self, name):
        return self._per_field.get(name, self._default)

    def check_unused(self, field_names):
        """Warn once about spec keys matching no batch field (a typoed key would
        otherwise silently leave its field on the batch-axis default)."""
        unused = set(self._per_field) - set(field_names)
        if unused:
            import warnings
            warnings.warn('partition_spec keys {} match no batch field (fields: {}); '
                          'those fields fall back to the default batch-axis sharding'
                          .format(sorted(unused), sorted(field_names)))


def resolve_sharding(mesh, partition_spec, device_put):
    """Sharding for emitted batch arrays: single default device without a mesh, else a
    ``NamedSharding`` over ``partition_spec`` (default: batch axis over the mesh's first
    axis). A dict ``partition_spec`` ({field: PartitionSpec}) returns a
    :class:`FieldShardings` table."""
    if isinstance(partition_spec, dict):
        per_field = {name: resolve_sharding(mesh, spec, device_put)
                     for name, spec in partition_spec.items()}
        default = resolve_sharding(mesh, None, device_put)
        return FieldShardings(per_field, default)
    if not device_put:
        if partition_spec is not None and mesh is None:
            raise ValueError('partition_spec requires a mesh')
        return None
    import jax
    from jax.sharding import NamedSharding, PartitionSpec, SingleDeviceSharding
    if mesh is None:
        if partition_spec is not None:
            raise ValueError('partition_spec requires a mesh')
        return SingleDeviceSharding(jax.devices()[0])
    spec = partition_spec
    if spec is None:
        spec = PartitionSpec(mesh.axis_names[0])
    return NamedSharding(mesh, spec)


def sharding_for_field(sharding, name):
    """Per-field sharding lookup: FieldShardings tables dispatch by name, plain
    shardings apply to every field."""
    return sharding.for_field(name) if isinstance(sharding, FieldShardings) else sharding


def _chunk_sharding(sharding):
    """Batch sharding -> chunk sharding: prepend an unsharded (replicated-over-mesh)
    scan axis to every NamedSharding's PartitionSpec, so a ``(batch, ...)`` spec
    applies to the trailing dims of a ``(n_batches, batch, ...)`` chunk array.
    SingleDeviceSharding (mesh=None) already covers any rank and passes through."""
    from jax.sharding import NamedSharding, PartitionSpec
    if isinstance(sharding, FieldShardings):
        return FieldShardings(
            {name: _chunk_sharding(s) for name, s in sharding._per_field.items()},
            _chunk_sharding(sharding._default))
    if isinstance(sharding, NamedSharding):
        return NamedSharding(sharding.mesh, PartitionSpec(None, *sharding.spec))
    return sharding


def coalescible_layout(columns):
    """Layout key for the coalesced single-transfer upload, or None when any
    field disqualifies the batch: every column must be a C-contiguous ndarray of
    a native-endian bool/int/uint/float dtype whose device representation the
    unpack program can reproduce bit- (or canonicalization-) exactly. Under
    default x32, 64-bit ints canonicalize by mod-2^32 truncation — reproduced
    on device from the packed bytes' low words — while ``float64``'s rounding
    conversion cannot be expressed without 64-bit types, so it falls back to
    the per-field path. The key is a tuple of ``(name, dtype_str, shape)`` —
    hashable, and identical batches of a stream share one compiled program."""
    import jax
    x64 = bool(jax.config.jax_enable_x64)
    layout = []
    for name in sorted(columns):
        col = columns[name]
        if not isinstance(col, np.ndarray) or col.dtype.kind not in 'biuf':
            return None
        if col.dtype.byteorder not in ('=', '|', '<'):
            return None
        if col.dtype.itemsize == 8 and col.dtype.kind == 'f' and not x64:
            return None
        if not col.flags.c_contiguous:
            return None
        layout.append((name, col.dtype.str, col.shape))
    return tuple(layout) if layout else None


def _make_unpack(layout, x64):
    """Device-side unpack for a packed uint8 buffer: static slices + bitcast per
    field — view-level ops XLA fuses into the consuming program. Matches
    ``jax.device_put``'s dtype canonicalization: under x32, int64/uint64
    columns land as int32/uint32 via mod-2^32 truncation, which for
    little-endian packed bytes is exactly the low 4-byte word."""
    import jax.numpy as jnp
    from jax import lax

    def unpack(buf):
        out = {}
        offset = 0
        for name, dtype_str, shape in layout:
            dtype = np.dtype(dtype_str)
            nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            seg = buf[offset:offset + nbytes]
            offset += nbytes
            if dtype == np.uint8:
                arr = seg
            elif dtype == np.bool_:
                arr = seg != 0
            elif dtype.itemsize == 1:
                arr = lax.bitcast_convert_type(seg, jnp.dtype(dtype))
            elif dtype.itemsize == 8 and dtype.kind in 'iu' and not x64:
                words = lax.bitcast_convert_type(seg.reshape(-1, 4), jnp.uint32)
                low = words.reshape(-1, 2)[:, 0]  # little-endian low word
                target = jnp.int32 if dtype.kind == 'i' else jnp.uint32
                arr = lax.bitcast_convert_type(low, target)
            else:
                arr = lax.bitcast_convert_type(
                    seg.reshape(-1, dtype.itemsize), jnp.dtype(dtype))
            out[name] = arr.reshape(shape)
        return out

    return unpack


def sanitize_columns(columns, pad_ragged, device_put, passthrough=frozenset()):
    """Dtype sanitization for the device (the analog of the torch/tf sanitizers,
    pytorch.py:40-65 / tf_utils.py:57-96): datetimes -> int64 ns, ragged fields padded
    per ``pad_ragged`` (emitting a ``<field>_len`` mask column), strings/objects rejected
    with the field named when a device representation is required. Columns named
    in ``passthrough`` skip sanitization entirely — raw-shipped payloads (and
    their auxiliary columns) keep whatever form the ship-raw kernel produced
    until the device decode tail finishes them (docs/performance.md)."""
    out = {}
    for name, col in columns.items():
        if name in passthrough:
            out[name] = col
            continue
        if name in pad_ragged:
            padded, lengths = _pad_column(col, pad_ragged[name], name)
            out[name] = padded
            out[name + '_len'] = lengths
            continue
        if isinstance(col, list):
            raise ValueError(
                'Field {!r} is ragged (variable shape); pass pad_ragged={{{!r}: '
                '(max_shape...)}} to pad it, or drop it via schema_fields'
                .format(name, name))
        if col.dtype.kind == 'M':
            out[name] = col.astype('datetime64[ns]').astype(np.int64)
        elif col.dtype.kind in ('U', 'S', 'O'):
            if device_put:
                raise ValueError(
                    'Field {!r} has dtype {} which has no device representation; '
                    'drop it via schema_fields or use device_put=False'
                    .format(name, col.dtype))
            out[name] = col
        else:
            out[name] = np.ascontiguousarray(col)
    return out


def _iter_column_slices(columns, slice_rows):
    n = 0
    for col in columns.values():
        n = len(col)
        break
    if n <= slice_rows:
        yield columns
        return
    for start in range(0, n, slice_rows):
        yield {name: col[start:start + slice_rows] for name, col in columns.items()}


def _concat_column_chunks(chunks):
    """Concatenate a list of sanitized column dicts along the row axis (single-chunk
    lists pass through without a copy)."""
    if len(chunks) == 1:
        return chunks[0]
    return {name: np.concatenate([c[name] for c in chunks])
            for name in chunks[0]}


def _rows_to_columns(rows):
    columns = {}
    for name in rows[0]:
        values = [row[name] for row in rows]
        first = values[0]
        if isinstance(first, np.ndarray) and first.ndim >= 1:
            shapes = {v.shape for v in values}
            if len(shapes) == 1:
                columns[name] = np.stack(values)
            else:
                columns[name] = values  # ragged: stays a list until pad_ragged
        elif isinstance(first, (str, bytes)) or first is None:
            columns[name] = np.array(values, dtype=object)
        else:
            columns[name] = np.asarray(values)
    return columns


def _pad_column(col, target_shape, name):
    """Zero-pad each row of a ragged column to ``target_shape``; return (padded array,
    int32 first-dim lengths)."""
    values = list(col)
    target_shape = tuple(target_shape)
    first = np.asarray(values[0])
    padded = np.zeros((len(values),) + target_shape, dtype=first.dtype)
    lengths = np.zeros(len(values), dtype=np.int32)
    for i, value in enumerate(values):
        value = np.asarray(value)
        if value.ndim != len(target_shape):
            raise ValueError('pad_ragged[{!r}]={} rank mismatch with value shape {}'
                             .format(name, target_shape, value.shape))
        if any(v > t for v, t in zip(value.shape, target_shape)):
            raise ValueError('Value of field {!r} with shape {} exceeds pad_ragged '
                             'target {}'.format(name, value.shape, target_shape))
        region = tuple(slice(0, s) for s in value.shape)
        padded[(i,) + region] = value
        lengths[i] = value.shape[0]
    return padded, lengths


def make_jax_loader(dataset_url_or_urls, batch_size, mesh=None, partition_spec=None,
                    batched=True, loader_kwargs=None, **reader_kwargs):
    """Convenience factory: reader + JaxDataLoader in one call. ``batched=True`` uses
    make_batch_reader (native Parquet, fastest); ``batched=False`` uses make_reader
    (codec decode)."""
    from petastorm_tpu.parallel.mesh import distributed_shard_info
    from petastorm_tpu.reader import make_batch_reader, make_reader
    cur_shard, shard_count = distributed_shard_info(
        reader_kwargs.pop('cur_shard', None), reader_kwargs.pop('shard_count', None))
    if shard_count is not None:
        reader_kwargs['cur_shard'] = cur_shard
        reader_kwargs['shard_count'] = shard_count
    factory = make_batch_reader if batched else make_reader
    reader = factory(dataset_url_or_urls, **reader_kwargs)
    return JaxDataLoader(reader, batch_size, mesh=mesh, partition_spec=partition_spec,
                         **(loader_kwargs or {}))
