"""Orbax-bundled training checkpoints: model state + input-pipeline position in ONE
atomic checkpoint.

The reference's story is "persistent artifacts only" (SURVEY.md §5.4: restart
granularity is the epoch; petastorm/reader.py:496-520). This repo's readers/loaders are
mid-epoch resumable (``Reader.state_dict`` / ``JaxDataLoader.state_dict``), and the
natural TPU-native home for that state is the same orbax checkpoint that holds the
model: saving them together means a restored job resumes from the exact rows it had
not yet trained on, and a torn checkpoint (model saved, loader position lost) cannot
happen. Orbax handles atomicity, retention, and async-friendly layout.

Usage::

    ckpt = TrainingCheckpointer('/ckpts', max_to_keep=3)
    for batch in loader:
        state = train_step(state, batch)
        if step % 1000 == 0:
            ckpt.save(step, state, loader=loader)

    # on restart
    state, loader_state = ckpt.restore(state)      # template for structure
    reader = make_reader(url, ..., resume_state=loader_state['reader'])
    loader = JaxDataLoader(reader, ...)

Cross-topology restore (save on 4 hosts, resume on 2): collect every host's
restored ``loader_state['reader']`` and re-deal them with
:func:`restore_across_topology` — each merged state pins the new host's
identity and shard assignment, so the resumed pod covers exactly the
unconsumed remainder regardless of the new host count
(docs/robustness.md "Elastic pod-scale sharding").
"""

import json

import orbax.checkpoint as ocp

_MODEL_KEY = 'train_state'
_LOADER_KEY = 'input_pipeline'


def restore_across_topology(reader_states, new_count):
    """Re-deal a full pod's saved reader states onto ``new_count`` hosts.

    ``reader_states`` is every old host's ``loader_state['reader']`` (all of
    them — a partial pod cannot prove coverage). Returns one merged reader
    state per NEW host; feed state ``i`` to new host ``i`` as::

        from petastorm_tpu.parallel.topology import policy_from_state
        state = merged[jax.process_index()]
        reader = make_reader(url, ...,
                             topology=policy_from_state(state, journal_path),
                             resume_state=state)

    Thin bridge over :func:`petastorm_tpu.parallel.topology.
    merge_topology_states`, which refuses mid-batch cursors, mismatched
    epochs, and states not saved by a topology-armed reader."""
    from petastorm_tpu.parallel.topology import merge_topology_states
    return merge_topology_states(reader_states, new_count)


def _check_json_roundtrip(loader_state):
    """Fail a save EARLY (and name the offending key) when the loader state
    would not survive orbax's JsonSave: a non-JSON-serializable value (bytes
    digest, numpy scalar, set) raises deep inside the async save machinery
    with no hint of which entry is at fault — and under elastic resharding
    the service loader state now carries nested scheduler/ledger fields that
    make this failure mode easy to hit."""
    try:
        json.dumps(loader_state)
        return
    except (TypeError, ValueError):
        pass

    def blame(node, path):
        if isinstance(node, dict):
            for key, value in node.items():
                blame(value, path + (str(key),))
        elif isinstance(node, (list, tuple)):
            for index, value in enumerate(node):
                blame(value, path + (str(index),))
        else:
            try:
                json.dumps(node)
            except (TypeError, ValueError):
                raise TypeError(
                    'loader state is not JSON-serializable at {!r}: {!r} '
                    '({}) — convert it before save() or drop it from '
                    'state_dict()'.format('/'.join(path) or '<root>', node,
                                          type(node).__name__)) from None

    blame(loader_state, ())
    # structure-level failure (circular reference): no single leaf to blame
    raise TypeError('loader state is not JSON-serializable (circular '
                    'reference?)')


class TrainingCheckpointer(object):
    """Atomic (model pytree, input-pipeline position) checkpoints via an orbax
    ``CheckpointManager``.

    :param directory: checkpoint root (local path or any orbax-supported store).
    :param max_to_keep: retention count (orbax deletes older steps).
    :param save_interval_steps: if set, :meth:`save` becomes a no-op except every
        N-th step — lets the training loop call it unconditionally.
    """

    def __init__(self, directory, max_to_keep=3, save_interval_steps=None):
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps or 1,
            create=True)
        self._manager = ocp.CheckpointManager(directory, options=options)

    def save(self, step, train_state, loader=None, loader_state=None, force=False):
        """Save ``train_state`` (any pytree of arrays) plus the input position.

        Pass either ``loader`` (its ``state_dict()`` is taken — raising where the
        loader cannot attribute in-flight rows, exactly like a direct call) or an
        explicit ``loader_state`` dict; with neither, only the model state is saved.
        Returns True when orbax actually wrote a step."""
        if loader is not None and loader_state is not None:
            raise ValueError('Pass loader or loader_state, not both')
        if not force and not self._manager.should_save(step):
            # The no-op contract must hold BEFORE deriving loader state: state_dict()
            # can legitimately raise mid-stream (shuffling buffer) on steps orbax
            # would skip anyway.
            return False
        if loader is not None:
            loader_state = {'reader': loader.state_dict()}
        elif loader_state is not None and 'reader' not in loader_state:
            loader_state = {'reader': loader_state}
        composite = {_MODEL_KEY: ocp.args.StandardSave(train_state)}
        if loader_state is not None:
            _check_json_roundtrip(loader_state)
            composite[_LOADER_KEY] = ocp.args.JsonSave(loader_state)
        return self._manager.save(step, args=ocp.args.Composite(**composite),
                                  force=force)

    def restore(self, train_state_template, step=None):
        """Restore ``(train_state, loader_state)`` from ``step`` (default: latest).

        ``train_state_template`` supplies the pytree structure/shapes (pass the
        freshly initialized state). ``loader_state`` is the dict whose ``['reader']``
        entry feeds ``make_reader(..., resume_state=...)``; it is None when the
        checkpoint carried no input position."""
        # Settle any in-flight async save FIRST: the step-directory probe below would
        # otherwise miss the not-yet-finalized input_pipeline item and silently drop
        # the read position (manager.restore waits internally, but too late for the
        # probe).
        self._manager.wait_until_finished()
        if step is None:
            step = self._manager.latest_step()
        if step is None:
            raise ValueError('No checkpoint found under {!r}'
                             .format(str(self._manager.directory)))
        composite = {_MODEL_KEY: ocp.args.StandardRestore(train_state_template)}
        # Directory probe instead of manager.item_metadata(step): the latter logs a
        # scary "could not be restored" warning per item on a fresh manager that has
        # no handler registry yet.
        step_dir = self._manager.directory / str(step)
        if (step_dir / _LOADER_KEY).exists():
            composite[_LOADER_KEY] = ocp.args.JsonRestore()
        restored = self._manager.restore(step, args=ocp.args.Composite(**composite))
        return restored[_MODEL_KEY], restored.get(_LOADER_KEY)

    @property
    def latest_step(self):
        return self._manager.latest_step()

    def all_steps(self):
        return list(self._manager.all_steps())

    def wait_until_finished(self):
        self._manager.wait_until_finished()

    def close(self):
        self._manager.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.close()
