"""Columnar shuffling buffers (reference: petastorm/reader_impl/shuffling_buffer.py:23-180
and pytorch_shuffling_buffer.py:22-279, unified).

One columnar implementation serves every adapter (JAX, torch, TF): batches are
dicts of ``(n, ...)`` arrays (or lists for ragged fields). Columns may be numpy arrays
*or* torch tensors on any device — gather/concat dispatch per column, so the torch
loaders shuffle device-resident tensors exactly like the reference's batched torch
buffers (pytorch_shuffling_buffer.py:22-279, CPU or CUDA) without a separate class.
Both buffers hold added chunks as separate *parts* and only materialize the rows a
retrieve touches — ``add_many`` never re-copies the whole store, so cost is amortized
O(rows moved), not O(buffer) per call (the reference achieves the same with swap-to-end
pops, shuffling_buffer.py:116-140). The random buffer keeps a ``min_after_retrieve``
decorrelation floor. Not thread safe (same contract as the reference,
shuffling_buffer.py:24-26).
"""

import sys
from collections import deque

import numpy as np


def _is_torch_tensor(value):
    torch = sys.modules.get('torch')
    return torch is not None and isinstance(value, torch.Tensor)


class ShufflingBufferBase(object):
    """Columnar shuffling-buffer interface (reference: petastorm/reader_impl/
    shuffling_buffer.py): ``add_many`` columns in, ``retrieve`` rows out."""

    def add_many(self, columns):
        raise NotImplementedError()

    def retrieve(self, n):
        """Return a dict of columns with ``n`` rows (fewer only after ``finish``)."""
        raise NotImplementedError()

    @property
    def size(self):
        raise NotImplementedError()

    def can_retrieve(self, n):
        raise NotImplementedError()

    def finish(self):
        """No more adds; drain whatever remains."""
        raise NotImplementedError()


def _gather(columns, indices):
    out = {}
    for name, col in columns.items():
        if isinstance(col, np.ndarray):
            out[name] = col[indices]
        elif _is_torch_tensor(col):
            # Advanced indexing gathers on the tensor's own device (cpu/cuda).
            out[name] = col[np.asarray(indices)]
        else:
            out[name] = [col[i] for i in indices]
    return out


def _concat_parts(parts):
    out = {}
    for name in parts[0]:
        values = [p[name] for p in parts]
        if isinstance(values[0], np.ndarray) and values[0].ndim >= 1:
            out[name] = np.concatenate(values) if len(values) > 1 else values[0]
        elif _is_torch_tensor(values[0]):
            import torch
            out[name] = torch.cat(values) if len(values) > 1 else values[0]
        else:
            merged = []
            for v in values:
                merged.extend(list(v))
            out[name] = merged
    return out


def _num_rows(columns):
    for col in columns.values():
        return len(col)
    return 0


def _slice_columns(columns, start, stop):
    return {name: col[start:stop] for name, col in columns.items()}


class NoopShufflingBuffer(ShufflingBufferBase):
    """FIFO pass-through: deque of parts + read cursor into the head part (reference:
    shuffling_buffer.py:29-77)."""

    def __init__(self):
        self._parts = deque()
        self._head_offset = 0
        self._size = 0
        self._finished = False

    def add_many(self, columns):
        if self._finished:
            raise RuntimeError('Cannot add to a finished shuffling buffer')
        n = _num_rows(columns)
        if n:
            self._parts.append(columns)
            self._size += n

    def retrieve(self, n):
        take = min(n, self._size) if self._finished else n
        if take > self._size:
            raise RuntimeError('Not enough rows buffered: asked {}, have {}'
                               .format(n, self._size))
        pieces = []
        needed = take
        while needed > 0:
            head = self._parts[0]
            head_rows = _num_rows(head) - self._head_offset
            use = min(head_rows, needed)
            pieces.append(_slice_columns(head, self._head_offset,
                                         self._head_offset + use))
            needed -= use
            self._head_offset += use
            if self._head_offset >= _num_rows(head):
                self._parts.popleft()
                self._head_offset = 0
        self._size -= take
        return _concat_parts(pieces) if pieces else {}

    @property
    def size(self):
        return self._size

    def can_retrieve(self, n):
        return self._size >= n or (self._finished and self._size > 0)

    def finish(self):
        self._finished = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Random-order buffer with a decorrelation floor (reference:
    shuffling_buffer.py:80-180): holds up to ``shuffling_buffer_capacity`` rows; retrieval
    is blocked until ``min_after_retrieve`` rows would remain (until ``finish``).

    Each added chunk stays a separate part with an array of still-alive row positions;
    a retrieve samples uniformly over the global alive set (exact, without replacement)
    and removes only the picked positions — no whole-store reshuffle or copy.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve must be <= capacity')
        self._capacity = shuffling_buffer_capacity
        self._min_after = min_after_retrieve
        self._random = np.random.default_rng(seed)
        self._parts = []        # list of column dicts
        self._alive = []        # list of int arrays: still-alive row positions per part
        self._size = 0
        self._finished = False

    def add_many(self, columns):
        if self._finished:
            raise RuntimeError('Cannot add to a finished shuffling buffer')
        n = _num_rows(columns)
        if not n:
            return
        self._parts.append(columns)
        self._alive.append(np.arange(n))
        self._size += n

    def can_add(self):
        return self._size < self._capacity and not self._finished

    @property
    def min_after_retrieve(self):
        """The current decorrelation floor."""
        return self._min_after

    def set_min_after_retrieve(self, value):
        """Runtime adjust of the decorrelation floor, clamped to
        ``[0, capacity]`` — the loader fill-threshold knob the autotuner turns
        (docs/autotuning.md). A single attribute store, so it is safe to call
        from a controller thread while the producer thread retrieves (the
        buffer's not-thread-safe contract otherwise stands). Returns the
        applied value."""
        value = max(0, min(int(value), self._capacity))
        self._min_after = value
        return value

    def retrieve(self, n):
        if self._finished:
            take = min(n, self._size)
        else:
            take = n
            if self._size - n < self._min_after:
                raise RuntimeError('Retrieval would drop below min_after_retrieve; '
                                   'buffer more rows first (size={}, min={})'
                                   .format(self._size, self._min_after))
        counts = np.array([len(a) for a in self._alive])
        cum = np.concatenate([[0], np.cumsum(counts)])
        ranks = self._random.choice(self._size, size=take, replace=False)
        part_ids = np.searchsorted(cum, ranks, side='right') - 1
        pieces = []
        for part_id in np.unique(part_ids):
            local_ranks = ranks[part_ids == part_id] - cum[part_id]
            positions = self._alive[part_id][local_ranks]
            pieces.append(_gather(self._parts[part_id], positions))
            self._alive[part_id] = np.delete(self._alive[part_id], local_ranks)
        self._parts = [p for p, a in zip(self._parts, self._alive) if len(a)]
        self._alive = [a for a in self._alive if len(a)]
        self._size -= take
        return _concat_parts(pieces) if pieces else {}

    @property
    def size(self):
        return self._size

    def can_retrieve(self, n):
        if self._finished:
            return self._size > 0
        return self._size - n >= self._min_after

    def finish(self):
        self._finished = True
