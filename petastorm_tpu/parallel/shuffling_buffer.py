"""Columnar shuffling buffers (reference: petastorm/reader_impl/shuffling_buffer.py:23-180
and pytorch_shuffling_buffer.py:22-279, unified).

One numpy-columnar implementation serves every adapter (JAX, torch, TF): batches are
dicts of ``(n, ...)`` arrays; retrieval gathers random indices. The random buffer keeps a
``min_after_retrieve`` floor so samples stay decorrelated, exactly the reference's
semantics. Not thread safe (same contract as the reference, shuffling_buffer.py:24-26).
"""

import numpy as np


class ShufflingBufferBase(object):
    def add_many(self, columns):
        raise NotImplementedError()

    def retrieve(self, n):
        """Return a dict of columns with ``n`` rows (fewer only after ``finish``)."""
        raise NotImplementedError()

    @property
    def size(self):
        raise NotImplementedError()

    def can_retrieve(self, n):
        raise NotImplementedError()

    def finish(self):
        """No more adds; drain whatever remains."""
        raise NotImplementedError()


def _concat_columns(parts):
    out = {}
    for name in parts[0]:
        values = [p[name] for p in parts]
        if isinstance(values[0], np.ndarray) and values[0].ndim >= 1:
            out[name] = np.concatenate(values)
        else:
            merged = []
            for v in values:
                merged.extend(list(v))
            out[name] = merged
    return out


def _gather(columns, indices):
    return {name: (col[indices] if isinstance(col, np.ndarray)
                   else [col[i] for i in indices])
            for name, col in columns.items()}


def _num_rows(columns):
    for col in columns.values():
        return len(col)
    return 0


class NoopShufflingBuffer(ShufflingBufferBase):
    """FIFO pass-through (reference: shuffling_buffer.py:29-77)."""

    def __init__(self):
        self._parts = []
        self._size = 0
        self._finished = False

    def add_many(self, columns):
        if self._finished:
            raise RuntimeError('Cannot add to a finished shuffling buffer')
        n = _num_rows(columns)
        if n:
            self._parts.append(columns)
            self._size += n

    def retrieve(self, n):
        take = min(n, self._size) if self._finished else n
        if take > self._size:
            raise RuntimeError('Not enough rows buffered: asked {}, have {}'
                               .format(n, self._size))
        merged = _concat_columns(self._parts) if self._parts else {}
        result = _gather(merged, np.arange(take))
        rest_indices = np.arange(take, _num_rows(merged))
        self._parts = [_gather(merged, rest_indices)] if len(rest_indices) else []
        self._size -= take
        return result

    @property
    def size(self):
        return self._size

    def can_retrieve(self, n):
        return self._size >= n or (self._finished and self._size > 0)

    def finish(self):
        self._finished = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Random-order buffer with a decorrelation floor (reference:
    shuffling_buffer.py:80-180): holds up to ``shuffling_buffer_capacity`` rows; retrieval
    is blocked until ``min_after_retrieve`` rows are present (until ``finish``)."""

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve must be <= capacity')
        self._capacity = shuffling_buffer_capacity
        self._min_after = min_after_retrieve
        self._random = np.random.default_rng(seed)
        self._store = None
        self._size = 0
        self._finished = False

    def add_many(self, columns):
        if self._finished:
            raise RuntimeError('Cannot add to a finished shuffling buffer')
        n = _num_rows(columns)
        if not n:
            return
        self._store = columns if self._store is None \
            else _concat_columns([self._store, columns])
        self._size = _num_rows(self._store)

    def can_add(self):
        return self._size < self._capacity and not self._finished

    def retrieve(self, n):
        available = self._size if self._finished else self._size - self._min_after
        take = min(n, max(0, available)) if self._finished else n
        if not self._finished and self._size - n < self._min_after:
            raise RuntimeError('Retrieval would drop below min_after_retrieve; buffer '
                               'more rows first (size={}, min={})'
                               .format(self._size, self._min_after))
        permutation = self._random.permutation(self._size)
        pick, keep = permutation[:take], permutation[take:]
        result = _gather(self._store, pick)
        self._store = _gather(self._store, keep) if len(keep) else None
        self._size = len(keep)
        return result

    @property
    def size(self):
        return self._size

    def can_retrieve(self, n):
        if self._finished:
            return self._size > 0
        return self._size - n >= self._min_after

    def finish(self):
        self._finished = True
