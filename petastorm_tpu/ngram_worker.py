"""NGram piece processing inside the rowgroup worker (reference:
petastorm/py_dict_reader_worker.py:179-180,271-313).

One ventilated piece = one rowgroup; windows are formed within it (ngram.py:85-91 caveat:
rowgroup size bounds max window length). Shuffle-row-drop partitions receive ``length-1``
carry-over rows from the next partition so windows at the partition boundary survive
(reference: py_dict_reader_worker.py:299-304).

The published payload is columnar end-to-end: one :class:`NGramWindows` per piece holding
the decoded columns ONCE plus the window start indices from
``NGram.form_ngram_columnar`` — windows are views (gather indices), not materialized
per-row dicts, so N overlapping windows cost O(rows) not O(N x length) to ship, cache,
and shuffle. The per-window namedtuple view is built lazily at consumption
(``NGram.window_plan`` + ``NGram.window_from_plan`` in the reader's results reader)."""

import numpy as np


class NGramWindows(object):
    """Columnar window set of one rowgroup piece: ``starts[i]`` is the first row of
    window i; every window spans ``length`` consecutive rows of ``columns``.
    ``item_id`` is the ventilated work item's ``(epoch, piece, drop_partition)`` —
    the unit of NGram checkpoint/resume accounting (VERDICT r3 item 4); zero-window
    pieces still publish (empty ``starts``) solely to carry it. ``retries`` /
    ``quarantine`` are the resilience sidecar, ``telemetry`` the stage-span
    sidecar, ``breakers`` the circuit-breaker sidecar, ``trace`` the
    flight-recorder sidecar, ``lineage`` the sampled content-fingerprint
    sidecar — same contracts as
    :class:`~petastorm_tpu.reader_worker.ColumnarBatch` (docs/robustness.md,
    docs/observability.md)."""

    __slots__ = ('columns', 'starts', 'item_id', 'retries', 'quarantine',
                 'telemetry', 'breakers', 'trace', 'lineage')

    def __init__(self, columns, starts, item_id=None, retries=0, quarantine=None,
                 telemetry=None, breakers=None, trace=None, lineage=None):
        self.columns = columns
        self.starts = starts
        self.item_id = item_id
        self.retries = retries
        self.quarantine = quarantine
        self.telemetry = telemetry
        self.breakers = breakers
        self.trace = trace
        self.lineage = lineage

    def __len__(self):
        return len(self.starts)

    @property
    def num_rows(self):
        """Windows in this payload (a window is the NGram path's row unit)."""
        return len(self.starts)


def process_ngram_piece(worker, piece_index, fragment_path, row_group_id, partition_keys,
                        worker_predicate, shuffle_row_drop_partition, epoch_index=0):
    """Decode one ventilated rowgroup piece and form its NGram windows: returns an
    :class:`NGramWindows` payload (possibly zero windows) tagged with the piece's
    ``(epoch_index, piece_index, drop_partition)`` item id."""
    from petastorm_tpu.reader_worker import _take
    setup = worker._setup
    ngram = setup.ngram
    if worker_predicate is not None:
        raise NotImplementedError('Predicates are not supported together with NGram '
                                  '(reference semantics: reader.py:430-434)')

    def load_windows():
        fragment = worker._make_fragment(fragment_path, row_group_id)
        table = fragment.to_table(columns=worker._storage_columns(setup.fields_to_read))
        columns = worker._decode_table(table, partition_keys, setup.fields_to_read,
                                       fragment_path=fragment_path)
        num_rows = table.num_rows

        part_index, num_parts = shuffle_row_drop_partition
        if num_parts > 1 and num_rows > 0:
            partition_indexes = np.floor(
                np.arange(num_rows) / (float(num_rows) / min(num_rows, num_parts)))
            # Carry over length-1 rows from the next partition so boundary windows form
            # (reference: py_dict_reader_worker.py:299-304).
            next_part = np.nonzero(partition_indexes >= part_index + 1)[0]
            if next_part.size:
                partition_indexes[next_part[:ngram.length - 1]] = part_index
            selected = np.nonzero(partition_indexes == part_index)[0]
            columns = {name: _take(col, selected) for name, col in columns.items()}
            num_rows = len(selected)

        timestamps = np.asarray(columns[ngram.timestamp_field_name][:num_rows])
        starts = ngram.form_ngram_columnar(timestamps)
        return {'columns': columns, 'starts': starts}

    cache_key = 'ngram:{}:{}:{}:{}'.format(setup.dataset_token, fragment_path,
                                           row_group_id, shuffle_row_drop_partition)
    payload = setup.cache.get(cache_key, load_windows)
    starts = payload['starts']

    if setup.shuffle_rows and len(starts):
        # Seeded per piece: replaying the piece reproduces the window order, which
        # is what makes window-exact resume possible (seed=None degrades resume to
        # piece-exact, same caveat as the row path).
        seed = None if setup.seed is None else (setup.seed + piece_index) % (2 ** 31)
        starts = starts[np.random.RandomState(seed).permutation(len(starts))]
    item_id = (epoch_index, piece_index, shuffle_row_drop_partition[0])
    return NGramWindows(payload['columns'], starts, item_id=item_id)
