"""NGram: sliding sequence windows over timestamp-sorted rows — the reference's
long-context/sequence-construction feature (reference: petastorm/ngram.py:20-339;
behavior spec in its docstring :20-100).

Spec: ``fields`` maps timestep offsets to per-timestep field subsets (fields or regexes);
``delta_threshold`` bounds the timestamp gap between *consecutive* timesteps;
``timestamp_overlap=False`` forbids emitted windows from overlapping in timestamp range.
Windows are formed inside one rowgroup (the reference's documented caveat — ngram.py:85-91:
rowgroup size bounds max sequence length; make rowgroups >= window length).

TPU-first extension: :meth:`form_ngram_columnar` works directly on columnar batches and
returns gather indices, so the device layer can emit sequence batches without building
row dicts.
"""

import re

import numpy as np

from petastorm_tpu.unischema import Unischema, UnischemaField, match_unischema_fields


class NGram(object):
    """Sequence-window spec (reference: petastorm/ngram.py): ``{offset: fields}``
    windows over timestamp-ordered rows, gated by ``delta_threshold``. Pass as
    ``schema_fields`` to ``make_reader``; the row path yields ``{offset:
    namedtuple}`` per window, the device path window-major sequence batches
    (:meth:`windows_as_arrays`)."""

    def __init__(self, fields, delta_threshold, timestamp_field, timestamp_overlap=True):
        """
        :param fields: dict {offset(int): list of UnischemaField or regex str}
        :param delta_threshold: max allowed timestamp delta between consecutive timesteps
        :param timestamp_field: UnischemaField (or name) ordering the rows
        :param timestamp_overlap: when False, consecutive emitted windows must not overlap
            in timestamp range (reference: ngram.py:102-125)
        """
        if not isinstance(fields, dict) or not fields:
            raise ValueError('fields must be a non-empty dict of {offset: [fields]}')
        if not all(isinstance(key, int) for key in fields):
            raise ValueError('field keys must be integers (timestep offsets)')
        self._fields = {key: list(value) for key, value in sorted(fields.items())}
        self._delta_threshold = delta_threshold
        self._timestamp_field = timestamp_field
        self.timestamp_overlap = timestamp_overlap
        self._resolved = all(isinstance(f, UnischemaField)
                             for flist in self._fields.values() for f in flist)

    @property
    def length(self):
        """Window span: max offset - min offset + 1 (reference: ngram.py:127-133)."""
        keys = list(self._fields.keys())
        return max(keys) - min(keys) + 1

    @property
    def fields(self):
        return self._fields

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def timestamp_field_name(self):
        if isinstance(self._timestamp_field, UnischemaField):
            return self._timestamp_field.name
        return self._timestamp_field

    # -------------------------------------------------------------- resolution

    def resolve_regex_field_names(self, schema):
        """Expand any regex entries against the schema (reference: ngram.py:195-203)."""
        for key, field_list in self._fields.items():
            resolved = []
            for item in field_list:
                if isinstance(item, UnischemaField):
                    resolved.append(item)
                elif isinstance(item, str):
                    matched = match_unischema_fields(schema, [item])
                    if not matched:
                        raise ValueError('NGram pattern {!r} matched no fields'.format(item))
                    resolved.extend(matched)
                else:
                    raise ValueError('NGram fields must be UnischemaFields or regex '
                                     'strings, got {!r}'.format(item))
            # overlapping patterns may match the same field twice: dedup by name,
            # preserving first-match order
            seen = {}
            for field in resolved:
                seen.setdefault(field.name, field)
            self._fields[key] = list(seen.values())
        self._resolved = True

    def get_field_names_at_timestep(self, key):
        return [f.name for f in self._fields.get(key, [])]

    def get_field_names_at_all_timesteps(self):
        names = []
        for key in self._fields:
            for name in self.get_field_names_at_timestep(key):
                if name not in names:
                    names.append(name)
        ts_name = self.timestamp_field_name
        if ts_name not in names:
            names.append(ts_name)
        return names

    def get_schema_at_timestep(self, schema, key):
        """Per-timestep schema view (reference: ngram.py:215-223)."""
        names = [n for n in self.get_field_names_at_timestep(key) if n in schema.fields]
        return schema.create_schema_view([re.escape(n) for n in names])

    # -------------------------------------------------------------- formation

    def form_ngram_columnar(self, timestamps):
        """Compute window start indices over a timestamp vector (rows of ONE rowgroup,
        sorted ascending). Returns an array of starts; window i spans
        ``starts[i] : starts[i] + length``. Columnar analog of reference form_ngram
        (ngram.py:225-270).

        Vectorized: the delta-threshold scan is a cumulative count of oversized gaps
        (a window is valid iff no bad gap falls inside it) — O(n) numpy, no Python loop
        over rows. Only the ``timestamp_overlap=False`` greedy selection walks the
        (already-filtered) candidate list sequentially, as the emitted-window dependency
        chain requires."""
        timestamps = np.asarray(timestamps)
        n = len(timestamps)
        length = self.length
        if n < length:
            return np.empty(0, dtype=np.int64)
        if np.any(timestamps[1:] < timestamps[:-1]):
            raise NotImplementedError(
                'NGram assumes data sorted by {!r}, which is not the case'
                .format(self.timestamp_field_name))
        if length == 1:
            candidates = np.arange(n, dtype=np.int64)
        else:
            bad = np.diff(timestamps) > self._delta_threshold
            bad_before = np.concatenate([[0], np.cumsum(bad)])
            # window at start s covers deltas s .. s+length-2
            window_bad = bad_before[length - 1:] - bad_before[:n - length + 1]
            candidates = np.nonzero(window_bad == 0)[0].astype(np.int64)
        if self.timestamp_overlap:
            return candidates
        starts = []
        prev_end_ts = None
        for start in candidates:
            if prev_end_ts is not None and timestamps[start] <= prev_end_ts:
                continue
            starts.append(start)
            prev_end_ts = timestamps[start + length - 1]
        return np.asarray(starts, dtype=np.int64)

    def windows_as_arrays(self, columns, starts):
        """Materialize windows as window-major arrays: ``{field: (num_windows, length,
        *field_shape)}`` via one vectorized gather per column — the device-layer
        representation (SURVEY.md §5.7: sequence batches for the mesh, the idiomatic
        TPU extension the reference's row-dict windows cannot feed).

        Every column is emitted over the FULL window length; the reference's per-offset
        field subsets (ngram.py:215-223) are a row-path view — on device, slicing the
        length axis is free (XLA fuses it), so consumers take ``batch[field][:, off]``
        where needed. Overlapping windows are materialized (O(windows x length) host
        memory, vs the shared-column row path's O(rows)); that copy is the price of a
        dense device array and is what ``jax.Array`` needs anyway."""
        starts = np.asarray(starts, dtype=np.int64)
        length = self.length
        idx = starts[:, None] + np.arange(length, dtype=np.int64)
        out = {}
        for name, col in columns.items():
            if isinstance(col, list):
                raise ValueError(
                    'NGram field {!r} is ragged (variable shape); give it a fixed '
                    'shape via a TransformSpec before forming device windows'
                    .format(name))
            out[name] = np.asarray(col)[idx]
        return out

    def form_ngram(self, rows):
        """Row-dict formation: list of {offset: row_dict-subset} (reference semantics)."""
        if not rows:
            return []
        ts_name = self.timestamp_field_name
        timestamps = np.asarray([row[ts_name] for row in rows])
        starts = self.form_ngram_columnar(timestamps)
        base_key = min(self._fields.keys())
        result = []
        for start in starts:
            window = {}
            for position in range(self.length):
                key = base_key + position
                if key not in self._fields:
                    continue
                row = rows[start + position]
                wanted = self.get_field_names_at_timestep(key)
                window[key] = {k: row[k] for k in row if k in wanted}
            result.append(window)
        return result

    def make_namedtuples(self, window, schema=None):
        """Convert {offset: row_dict} into {offset: namedtuple} — companion to the
        row-dict :meth:`form_ngram` API (reference: ngram.py:272-297). The reader hot
        path uses :meth:`window_plan` + :meth:`window_from_plan` instead."""
        result = {}
        for key, row in window.items():
            names = sorted(row.keys())
            cls = _timestep_namedtuple(tuple(names))
            result[key] = cls(**row)
        return result

    def window_plan(self, column_names):
        """Precompute the per-timestep emission plan for a given set of available
        columns: ``[(offset, row_position, field_names, namedtuple_cls), ...]``. The
        plan is identical for every window of every batch with the same columns —
        compute it once, then emit windows with :meth:`window_from_plan` (hoists the
        sort/filter/namedtuple-cache work off the per-window hot path)."""
        column_names = set(column_names)
        base_key = min(self._fields.keys())
        plan = []
        for key, field_list in self._fields.items():
            names = tuple(sorted({f.name for f in field_list if f.name in column_names}))
            plan.append((key, key - base_key, names, _timestep_namedtuple(names)))
        return plan

    @staticmethod
    def window_from_plan(columns, start, plan):
        """Emit one ``{offset: namedtuple}`` window straight from columnar data using a
        precomputed :meth:`window_plan` — the hot-path consumer of
        :meth:`form_ngram_columnar` gather indices (no intermediate per-row dicts;
        columns are shared across all windows of a rowgroup)."""
        return {key: cls._make(columns[name][start + position] for name in names)
                for key, position, names, cls in plan}



_timestep_cache = {}


def _timestep_namedtuple(names):
    if names not in _timestep_cache:
        from collections import namedtuple
        _timestep_cache[names] = namedtuple('NGramTimestep', names)
    return _timestep_cache[names]
