"""Disaggregated input service: one shared preprocessing fleet serving many readers.

The cross-process data plane (ZMQ ROUTER/DEALER dispatch, wire codec, watchdog,
breakers — PRs 1-6) promoted to a standalone network service, mirroring the
tf.data-service split (arXiv 2210.14826: "A Case for Disaggregating ML Input
Data Processing"): decode workers and a warm Arrow-IPC rowgroup cache are
amortized across every job reading the same dataset, and a reader joins with
nothing but ``make_reader(..., service_url='tcp://host:port')``.

Three roles (docs/service.md):

- :class:`~petastorm_tpu.service.dispatcher.Dispatcher` — the broker: a ROUTER
  front-end for N concurrent reader clients, a ROUTER back-end where elastic
  decode workers register and heartbeat, per-client deficit-round-robin
  fair-share scheduling over rowgroup work items, and admission control with a
  bounded per-client in-flight window (explicit BUSY rejection).
- :mod:`~petastorm_tpu.service.service_worker` — a stateless decode worker
  process: wraps the existing :class:`~petastorm_tpu.reader_worker.RowGroupWorker`
  decode path, joins/leaves the dispatcher at runtime, serves results over TCP
  via the :mod:`~petastorm_tpu.workers.serializers` wire codec (one-shot
  shared-memory fast path when co-located with the client), and shares one
  :class:`~petastorm_tpu.cache.ArrowIpcDiskCache` directory with its siblings.
- :class:`~petastorm_tpu.service.service_client.ServicePool` — the client
  transport: implements the same pool interface as
  :class:`~petastorm_tpu.workers.process_pool.ProcessPool`, so ``Reader``,
  ``on_error`` resilience modes, the quarantine ledger, telemetry sidecars and
  trace context all work unchanged over the network.

:class:`~petastorm_tpu.service.fleet.ServiceFleet` runs dispatcher + N worker
processes on one host (the ``petastorm-tpu-throughput serve`` CLI and the
tests/bench entry point)."""

from petastorm_tpu.service.dispatcher import Dispatcher, FairShareScheduler
from petastorm_tpu.service.fleet import ServiceFleet
from petastorm_tpu.service.service_client import ServicePool, fetch_service_state

__all__ = ['Dispatcher', 'FairShareScheduler', 'ServiceFleet', 'ServicePool',
           'fetch_service_state']
