"""Single-host service runner: one dispatcher plus N spawned decode workers.

:class:`ServiceFleet` is how the service is actually started — by the
``petastorm-tpu-throughput serve`` CLI, by ``bench.py``'s service section and
by the tests: it runs a :class:`~petastorm_tpu.service.dispatcher.Dispatcher`
in-process (a daemon thread) and spawns each worker as a fresh interpreter
running :mod:`petastorm_tpu.service.service_worker` (spawn, never fork — the
same JVM/libhdfs rationale as the in-process pool), all sharing one cache
directory. Workers are *elastic*: :meth:`spawn_worker` adds one at any time
(it registers with the live dispatcher), :meth:`kill_worker` SIGKILLs one
(the dispatcher's heartbeat watchdog deregisters it and re-queues its
items) — the join/leave choreography the tests drive explicitly.

A multi-host deployment runs the same two entry points by hand: one
``serve --workers 0`` for the dispatcher, and ``service_worker`` processes
pointed at its URL from every decode host (docs/service.md's deployment
matrix)."""

from __future__ import annotations

import logging
import os
import pickle
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from petastorm_tpu.service.dispatcher import (DEFAULT_ADMISSION_WINDOW,
                                              DEFAULT_CLIENT_TTL_S,
                                              DEFAULT_MAX_ITEM_ATTEMPTS,
                                              DEFAULT_QUANTUM,
                                              DEFAULT_STALE_TIMEOUT_S,
                                              Dispatcher)
from petastorm_tpu.service.wire import worker_endpoint

logger = logging.getLogger(__name__)

#: how long ``start`` waits for the initial workers to register
_WORKER_STARTUP_TIMEOUT_S = 60


class ServiceFleet(object):
    """Dispatcher + N service-worker processes on this host (module doc).

    ``cache_dir`` (created when missing) is shared by every worker — the
    fleet-wide warm Arrow-IPC rowgroup cache; None disables the shared cache
    and each client's own cache setting applies. ``shm_results`` enables the
    one-shot shared-memory result path for co-located clients. ``autotune``
    (True or an :class:`~petastorm_tpu.autotune.AutotunePolicy`) arms the
    dispatcher's closed-loop admission retuning — docs/autotuning.md.
    ``metrics_port`` attaches the dispatcher's fleet-wide scrape endpoint
    (``/metrics`` aggregating every worker's heartbeat metric snapshots with
    per-worker/per-client labels, ``/healthz``, ``/vars``; ``0`` binds an
    ephemeral port — ``dispatcher.metrics_url`` names it) —
    docs/observability.md "Live metrics plane". ``incidents`` (True or an
    :class:`~petastorm_tpu.telemetry.incident.IncidentPolicy`) arms the
    incident autopsy plane fleet-wide: every worker captures black-box
    bundles locally and ships references up the heartbeat socket, the
    dispatcher adopts and correlates them — docs/observability.md
    "Incident autopsy plane". ``ledger`` (True or an explicit journal
    path) arms the dispatcher's durable token ledger — the
    epoch-survivable control plane that lets :meth:`crash_dispatcher`
    restart the dispatcher mid-epoch without re-delivering retired work
    or losing in-flight items (docs/service.md "Failure modes").
    ``history`` (True, a store path, or a
    :class:`~petastorm_tpu.telemetry.history.HistoryPolicy`) arms the
    longitudinal observatory: the dispatcher records one run record at
    stop and watches its items-served rate with the live regression
    sentinel — docs/observability.md "Longitudinal observatory"."""

    def __init__(self, workers: int = 2, host: str = '127.0.0.1',
                 port: Optional[int] = None,
                 cache_dir: Optional[str] = None,
                 cache_size_limit: Optional[int] = None,
                 shm_results: bool = True,
                 heartbeat_interval_s: float = 0.5,
                 stale_timeout_s: float = DEFAULT_STALE_TIMEOUT_S,
                 admission_window: int = DEFAULT_ADMISSION_WINDOW,
                 quantum: float = DEFAULT_QUANTUM,
                 max_item_attempts: int = DEFAULT_MAX_ITEM_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 client_ttl_s: float = DEFAULT_CLIENT_TTL_S,
                 autotune: Any = None,
                 metrics_port: Optional[int] = None,
                 incidents: Any = None,
                 ledger: Any = None,
                 history: Any = None) -> None:
        self._initial_workers = workers
        self._cache_dir = cache_dir
        self._cache_size_limit = cache_size_limit
        self._shm_results = shm_results
        self._heartbeat_interval_s = heartbeat_interval_s
        self._incidents = incidents
        self._ledger_path = self._resolve_ledger(ledger)
        self._history_policy = self._resolve_history(history)
        # the dispatcher's construction arguments, kept so crash_dispatcher
        # can rebuild an identical incarnation on the same port
        self._dispatcher_kwargs: Dict[str, Any] = dict(
            host=host, port=port, admission_window=admission_window,
            quantum=quantum, stale_timeout_s=stale_timeout_s,
            max_item_attempts=max_item_attempts,
            item_deadline_s=item_deadline_s, client_ttl_s=client_ttl_s,
            autotune=autotune, metrics_port=metrics_port,
            incidents=incidents, ledger=self._ledger_path,
            history=self._history_policy)
        self.dispatcher = Dispatcher(**self._dispatcher_kwargs)
        self.processes: List[subprocess.Popen] = []
        self._next_worker_id = 0
        self.service_url: Optional[str] = None

    def _resolve_ledger(self, ledger: Any) -> Optional[str]:
        """``None``/``False`` → no ledger; a str → that journal path;
        ``True`` → the fleet cache directory (or a private temp directory
        when the fleet runs cacheless)."""
        if not ledger:
            return None
        if isinstance(ledger, str):
            return ledger
        from petastorm_tpu.service.ledger import LEDGER_BASENAME
        home = self._cache_dir or tempfile.mkdtemp(
            prefix='petastorm-tpu-ledger-')
        os.makedirs(home, exist_ok=True)
        return os.path.join(home, LEDGER_BASENAME)

    def _resolve_history(self, history: Any) -> Any:
        """``None``/``False`` → off; a path (or path-carrying policy) passes
        through; ``True`` / a path-less policy gets a store under the fleet
        cache directory (or a private temp directory when cacheless) —
        unlike a bare dispatcher, the fleet always has a home to persist
        its longitudinal series in."""
        import dataclasses
        from petastorm_tpu.telemetry.history import (HISTORY_BASENAME,
                                                     resolve_history_policy)
        policy = resolve_history_policy(history)
        if policy is None or policy.path:
            return policy
        home = self._cache_dir or tempfile.mkdtemp(
            prefix='petastorm-tpu-history-')
        os.makedirs(home, exist_ok=True)
        return dataclasses.replace(
            policy, path=os.path.join(home, HISTORY_BASENAME))

    @property
    def history_path(self) -> Optional[str]:
        """The run-history store path (None when the observatory is off)."""
        if self._history_policy is None:
            return None
        path: Optional[str] = self._history_policy.path
        return path

    # ------------------------------------------------------------ lifecycle

    def start(self) -> str:
        """Start the dispatcher and the initial workers; blocks until every
        initial worker has registered. Returns the ``service_url``."""
        self.service_url = self.dispatcher.start()
        if self._cache_dir:
            os.makedirs(self._cache_dir, exist_ok=True)
        for _ in range(self._initial_workers):
            self.spawn_worker()
        deadline = time.monotonic() + _WORKER_STARTUP_TIMEOUT_S
        while (self.dispatcher.scheduler.worker_count()
               < self._initial_workers):
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError(
                    'only {} of {} service workers registered within {}s'
                    .format(self.dispatcher.scheduler.worker_count(),
                            self._initial_workers,
                            _WORKER_STARTUP_TIMEOUT_S))
            time.sleep(0.05)
        return self.service_url

    def spawn_worker(self) -> subprocess.Popen:
        """Spawn one decode worker (elastic join — works mid-epoch; it
        registers with the dispatcher on its own)."""
        if self.service_url is None:
            raise RuntimeError('start() the fleet before spawning workers')
        worker_id = self._next_worker_id
        self._next_worker_id += 1
        bootstrap: Dict[str, Any] = {
            'worker_id': worker_id,
            'worker_endpoint': worker_endpoint(self.service_url),
            'heartbeat_interval_s': self._heartbeat_interval_s,
            'shm_results': self._shm_results,
            'parent_pid': os.getpid(),
            'cache_dir': self._cache_dir,
            'cache_size_limit': self._cache_size_limit,
            'incidents': self._incidents,
        }
        fd, path = tempfile.mkstemp(suffix='.petastorm-tpu-service-worker')
        try:
            with os.fdopen(fd, 'wb') as f:
                pickle.dump(bootstrap, f)
            env = dict(os.environ)
            parent_paths = [p for p in sys.path if p]
            existing = env.get('PYTHONPATH')
            env['PYTHONPATH'] = os.pathsep.join(
                parent_paths + ([existing] if existing else []))
            # after a successful spawn the WORKER owns the bootstrap file
            # (service_worker.main unlinks it right after loading)
            process = subprocess.Popen(
                [sys.executable, '-m', 'petastorm_tpu.service.service_worker',
                 path], env=env)
        except Exception:  # noqa: BLE001 - failed spawn: reclaim the bootstrap file, then surface
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        self.processes.append(process)
        return process

    def kill_worker(self, index: int = -1) -> int:
        """SIGKILL one worker process (crash injection for the tests); the
        dispatcher's staleness watchdog deregisters it and re-queues its
        in-flight items. Returns the killed pid."""
        process = self.processes[index]
        process.kill()
        process.wait(timeout=10)
        return process.pid

    def crash_dispatcher(self) -> str:
        """Hard-stop the dispatcher WITHOUT the goodbye choreography (no
        ``w_stop`` broadcast, no worker-tail drain — the moral equivalent of
        SIGKILL for the in-process thread) and start a fresh incarnation on
        the same port. With a ledger armed the replacement replays the
        journal, re-adopts the live workers via the ``w_rejoin`` handshake
        and resumes the epoch without re-delivering retired tokens; without
        one it comes up empty and the clients' starvation re-arm recovers
        the in-flight work the slow way. Returns the (unchanged)
        ``service_url``."""
        if self.service_url is None:
            raise RuntimeError('start() the fleet before crashing it')
        # the replacement must bind the SAME client port or nobody finds it:
        # recover the actual bound port for fleets started with port=None
        port = int(self.service_url.rsplit(':', 1)[1])
        self.dispatcher.crash()
        kwargs = dict(self._dispatcher_kwargs)
        kwargs['port'] = port
        self.dispatcher = Dispatcher(**kwargs)
        self.service_url = self.dispatcher.start()
        return self.service_url

    @property
    def ledger_path(self) -> Optional[str]:
        """The durable ledger journal path (None when the ledger is off)."""
        return self._ledger_path

    def state(self) -> Dict[str, Any]:
        """The dispatcher's scheduler snapshot (clients/workers/queues)."""
        return self.dispatcher.state()

    def stop(self) -> None:
        """Stop the dispatcher (it broadcasts ``w_stop``) and reap the
        worker processes — SIGTERM, then SIGKILL, for any worker that missed
        the broadcast (e.g. one spawned moments before stop that never
        finished registering)."""
        self.dispatcher.stop()
        self.dispatcher.join()
        deadline = time.monotonic() + 5
        for process in self.processes:
            while process.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if process.poll() is None:
                logger.info('service worker (pid %d) missed the stop '
                            'broadcast; terminating it', process.pid)
                process.terminate()
                try:
                    process.wait(timeout=2)
                except subprocess.TimeoutExpired:
                    logger.warning('service worker (pid %d) survived '
                                   'SIGTERM; sending SIGKILL', process.pid)
                    process.kill()
                    try:
                        process.wait(timeout=5)
                    except subprocess.TimeoutExpired:
                        logger.error('service worker (pid %d) is unreaped '
                                     'after SIGKILL; abandoning it as a '
                                     'zombie', process.pid)
        self.processes = []

    def __enter__(self) -> 'ServiceFleet':
        self.start()
        return self

    def __exit__(self, exc_type: Any, exc_val: Any, exc_tb: Any) -> None:
        self.stop()


def serve(argv: Optional[List[str]] = None) -> int:
    """``petastorm-tpu-throughput serve`` entry: run dispatcher + workers in
    one command until interrupted, printing the service URL and a periodic
    one-line state summary."""
    import argparse
    import json
    parser = argparse.ArgumentParser(
        description='Run a petastorm-tpu input-service fleet '
                    '(dispatcher + decode workers) on this host')
    parser.add_argument('--host', default='127.0.0.1')
    parser.add_argument('--port', type=int, default=8780,
                        help='client port (workers register on port+1)')
    parser.add_argument('--workers', type=int, default=4,
                        help='decode workers to spawn (0 = dispatcher only; '
                             'point remote service_worker processes at the '
                             'worker endpoint)')
    parser.add_argument('--cache-dir', default=None,
                        help='shared Arrow-IPC rowgroup cache directory '
                             '(warm across every client reading the same '
                             'dataset)')
    parser.add_argument('--cache-size-limit', type=int, default=None,
                        help='shared cache size limit in bytes')
    parser.add_argument('--admission-window', type=int,
                        default=DEFAULT_ADMISSION_WINDOW,
                        help='per-client in-flight window before BUSY')
    parser.add_argument('--item-deadline-s', type=float, default=None,
                        help='per-item wall-clock budget: a worker holding '
                             'one rowgroup longer is deregistered and the '
                             'item re-queued (default: off — catches hung '
                             'decodes that keep heartbeating)')
    parser.add_argument('--autotune', action='store_true',
                        help='arm the closed-loop service autotuner: retunes '
                             'the admission window and live per-client '
                             'in-flight depth from queue-depth/busy signals '
                             '(docs/autotuning.md)')
    parser.add_argument('--no-shm', action='store_true',
                        help='disable the co-located shared-memory result '
                             'path (TCP frames only)')
    parser.add_argument('--metrics-port', type=int, default=None,
                        help='serve the fleet-wide Prometheus scrape '
                             'endpoint (/metrics, /healthz, /vars) on this '
                             'port (0 = ephemeral; default: off) — '
                             'docs/observability.md')
    parser.add_argument('--incidents', action='store_true',
                        help='arm the fleet-wide incident autopsy plane: '
                             'workers black-box-capture bundles on failure '
                             'edges and ship references to the dispatcher, '
                             'which correlates them into state() — '
                             'docs/observability.md "Incident autopsy plane"')
    parser.add_argument('--ledger', nargs='?', const=True, default=None,
                        metavar='PATH',
                        help='arm the durable dispatcher ledger: journal '
                             'token lifecycle to PATH (bare --ledger uses '
                             'the cache dir) so a restarted dispatcher '
                             'resumes mid-epoch — docs/service.md '
                             '"Failure modes"')
    parser.add_argument('--history', nargs='?', const=True, default=None,
                        metavar='PATH',
                        help='arm the longitudinal observatory: record one '
                             'run record per dispatcher life to PATH (bare '
                             '--history uses the cache dir) and watch the '
                             'items-served rate with the live regression '
                             'sentinel — docs/observability.md '
                             '"Longitudinal observatory"')
    parser.add_argument('--state-interval', type=float, default=30.0,
                        help='seconds between state summaries (0 = quiet)')
    parser.add_argument('--json', action='store_true',
                        help='print state summaries as JSON lines')
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    fleet = ServiceFleet(
        workers=args.workers, host=args.host, port=args.port,
        cache_dir=args.cache_dir, cache_size_limit=args.cache_size_limit,
        shm_results=not args.no_shm, admission_window=args.admission_window,
        item_deadline_s=args.item_deadline_s, autotune=args.autotune,
        metrics_port=args.metrics_port, incidents=args.incidents or None,
        ledger=args.ledger, history=args.history)
    url = fleet.start()
    print('petastorm-tpu input service running at {} ({} worker(s); '
          'workers register on port {}). Point readers at '
          'make_reader(..., service_url={!r}); Ctrl-C stops the fleet.'
          .format(url, args.workers, args.port + 1, url))
    if fleet.dispatcher.metrics_url is not None:
        print('fleet metrics: {}/metrics (Prometheus text), /healthz, /vars'
              .format(fleet.dispatcher.metrics_url))
    try:
        while True:
            time.sleep(args.state_interval or 3600.0)
            if args.state_interval:
                state = fleet.state()
                if args.json:
                    print(json.dumps(state))
                else:
                    print('service: {} worker(s), {} client(s), queue depth '
                          '{}, {} in flight, {} busy rejection(s), {} item(s) '
                          're-queued'.format(
                              len(state['workers']), len(state['clients']),
                              state['queue_depth'], state['in_flight'],
                              state['busy_rejections'],
                              state['items_requeued']))
    except KeyboardInterrupt:
        print('stopping the fleet...')
    finally:
        fleet.stop()
    return 0
