"""Stateless decode worker for the disaggregated input service.

Entry point executed inside each service worker process (the service-side
mirror of ``workers/process_worker_main.py``): connect a DEALER to the
dispatcher's worker ROUTER, ``register`` a
:class:`~petastorm_tpu.service.wire.WorkerDescriptor`, then pull work —
``w_ready`` up, ``work`` assignments down — until ``w_stop`` (or the parent
process dies, the same orphan watchdog as the in-process pool).

The worker is *stateless by contract*: everything dataset-specific arrives
over the wire. A client's ``open`` blob (dilled ``{worker_class, worker_args,
serializer}`` — in practice :class:`~petastorm_tpu.reader_worker.RowGroupWorker`
plus its ``WorkerSetup``) is attached by the dispatcher to the first ``work``
message each worker sees per setup; the worker instantiates and memoizes the
runtime per setup id (a bounded LRU — old clients' runtimes are shut down,
not hoarded). When the service is configured with a shared cache directory,
the setup's cache is replaced with one fleet-wide
:class:`~petastorm_tpu.cache.ArrowIpcDiskCache`, so a rowgroup decoded for one
job is a warm mmap hit for every other job reading the same dataset — the
amortization argument of the tf.data-service paper (arXiv 2210.14826).

Results ride the :mod:`~petastorm_tpu.workers.serializers` wire codec as
``w_result`` frames over TCP; when the dispatcher flags the owning client as
co-located (same host token) and shm is enabled, the serialized frames are
written into a fresh one-shot ``multiprocessing.shared_memory`` segment
instead and only a CRC-carrying
:class:`~petastorm_tpu.service.wire.ShmResultDescriptor` crosses the wire —
the client maps, verifies, copies out and unlinks. A janitor unlinks any
segment nobody claimed within a grace window, so dropped duplicates and dead
clients cannot leak ``/dev/shm``.

Heartbeats ride a private DEALER socket (``w_heartbeat`` sequence stamps, the
PR-4 liveness model): the dispatcher detects stamp *change* on its own clock
and deregisters a worker whose stamp stalls, re-queuing its in-flight items.

Fleet metrics plane (docs/observability.md "Live metrics plane"): every few
heartbeats the same socket also carries a ``w_metrics`` frame — the worker's
CUMULATIVE telemetry registry snapshot
(:class:`~petastorm_tpu.service.wire.WorkerMetricsUpdate`). The worker's
registry is a consumer-side TEE of the stage-time sidecars each published
batch already carries (the client still gets its own copy untouched), so the
dispatcher's scrape surface shows real per-worker decode/read histograms
without any extra instrumentation on the hot path. Cumulative + seq-guarded:
a dropped or reordered update costs freshness, never correctness."""

from __future__ import annotations

import collections
import logging
import os
import pickle
import sys
import threading
import time
import traceback
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from petastorm_tpu.service.wire import (ShmResultDescriptor, WorkerDescriptor,
                                        WorkerIncidentUpdate,
                                        WorkerMetricsUpdate, host_token)

logger = logging.getLogger(__name__)

#: memoized per-setup runtimes kept per worker (old clients evict LRU)
_SETUP_CACHE_LIMIT = 8
#: heartbeats between ``w_metrics`` snapshots (snapshots are a few hundred
#: bytes, but there is no point re-shipping an unchanged registry every
#: 0.5s stamp)
_METRICS_EVERY_N_BEATS = 4
#: seconds an unclaimed one-shot shm segment survives before the janitor
#: unlinks it (covers dropped duplicate results and departed clients)
_SHM_GRACE_S = 60.0
#: how long to wait for the dispatcher's ``registered`` ack before retrying
_REGISTER_TIMEOUT_MS = 2000


def _watch_parent(parent_pid: int) -> None:
    """Exit if the fleet parent dies, so no orphan workers linger (same
    watchdog as ``workers/process_worker_main.py``)."""
    import psutil
    while True:
        if not psutil.pid_exists(parent_pid):
            os._exit(0)
        time.sleep(1)


def _heartbeat_loop(stop_event: threading.Event, context: Any, endpoint: str,
                    worker_id: int, interval_s: float,
                    metrics_snapshot_fn: Optional[Callable[[], Dict[str, Any]]]
                    = None,
                    incident_refs_fn: Optional[
                        Callable[[], List[Dict[str, Any]]]] = None) -> None:
    """Stamp liveness on a PRIVATE DEALER socket (ZMQ sockets are not
    thread-safe — the main thread owns the work socket). Dropped sends are
    fine: the dispatcher only needs *some* stamp to land inside its (much
    longer) staleness window. Every ``_METRICS_EVERY_N_BEATS`` stamps the
    same socket also carries the worker's cumulative telemetry snapshot as a
    ``w_metrics`` frame (module docstring) — best-effort like the stamps.
    Each beat also drains ``incident_refs_fn`` (bundle references captured by
    the worker's incident recorder since the last beat) and ships every
    reference as its own ``w_incident`` frame (docs/observability.md
    "Incident autopsy plane")."""
    import zmq
    socket = context.socket(zmq.DEALER)
    socket.setsockopt(zmq.SNDHWM, 8)
    socket.setsockopt(zmq.LINGER, 0)
    socket.connect(endpoint)
    seq = 0
    try:
        while not stop_event.wait(interval_s):
            seq += 1
            try:
                socket.send_multipart(
                    [b'w_heartbeat', b'%d' % worker_id, b'%d' % seq],
                    zmq.NOBLOCK)
            except Exception:  # noqa: BLE001 - liveness must never kill a worker
                pass
            if incident_refs_fn is not None:
                try:
                    for reference in incident_refs_fn():
                        update = WorkerIncidentUpdate(worker_id, seq,
                                                      reference)
                        socket.send_multipart(
                            [b'w_incident', update.to_bytes()], zmq.NOBLOCK)
                except Exception:  # noqa: BLE001 - the incident plane must never kill a worker
                    pass
            if (metrics_snapshot_fn is None
                    or seq % _METRICS_EVERY_N_BEATS != 1):
                continue
            try:
                update_m = WorkerMetricsUpdate(worker_id, seq,
                                               metrics_snapshot_fn())
                socket.send_multipart([b'w_metrics', update_m.to_bytes()],
                                      zmq.NOBLOCK)
            except Exception:  # noqa: BLE001 - the metrics plane must never kill a worker
                pass
    finally:
        socket.close(linger=0)


class _ShmPublisher(object):
    """One-shot shared-memory result segments for co-located clients.

    Each published result gets a fresh segment (created, unregistered from
    this process's resource tracker — the CLIENT owns the unlink after
    reading). The janitor reclaims segments nobody consumed within the grace
    window; ``close`` unlinks everything still tracked."""

    def __init__(self, grace_s: float = _SHM_GRACE_S) -> None:
        self._grace_s = grace_s
        self._created: Deque[Tuple[str, float]] = collections.deque()

    def write(self, frames: List[Any],
              checksum: bool = True) -> Optional[ShmResultDescriptor]:
        """Write serialized ``frames`` back-to-back into a fresh segment;
        returns the descriptor, or None when shared memory is unavailable
        (the caller falls back to wire frames)."""
        from multiprocessing import shared_memory
        views = [memoryview(frame) for frame in frames]
        lengths = [view.nbytes for view in views]
        total = sum(lengths)
        try:
            segment = shared_memory.SharedMemory(create=True,
                                                 size=max(total, 1))
        except Exception:  # noqa: BLE001 - no /dev/shm: degrade to the TCP wire
            logger.warning('one-shot shm segment unavailable; publishing '
                           'over the wire', exc_info=True)
            return None
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(segment._name, 'shared_memory')  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001 - tracker internals shifted; janitor unlink still wins
            pass
        try:
            offset = 0
            for view, length in zip(views, lengths):
                segment.buf[offset:offset + length] = view.cast('B')
                offset += length
            crc: Optional[int] = None
            if checksum:
                from petastorm_tpu.workers.integrity import payload_checksum
                crc = payload_checksum(views)
        except Exception:  # noqa: BLE001 - a torn copy must not leak the segment
            # Unregistered above, so nothing else will ever reclaim it:
            # close AND unlink before degrading this one result to the wire.
            logger.warning('one-shot shm segment write failed; publishing '
                           'over the wire', exc_info=True)
            segment.close()
            try:
                segment.unlink()
            except (FileNotFoundError, OSError):
                pass
            return None
        name = segment.name
        segment.close()
        self._created.append((name, time.monotonic()))
        return ShmResultDescriptor(name, lengths, crc)

    def _unlink(self, name: str) -> None:
        from multiprocessing import shared_memory
        try:
            segment = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            return  # the client consumed and unlinked it — the normal path
        try:
            from multiprocessing import resource_tracker
            resource_tracker.unregister(segment._name, 'shared_memory')  # type: ignore[attr-defined]
        except Exception:  # noqa: BLE001 - tracker internals shifted
            pass
        try:
            segment.unlink()
        except FileNotFoundError:
            pass
        finally:
            segment.close()

    def janitor(self) -> None:
        """Unlink segments past the grace window (nobody claimed them)."""
        now = time.monotonic()
        while self._created and now - self._created[0][1] > self._grace_s:
            name, _ = self._created.popleft()
            self._unlink(name)

    def close(self) -> None:
        """Unlink every segment still tracked (worker shutdown)."""
        while self._created:
            name, _ = self._created.popleft()
            self._unlink(name)


class _SetupRuntime(object):
    """One client setup materialized on this worker: the decode worker
    instance plus the wire serializer its results ship through."""

    __slots__ = ('worker', 'serializer')

    def __init__(self, worker: Any, serializer: Any) -> None:
        self.worker = worker
        self.serializer = serializer


def _build_runtime(setup_blob: bytes, worker_id: int,
                   publish: Callable[[Any], None],
                   shared_cache: Any) -> _SetupRuntime:
    """Materialize a client's dilled ``open`` payload into a runtime; when the
    fleet ships a shared cache, it replaces the setup's own (the service owns
    cache placement — that is the whole point of disaggregation)."""
    import dill
    spec = dill.loads(setup_blob)
    worker_class = spec['worker_class']
    worker_args = spec['worker_args']
    serializer = spec['serializer']
    if shared_cache is not None and hasattr(worker_args, 'cache'):
        worker_args.cache = shared_cache
    worker = worker_class(worker_id, publish, worker_args)
    return _SetupRuntime(worker, serializer)


def main(bootstrap_path: str) -> None:
    """Service-worker process entry: load the pickled bootstrap file, connect
    to the dispatcher's worker endpoint, register, and pull/process work items
    until ``w_stop`` (or parent death)."""
    with open(bootstrap_path, 'rb') as f:
        bootstrap = pickle.load(f)
    try:
        os.unlink(bootstrap_path)
    except OSError:
        pass

    import zmq

    worker_id = int(bootstrap['worker_id'])
    endpoint = bootstrap['worker_endpoint']
    heartbeat_interval_s = float(bootstrap.get('heartbeat_interval_s', 0.5))
    shm_results = bool(bootstrap.get('shm_results', True))
    parent_pid = bootstrap.get('parent_pid')
    if parent_pid is not None:
        threading.Thread(target=_watch_parent, args=(parent_pid,),
                         daemon=True).start()

    shared_cache: Any = None
    cache_dir = bootstrap.get('cache_dir')
    if cache_dir:
        from petastorm_tpu.cache import ArrowIpcDiskCache
        shared_cache = ArrowIpcDiskCache(
            cache_dir, int(bootstrap.get('cache_size_limit') or 10 << 30),
            int(bootstrap.get('cache_row_size_estimate') or 0))

    context = zmq.Context()
    socket = context.socket(zmq.DEALER)
    heartbeat_stop = threading.Event()
    heartbeat_thread: Optional[threading.Thread] = None
    incident_recorder: Any = None
    shm_publisher: Optional[_ShmPublisher] = None
    # One try/finally over registration and the work loop: an uncaught
    # error must still close the socket and terminate the context, or
    # zmq teardown hangs the exiting process and the fleet only notices
    # via the staleness watchdog instead of the exit code.
    try:
        socket.connect(endpoint)
        descriptor = WorkerDescriptor(
            worker_id=worker_id, pid=os.getpid(), host=host_token(),
            heartbeat_interval_s=heartbeat_interval_s, shm_results=shm_results)
        registered = False
        while not registered:
            socket.send_multipart([b'register', descriptor.to_bytes()])
            if not socket.poll(_REGISTER_TIMEOUT_MS, zmq.POLLIN):
                continue  # dispatcher not up yet — re-announce
            frames = socket.recv_multipart()
            kind = frames[0]
            if kind == b'registered':
                registered = True

        # Fleet metrics plane (module docstring): this worker's registry TEEs
        # the stage-time sidecars of every published batch (merge_stage_times is
        # read-only over the sidecar dict — the owning client's copy is
        # untouched) and ships cumulative snapshots on the heartbeat socket.
        from petastorm_tpu.telemetry import MetricsRegistry
        worker_metrics = MetricsRegistry()

        # Incident autopsy plane (docs/observability.md): when the fleet arms
        # incidents, this worker captures bundles locally on its own anomaly
        # edges (breaker closed->open, quarantined rowgroups) and the heartbeat
        # thread ships each bundle's compact reference as a ``w_incident`` frame.
        incident_refs_fn: Optional[Callable[[], List[Dict[str, Any]]]] = None
        incidents = bootstrap.get('incidents')
        if incidents:
            from petastorm_tpu.resilience import default_board
            from petastorm_tpu.telemetry.incident import (IncidentRecorder,
                                                          default_incident_home,
                                                          resolve_incident_policy)
            policy = resolve_incident_policy(incidents)
            # per-worker subdirectory: co-located workers must not race each
            # other's bundle sequence numbers in one shared home
            home = os.path.join(default_incident_home(cache_dir),
                                'worker-{}'.format(worker_id))
            incident_recorder = IncidentRecorder(home, policy,
                                                 registry=worker_metrics)
            incident_recorder.add_source('metrics', worker_metrics.snapshot)
            incident_recorder.add_source('breakers', default_board().snapshot)
            default_board().observe_transitions(
                incident_recorder.on_breaker_transition)
            incident_refs_fn = incident_recorder.drain_references

        if heartbeat_interval_s > 0:
            heartbeat_thread = threading.Thread(
                target=_heartbeat_loop,
                args=(heartbeat_stop, context, endpoint, worker_id,
                      heartbeat_interval_s, worker_metrics.snapshot,
                      incident_refs_fn),
                daemon=True)
            heartbeat_thread.start()

        shm_publisher = _ShmPublisher() if shm_results else None
        runtimes: 'collections.OrderedDict[bytes, _SetupRuntime]' = \
            collections.OrderedDict()
        current_token = [b'']
        current_attempt = [b'0']
        current_colocated = [False]
        current_serializer: List[Any] = [None]

        def publish(result: Any) -> None:
            from petastorm_tpu.telemetry.spans import stage_span
            stage_times = getattr(result, 'telemetry', None)
            if stage_times:
                worker_metrics.merge_stage_times(stage_times)
            if incident_recorder is not None:
                record = getattr(result, 'quarantine', None)
                if record is not None:
                    # same kind split as Reader._note_item_consumed: a reaped
                    # hang and a skipped rowgroup are distinct autopsy causes
                    trigger_kind = ('watchdog_reap' if record.reason == 'hang'
                                    else 'quarantine')
                    incident_recorder.trigger(
                        trigger_kind,
                        ctx=(record.epoch, record.piece_index, record.attempts),
                        args=record.as_dict())
            with stage_span('serialize'):
                frames = current_serializer[0].serialize(result)
            if shm_publisher is not None and current_colocated[0]:
                shm_descriptor = shm_publisher.write(frames)
                if shm_descriptor is not None:
                    socket.send_multipart(
                        [b'w_result_shm', current_token[0], current_attempt[0],
                         shm_descriptor.to_bytes()])
                    return
            socket.send_multipart(
                [b'w_result', current_token[0], current_attempt[0]]
                + list(frames))

        import dill
        socket.send_multipart([b'w_ready'])
        stopping = False
        idle_polls = 0
        while not stopping:
            if not socket.poll(1000, zmq.POLLIN):
                if shm_publisher is not None:
                    shm_publisher.janitor()
                # Idle re-announce (docs/service.md "Restarting with a ledger"):
                # a dispatcher that restarted while we sat idle never sees a
                # w_ready from us and so never learns we exist. Periodically
                # re-offer readiness — a live dispatcher that already knows us
                # treats the duplicate as a no-op (identity already in its ready
                # set), a restarted one answers with w_rejoin below.
                idle_polls += 1
                if idle_polls >= 5:
                    idle_polls = 0
                    socket.send_multipart([b'w_ready'])
                continue
            idle_polls = 0
            frames = socket.recv_multipart()
            kind = frames[0]
            if kind == b'w_stop':
                stopping = True
                continue
            if kind == b'registered':
                continue  # duplicate ack from the registration retry loop
            if kind == b'w_rejoin':
                # a restarted dispatcher does not know this identity: replay the
                # registration handshake inline (no blocking retry loop — the
                # dispatcher is demonstrably alive, it just answered us)
                socket.send_multipart([b'register', descriptor.to_bytes()])
                socket.send_multipart([b'w_ready'])
                continue
            if kind != b'work' or len(frames) < 7:
                continue  # unknown kind from a newer dispatcher: ignore
            token, setup_id, blob = frames[1], frames[2], frames[3]
            attempt, colocate_flag = frames[4], frames[5]
            setup_blob = frames[6]
            runtime = runtimes.get(setup_id)
            if runtime is None:
                if not setup_blob:
                    # the dispatcher believed this worker knew the setup (e.g. a
                    # pre-restart identity collision) — ask for a re-ship
                    socket.send_multipart([b'w_need_setup', token])
                    socket.send_multipart([b'w_ready'])
                    continue
                try:
                    runtime = _build_runtime(setup_blob, worker_id, publish,
                                             shared_cache)
                except Exception as exc:  # noqa: BLE001 - ship to the owning client
                    error_blob = pickle.dumps((exc, traceback.format_exc()))
                    socket.send_multipart([b'w_error', token, attempt,
                                           error_blob])
                    socket.send_multipart([b'w_ready'])
                    continue
                runtimes[setup_id] = runtime
                while len(runtimes) > _SETUP_CACHE_LIMIT:
                    _, evicted = runtimes.popitem(last=False)
                    evicted.worker.shutdown()
            else:
                runtimes.move_to_end(setup_id)
            current_token[0] = token
            current_attempt[0] = attempt
            current_colocated[0] = colocate_flag == b'1'
            current_serializer[0] = runtime.serializer
            from petastorm_tpu.telemetry.tracing import set_dispatch_attempt
            set_dispatch_attempt(int(attempt))
            try:
                # the kwargs decode belongs INSIDE the error funnel: a poison
                # blob (dill version skew, client-only modules) must fail that
                # one item to its owner, not kill this worker — the dispatcher
                # would re-queue it onto the next worker and fell the whole fleet
                kwargs = dill.loads(blob)
                runtime.worker.process(**kwargs)
                socket.send_multipart([b'w_done', token, attempt])
            except Exception as exc:  # noqa: BLE001 - ship to the owning client
                error_blob = pickle.dumps((exc, traceback.format_exc()))
                socket.send_multipart([b'w_error', token, attempt, error_blob])
            current_token[0] = b''
            current_colocated[0] = False
            if shm_publisher is not None:
                shm_publisher.janitor()
            socket.send_multipart([b'w_ready'])

        socket.send_multipart([b'w_leave'])
        for runtime in runtimes.values():
            runtime.worker.shutdown()
    finally:
        heartbeat_stop.set()
        if heartbeat_thread is not None:
            heartbeat_thread.join(timeout=2 * heartbeat_interval_s + 1)
        if incident_recorder is not None:
            incident_recorder.close()
        if shm_publisher is not None:
            shm_publisher.close()
        socket.close(linger=1000)
        context.term()


if __name__ == '__main__':
    main(sys.argv[1])
