"""The service dispatcher: fair-share broker between reader clients and decode workers.

Socket topology (docs/service.md):

    client DEALER  <─>  ROUTER (client endpoint, ``service_url`` port)
    worker DEALER  <─>  ROUTER (worker endpoint, ``port + 1``)

Clients ``hello``/``open`` (register + ship a dilled worker setup), then
``submit`` rowgroup work items; workers ``register`` (a
:class:`~petastorm_tpu.service.wire.WorkerDescriptor`), announce idleness with
``w_ready`` and receive ``work`` assignments — the same pull-based dispatch as
the in-process pool (``workers/process_pool.py``), so nothing ever queues in a
dead worker's socket buffer and every assignment is attributable.

Scheduling is **deficit round robin** per client
(:class:`FairShareScheduler`): each visit tops a client's deficit up by one
quantum and serves while the deficit covers the next item, so N clients with
pending work split the worker fleet evenly regardless of how fast each one
submits — the skewed-demand fairness the tf.data-service model calls for
(arXiv 2210.14826). **Admission control** bounds each client to a fixed
in-flight window (queued + assigned); a submit beyond it is rejected with an
explicit ``busy`` reply the client backs off on, so one greedy reader can
neither queue unboundedly nor starve the fleet.

**Elastic workers**: workers join (``register``) and leave (``w_leave``, or
just vanish) at any time. Liveness rides the PR-4 watchdog model: workers
stamp ``w_heartbeat`` sequence numbers, the dispatcher detects *change*
consumer-side (no cross-process clocks), and a worker whose stamp stalls past
its staleness window is deregistered — its in-flight items re-enter the owning
clients' queues (attempt-bumped, so a stale straggler ack can never retire a
redelivered item: the exact protocol ``process_pool.py`` uses). An item
re-queued more than ``max_item_attempts`` times fails loudly to its client
instead of poisoning the fleet forever.

The ``state`` request returns a JSON snapshot (clients, workers, queue depths,
fair-share debts) surfaced through ``Reader.diagnostics['service']``, doctor,
and the ``petastorm-tpu-throughput serve`` CLI."""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import math
import pickle
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

from petastorm_tpu.service.wire import (MAX_COST_HINT, MIN_COST_HINT,
                                        WorkerDescriptor, decode_cost)

logger = logging.getLogger(__name__)

#: client-side message kinds (client ROUTER): requests up, replies/results down
MSG_HELLO, MSG_WELCOME = b'hello', b'welcome'
MSG_OPEN, MSG_OPENED = b'open', b'opened'
MSG_SUBMIT, MSG_ACCEPT, MSG_BUSY = b'submit', b'accept', b'busy'
#: submit from an identity this dispatcher does not know (restart, or a
#: TTL-collected idle client): the client must re-``hello``/``open`` and
#: resubmit — how an epoch survives a dispatcher restart
MSG_REJOIN = b'rejoin'
MSG_RESULT, MSG_RESULT_SHM, MSG_ERROR = b'result', b'result_shm', b'error'
MSG_SHM_FAIL, MSG_BYE, MSG_STATE = b'shm_fail', b'bye', b'state'
#: ledger-epoch handshake (docs/service.md "Dispatcher crash with a
#: ledger"): a client probes with ``ledger_sync``; the ``ledger_state``
#: reply says whether this dispatcher knows the client and which ledger
#: epoch it serves — an unknown/epoch-changed answer means the client's
#: in-flight tokens died with the previous incarnation and must re-arm
MSG_LEDGER_SYNC, MSG_LEDGER_STATE = b'ledger_sync', b'ledger_state'
#: worker-side message kinds (worker ROUTER): registration/results up, work down
MSG_REGISTER, MSG_REGISTERED = b'register', b'registered'
MSG_W_READY, MSG_WORK, MSG_W_STOP = b'w_ready', b'work', b'w_stop'
MSG_W_HEARTBEAT, MSG_W_RESULT, MSG_W_RESULT_SHM = (b'w_heartbeat', b'w_result',
                                                   b'w_result_shm')
#: cumulative worker telemetry snapshot riding the heartbeat socket (the
#: fleet metrics plane — docs/observability.md "Live metrics plane")
MSG_W_METRICS = b'w_metrics'
#: worker-captured incident-bundle reference riding the heartbeat socket
#: (the fleet incident plane — docs/observability.md "Incident autopsy
#: plane")
MSG_W_INCIDENT = b'w_incident'
MSG_W_DONE, MSG_W_ERROR = b'w_done', b'w_error'
MSG_W_NEED_SETUP, MSG_W_LEAVE = b'w_need_setup', b'w_leave'
#: worker-side restart re-adoption: a ``w_ready`` from an identity this
#: dispatcher never registered (it belongs to the previous incarnation)
#: is answered with ``w_rejoin`` — the worker re-``register``s and the
#: fleet heals without respawning a single process
MSG_W_REJOIN = b'w_rejoin'

#: default per-client in-flight window (queued + assigned) before ``busy``
DEFAULT_ADMISSION_WINDOW = 16
#: default DRR quantum (deficit credit per scheduling visit). Items are
#: charged their MEASURED cost when the submit carries a cost hint from the
#: client's cost-aware scheduler (docs/performance.md "Cost-aware
#: scheduling") and unit cost otherwise — so with hints, a client burning
#: heavy rowgroups is served proportionally fewer of them per round.
DEFAULT_QUANTUM = 1.0
#: clamp for submit cost hints: one pathological ledger entry must neither
#: monopolize the deficit budget nor make an item effectively free. The
#: bounds are wire.py's MIN_COST_HINT/MAX_COST_HINT (aliased above): a
#: two-sided wire contract — the client scheduler prices into the SAME
#: range, and one shared constant keeps the sides from drifting apart.
MIN_ITEM_COST = MIN_COST_HINT
MAX_ITEM_COST = MAX_COST_HINT
#: a (clamped, median-relative) item cost at or above this routes via the
#: least-loaded ready worker instead of FIFO — heavy rowgroups spread across
#: the fleet instead of piling onto whichever worker asked first
HEAVY_ITEM_COST = 2.0
#: same-cause incident references landing within this window collapse into
#: ONE fleet incident (docs/observability.md "Incident autopsy plane")
INCIDENT_CORRELATION_WINDOW_S = 30.0
#: bound on the correlated fleet-incident list kept in ``state()``
MAX_FLEET_INCIDENTS = 32
#: how long a worker's heartbeat stamp may go unchanged before it counts as
#: departed (floored at 4x its own declared heartbeat interval, the same
#: jitter margin the in-process watchdog enforces)
DEFAULT_STALE_TIMEOUT_S = 15.0
#: re-dispatch budget per work item across worker deaths — a rowgroup that
#: kills every worker it lands on must fail loudly, not roam the fleet forever
DEFAULT_MAX_ITEM_ATTEMPTS = 5
#: how long a client may go completely silent (no hello/submit/shm_fail)
#: before the dispatcher garbage-collects its record + setups — an alive
#: client that got collected anyway simply ``rejoin``s on its next submit
DEFAULT_CLIENT_TTL_S = 900.0
#: dataset token the dispatcher's run-history records are keyed by — the
#: service serves many datasets, so its longitudinal series is keyed by the
#: service itself, not any one dataset (telemetry/history.py)
SERVICE_DATASET_TOKEN = 'service'


class _ClientState(object):
    """Dispatcher-side record of one connected reader client."""

    __slots__ = ('key', 'name', 'host', 'window', 'requested_window',
                 'queue', 'assigned', 'deficit', 'served', 'busy_rejections',
                 'last_seen', 'setup_ids')

    def __init__(self, key: bytes, name: str, host: str, window: int,
                 now: float, requested_window: Optional[int] = None) -> None:
        self.key = key
        self.name = name
        self.host = host
        self.window = window
        #: the window the client ASKED for at hello (None = follow the
        #: admission cap): a raised cap lifts follow-the-cap clients with it,
        #: but never silently widens a client that asked for less
        self.requested_window = requested_window
        self.queue: Deque[int] = collections.deque()
        self.assigned: Set[int] = set()
        self.deficit = 0.0
        self.served = 0
        self.busy_rejections = 0
        self.last_seen = now
        self.setup_ids: Set[bytes] = set()

    def in_flight(self) -> int:
        """Items this client currently owns inside the service."""
        return len(self.queue) + len(self.assigned)


class _WorkerState(object):
    """Dispatcher-side record of one registered decode worker."""

    __slots__ = ('key', 'descriptor', 'assigned', 'known_setups',
                 'hb_seq', 'hb_changed_at', 'cost_in_flight', 'cost_served')

    def __init__(self, key: bytes, descriptor: WorkerDescriptor,
                 now: float) -> None:
        self.key = key
        self.descriptor = descriptor
        self.assigned: Set[int] = set()
        self.known_setups: Set[bytes] = set()
        self.hb_seq = -1
        self.hb_changed_at = now
        #: measured cost currently assigned / retired on this worker — the
        #: least-loaded routing signal for heavy items (module constants)
        self.cost_in_flight = 0.0
        self.cost_served = 0.0


class _TokenState(object):
    """One submitted work item, alive until done-acked (or failed)."""

    __slots__ = ('token', 'client_key', 'client_token', 'setup_id', 'blob',
                 'attempt', 'worker_key', 'delivered', 'shm_ok', 'cost')

    def __init__(self, token: int, client_key: bytes, client_token: bytes,
                 setup_id: bytes, blob: bytes, cost: float = 1.0) -> None:
        self.token = token
        self.client_key = client_key
        self.client_token = client_token
        self.setup_id = setup_id
        self.blob = blob
        self.attempt = 0
        self.worker_key: Optional[bytes] = None
        self.delivered = False
        #: measured (median-relative) cost charged by the DRR; 1.0 when the
        #: submit carried no hint — the historical uniform-unit behavior
        self.cost = cost
        #: cleared on the first shm delivery failure (``shm_fail``): the
        #: redelivery must ride plain wire frames — a false co-location match
        #: (same hostname, different namespaces) would otherwise loop forever
        self.shm_ok = True


class Assignment(object):
    """One scheduling decision: which worker runs which item, with everything
    the dispatcher needs to build the ``work`` message (the setup blob is
    attached only the first time this worker sees this setup)."""

    __slots__ = ('worker_key', 'token', 'setup_id', 'blob', 'attempt',
                 'colocated', 'setup_blob')

    def __init__(self, worker_key: bytes, token: int, setup_id: bytes,
                 blob: bytes, attempt: int, colocated: bool,
                 setup_blob: Optional[bytes]) -> None:
        self.worker_key = worker_key
        self.token = token
        self.setup_id = setup_id
        self.blob = blob
        self.attempt = attempt
        self.colocated = colocated
        self.setup_blob = setup_blob


class FairShareScheduler(object):
    """Socket-free scheduling core: DRR fair share, admission control, token
    lifecycle and worker liveness — everything the dispatcher decides, none of
    what it transports. All clocks are injected (``clock``) so the fairness
    and staleness behavior is unit-testable deterministically."""

    def __init__(self, admission_window: int = DEFAULT_ADMISSION_WINDOW,
                 quantum: float = DEFAULT_QUANTUM,
                 stale_timeout_s: float = DEFAULT_STALE_TIMEOUT_S,
                 max_item_attempts: int = DEFAULT_MAX_ITEM_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 client_ttl_s: float = DEFAULT_CLIENT_TTL_S,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if quantum <= 0:
            raise ValueError('quantum must be > 0, got {!r}'.format(quantum))
        if admission_window < 1:
            raise ValueError('admission_window must be >= 1')
        self.admission_window = admission_window
        self.quantum = quantum
        self.stale_timeout_s = stale_timeout_s
        self.max_item_attempts = max_item_attempts
        #: optional per-item wall-clock budget (the service-side analog of the
        #: pool's ``item_deadline_s`` watchdog): a worker holding one item
        #: longer is treated exactly like a stale-heartbeat worker — its
        #: heartbeat thread keeps stamping through a wedged decode, so
        #: liveness alone cannot see a hung item
        self.item_deadline_s = item_deadline_s
        self.client_ttl_s = client_ttl_s
        self._clock = clock
        self._lock = threading.RLock()
        self._clients: Dict[bytes, _ClientState] = {}
        self._workers: Dict[bytes, _WorkerState] = {}
        self._worker_id_index: Dict[int, bytes] = {}
        self._tokens: Dict[int, _TokenState] = {}
        self._next_token = 0
        self._active: Deque[bytes] = collections.deque()  # clients w/ queued work
        self._ready_workers: Deque[bytes] = collections.deque()
        self._setups: Dict[bytes, bytes] = {}
        self._assign_time: Dict[int, float] = {}
        # ------------------------------------------------- durable ledger
        #: optional TokenLedger (service/ledger.py) the dispatcher arms;
        #: every lifecycle edge below journals through ``_journal`` so a
        #: restarted dispatcher can replay the epoch's token history
        self.journal: Any = None
        #: the ledger epoch this scheduler serves (0 = unarmed/first life);
        #: reported in the ``ledger_state`` handshake so re-adopting clients
        #: can tell a restart from a slow dispatcher
        self.ledger_epoch = 0
        #: pre-crash delivered tokens recovered by replay: a straggler
        #: ``w_result`` for one of these is a duplicate even though no live
        #: _TokenState remembers it — the dispatcher-side dedup that used to
        #: die with the process
        self._replay_delivered: Set[int] = set()
        self.replay_info: Optional[Dict[str, Any]] = None
        # -------------------------------------------- elastic resharding
        #: token -> preferred worker id from the last reshard; honored when
        #: that worker is ready, falls back to the normal pick otherwise
        self._preferred_worker: Dict[int, int] = {}
        self.resharded = 0
        # ----------------------------------------------------- aggregates
        self.busy_rejections = 0
        self.results_dropped = 0
        self.items_requeued = 0
        self.items_failed = 0
        self.items_served = 0
        self.workers_registered_total = 0
        self.workers_departed = 0

    # -------------------------------------------------------------- ledger

    def _journal(self, kind: str, **fields: Any) -> None:
        """Append one lifecycle record to the armed TokenLedger (no-op when
        the ledger is off; a failing journal degrades durability, never
        dispatch — the writer already swallows I/O errors)."""
        journal = self.journal
        if journal is not None:
            journal.append_record(kind, **fields)

    def adopt_replay(self, replay: Any, epoch: int) -> None:
        """Adopt a ledger replay at startup: restore token-counter
        monotonicity and the delivered-token dedup set, and remember the
        ledger epoch the handshake reports. Clients and setup blobs are NOT
        rebuilt here — live clients re-adopt themselves via the
        ``ledger_sync`` handshake (the blobs only they hold)."""
        with self._lock:
            # Epoch-scope the token space: a corrupt journal replays only the
            # prefix before the bad frame, so ``replay.next_token`` can be
            # stale — a restarted dispatcher would reissue token numbers, and
            # a ZMQ-buffered straggler w_result from the dead incarnation
            # would then route to the wrong client request. Basing each
            # incarnation at ``epoch << 40`` keeps token ranges disjoint
            # across restarts (the ledger bumps the epoch on every open).
            self._next_token = max(self._next_token, replay.next_token,
                                   epoch << 40)
            self._replay_delivered = set(replay.delivered)
            self.ledger_epoch = epoch
            self.resharded = replay.resharded
            self.replay_info = replay.as_dict()

    # ----------------------------------------------------------- resharding

    def reshard(self, reason: str) -> Optional[Dict[str, Any]]:
        """Re-split the UNDELIVERED work across the current worker set after
        an elastic join/leave: walk clients in sorted-name order and each
        client's queue in ventilation order (the lineage contract — the
        order is never reshuffled, only the token->worker placement moves)
        and deal tokens round-robin across sorted worker ids. Returns a
        summary for the reshard trace/incident event, or None when there is
        nothing to re-split."""
        with self._lock:
            worker_ids = sorted(w.descriptor.worker_id
                                for w in self._workers.values())
            self._preferred_worker.clear()
            if not worker_ids:
                return None
            undelivered: List[int] = []
            for key in sorted(self._clients,
                              key=lambda k: self._clients[k].name):
                undelivered.extend(self._clients[key].queue)
            if not undelivered:
                return None
            for index, token in enumerate(undelivered):
                self._preferred_worker[token] = \
                    worker_ids[index % len(worker_ids)]
            self.resharded += 1
            summary = {'reason': reason, 'workers': len(worker_ids),
                       'undelivered': len(undelivered),
                       'resharded': self.resharded}
        self._journal('reshard', **summary)
        return summary

    # ------------------------------------------------------------- autotune

    def set_admission_window(self, value: int) -> int:
        """Bounded runtime retune of the admission cap (docs/autotuning.md):
        new clients hello against the new cap; live clients whose window
        exceeds it are clamped down, and clients that follow the cap (hello'd
        without a window, or asked for more than the cap allows) are lifted
        with it — but a client that asked for less than the new cap is never
        silently widened past its request. Returns the applied value."""
        with self._lock:
            value = max(1, int(value))
            self.admission_window = value
            for client in self._clients.values():
                requested = client.requested_window
                client.window = min(requested or value, value)
            return value

    def set_client_windows(self, value: int) -> int:
        """Runtime retune of every live client's in-flight depth, clamped to
        ``[1, admission_window]`` (docs/autotuning.md) — the per-client half
        of the service autotuner. Returns the applied value."""
        with self._lock:
            value = max(1, min(int(value), self.admission_window))
            for client in self._clients.values():
                client.window = value
            return value

    def effective_client_window(self) -> int:
        """The smallest live client window (the admission cap when no client
        is connected) — the service-client-window knob's current value."""
        with self._lock:
            if not self._clients:
                return self.admission_window
            return min(client.window for client in self._clients.values())

    def autotune_snapshot(self) -> Dict[str, Any]:
        """A telemetry-shaped snapshot of the scheduler's control signals
        (cumulative counters + current gauges) for the autotune controller's
        window deltas and ``attribute_bottleneck``'s service advisories."""
        with self._lock:
            return {
                'histograms': {},
                'counters': {'service_busy': self.busy_rejections,
                             'service_resubmit': self.items_requeued},
                'gauges': {
                    'service_queue_depth': float(sum(
                        len(c.queue) for c in self._clients.values())),
                    'service_ready_workers': float(len(self._ready_workers)),
                    'service_workers': float(len(self._workers)),
                    'service_admission_window': float(self.admission_window),
                    # inlined effective_client_window (we already hold _lock)
                    'service_client_window': float(
                        min((c.window for c in self._clients.values()),
                            default=self.admission_window)),
                },
            }

    # ------------------------------------------------------------- clients

    def add_client(self, key: bytes, name: str, host: str,
                   window: Optional[int] = None) -> int:
        """Register (or re-register) a client; returns its effective window."""
        with self._lock:
            effective = min(window or self.admission_window,
                            self.admission_window)
            self._clients[key] = _ClientState(key, name, host, effective,
                                              self._clock(),
                                              requested_window=window)
            self._journal('client', name=name, host=host, window=effective)
            return effective

    def client_window(self, key: bytes) -> int:
        """The client's CURRENT in-flight window — piggybacked on every
        accept/busy reply so live clients adopt dispatcher-side retuning
        (the autotune window knobs would otherwise move a limit connected
        clients never observe; docs/autotuning.md)."""
        with self._lock:
            client = self._clients.get(key)
            return client.window if client is not None else self.admission_window

    def has_client(self, key: bytes) -> bool:
        """True when ``key`` is a registered client. A submit from an
        unregistered identity (dispatcher restart, or a TTL-collected idle
        client) gets a ``rejoin`` reply instead of a misleading ``busy``."""
        with self._lock:
            return key in self._clients

    def remove_client(self, key: bytes) -> None:
        """Drop a departed client: its queued items and setups die, its
        assigned items finish on the workers and their results are dropped
        on delivery."""
        with self._lock:
            client = self._clients.pop(key, None)
            if client is None:
                return
            for token in client.queue:
                self._tokens.pop(token, None)
                self._preferred_worker.pop(token, None)
            for setup_id in client.setup_ids:
                self._setups.pop(setup_id, None)
            try:
                self._active.remove(key)
            except ValueError:
                pass

    def expired_clients(self) -> List[bytes]:
        """Clients silent past ``client_ttl_s`` with nothing in flight —
        garbage for the caller to :meth:`remove_client` (a live client that
        gets collected anyway just ``rejoin``s on its next submit)."""
        with self._lock:
            now = self._clock()
            return [key for key, client in self._clients.items()
                    if not client.in_flight()
                    and now - client.last_seen > self.client_ttl_s]

    def add_setup(self, client_key: bytes, setup_id: bytes,
                  blob: bytes) -> None:
        """Store a client's dilled worker setup for lazy per-worker shipping
        (owned by the client — collected with it)."""
        with self._lock:
            self._setups[setup_id] = blob
            client = self._clients.get(client_key)
            if client is not None:
                client.setup_ids.add(setup_id)
                client.last_seen = self._clock()
            self._journal(
                'setup', setup=setup_id.decode('ascii', 'replace'),
                digest=hashlib.blake2b(blob, digest_size=8).hexdigest(),
                client=client.name if client is not None else None)

    def submit(self, client_key: bytes, client_token: bytes, setup_id: bytes,
               blob: bytes, cost: float = 1.0) -> Optional[int]:
        """Admission-checked submit: returns the global token, or None when
        the client's window is full (the caller replies ``busy``). ``cost``
        is the client's measured-cost hint (clamped; 1.0 = the historical
        uniform unit) — what the DRR charges and the heavy-routing keys on."""
        with self._lock:
            client = self._clients.get(client_key)
            if client is None:
                return None
            client.last_seen = self._clock()
            if client.in_flight() >= client.window:
                client.busy_rejections += 1
                self.busy_rejections += 1
                return None
            token = self._next_token
            self._next_token += 1
            cost = max(MIN_ITEM_COST, min(MAX_ITEM_COST, float(cost)))
            self._tokens[token] = _TokenState(token, client_key, client_token,
                                              setup_id, blob, cost=cost)
            client.queue.append(token)
            if client.key not in self._active:
                self._active.append(client.key)
            self._journal('issued', token=token, client=client.name,
                          cost=cost)
            return token

    # ------------------------------------------------------------- workers

    def add_worker(self, key: bytes, descriptor: WorkerDescriptor) -> bool:
        """Register a worker (elastic join — any time, including mid-epoch).
        Idempotent per identity: a re-sent ``register`` (slow-ack retry) must
        neither reset the worker's assignment record nor double-count it.
        Returns True only for a NEW registration — the edge the caller
        reshards on."""
        with self._lock:
            if key in self._workers:
                return False
            self._workers[key] = _WorkerState(key, descriptor, self._clock())
            self._worker_id_index[descriptor.worker_id] = key
            self.workers_registered_total += 1
            return True

    def remove_worker(self, key: bytes) -> List[Tuple[int, bytes, bytes]]:
        """Deregister a worker (leave, or reaped as stale) and re-queue its
        in-flight items at the FRONT of their owners' queues (oldest work
        first, same as the pool's respawn path). Returns the items that
        exhausted their attempt budget as ``(token, client_key,
        client_token)`` — the caller fails those loudly to their clients."""
        failed: List[Tuple[int, bytes, bytes]] = []
        with self._lock:
            worker = self._workers.pop(key, None)
            if worker is None:
                return failed
            if self._worker_id_index.get(worker.descriptor.worker_id) == key:
                del self._worker_id_index[worker.descriptor.worker_id]
            try:
                self._ready_workers.remove(key)
            except ValueError:
                pass
            self.workers_departed += 1
            for token in sorted(worker.assigned):
                state = self._tokens.get(token)
                self._assign_time.pop(token, None)
                if state is None:
                    continue
                state.worker_key = None
                # a stale ack from the departed worker can never retire the
                # redelivered attempt (echoed-attempt protocol, process_pool.py)
                state.attempt += 1
                if state.attempt >= self.max_item_attempts:
                    del self._tokens[token]
                    self._preferred_worker.pop(token, None)
                    client = self._clients.get(state.client_key)
                    if client is not None:
                        client.assigned.discard(token)
                    self.items_failed += 1
                    self._journal('quarantined', token=token)
                    failed.append((token, state.client_key,
                                   state.client_token))
                    continue
                client = self._clients.get(state.client_key)
                if client is None:
                    del self._tokens[token]
                    continue
                client.assigned.discard(token)
                client.queue.appendleft(token)
                if client.key not in self._active:
                    # oldest work first: schedule this client ahead of the
                    # regular rotation
                    self._active.appendleft(client.key)
                self.items_requeued += 1
        return failed

    def worker_ready(self, key: bytes) -> bool:
        """A worker announced itself idle; it may receive one assignment.
        Returns False for an UNKNOWN identity — a live worker left over from
        a previous dispatcher incarnation, which the caller answers with
        ``w_rejoin`` so it re-registers instead of idling forever."""
        with self._lock:
            if key not in self._workers:
                return False
            if key not in self._ready_workers:
                self._ready_workers.append(key)
            return True

    def heartbeat(self, worker_id: int, seq: int) -> None:
        """Record a worker's liveness stamp (change-detected on our clock —
        no cross-process clock comparison, the PR-4 discipline)."""
        with self._lock:
            key = self._worker_id_index.get(worker_id)
            worker = self._workers.get(key) if key is not None else None
            if worker is not None and worker.hb_seq != seq:
                worker.hb_seq = seq
                worker.hb_changed_at = self._clock()

    def stale_workers(self) -> List[bytes]:
        """Workers to reap: heartbeat stamp unchanged past the staleness
        window (departed or process-wide wedged), or — when an
        ``item_deadline_s`` is set — holding an item past its wall-clock
        budget (a wedged *decode* keeps heartbeating from its independent
        stamp thread, so item progress needs its own detector, exactly as in
        the in-process pool's two-detector watchdog). The caller removes
        them; re-queue + the attempt budget take it from there."""
        with self._lock:
            now = self._clock()
            stale = []
            for key, worker in self._workers.items():
                interval = worker.descriptor.heartbeat_interval_s or 0.0
                window = max(self.stale_timeout_s, 4 * interval)
                if now - worker.hb_changed_at > window:
                    stale.append(key)
                    continue
                if self.item_deadline_s is not None and any(
                        now - self._assign_time.get(token, now)
                        > self.item_deadline_s
                        for token in worker.assigned):
                    stale.append(key)
            return stale

    # ----------------------------------------------------------- scheduling

    def next_assignment(self) -> Optional[Assignment]:
        """One DRR scheduling step: pick the next (client, item) pair and a
        ready worker for it, or None when either side is empty.

        Each visit to the head-of-rotation client serves it if its deficit
        covers its head item's MEASURED cost, else tops the deficit up by
        ``quantum`` and rotates — so over any window, every client with
        pending work is served in proportion to its quantum, and a client
        burning heavy rowgroups is served proportionally fewer of them
        (deficit round robin; unit cost when no submit hint was shipped —
        the historical behavior). Heavy items (cost >= ``HEAVY_ITEM_COST``)
        route via the least-loaded ready worker instead of FIFO."""
        with self._lock:
            if not self._ready_workers:
                return None
            # a heavy head item needs up to ceil(MAX/quantum) deficit top-ups;
            # the guard must allow that many full rotations before giving up
            guard = ((1 + int(math.ceil(MAX_ITEM_COST / self.quantum)))
                     * (len(self._active) + 1))
            while self._active and guard > 0:
                guard -= 1
                key = self._active[0]
                client = self._clients.get(key)
                if client is None or not client.queue:
                    self._active.popleft()
                    if client is not None:
                        client.deficit = 0.0
                    continue
                state = self._tokens.get(client.queue[0])
                if state is None:  # superseded while queued
                    client.queue.popleft()
                    continue
                cost = state.cost
                if client.deficit < cost:
                    client.deficit += self.quantum
                    if client.deficit < cost:
                        self._active.rotate(-1)
                        continue
                worker_key = self._pick_worker_for(state.token, cost)
                if worker_key is None:
                    return None
                client.deficit -= cost
                token = client.queue.popleft()
                self._preferred_worker.pop(token, None)
                if not client.queue:
                    self._active.popleft()
                    client.deficit = 0.0
                else:
                    self._active.rotate(-1)
                worker = self._workers[worker_key]
                state.worker_key = worker_key
                worker.assigned.add(token)
                worker.cost_in_flight += cost
                client.assigned.add(token)
                self._assign_time[token] = self._clock()
                colocated = (worker.descriptor.shm_results
                             and worker.descriptor.host == client.host
                             and state.shm_ok)
                setup_blob: Optional[bytes] = None
                if state.setup_id not in worker.known_setups:
                    setup_blob = self._setups.get(state.setup_id)
                    if setup_blob is not None:
                        # only a SHIPPED setup counts as known — a missing
                        # blob must keep triggering w_need_setup until the
                        # attempt budget fails the item loudly
                        worker.known_setups.add(state.setup_id)
                return Assignment(worker_key, token, state.setup_id,
                                  state.blob, state.attempt, colocated,
                                  setup_blob)
            return None

    def _pick_worker_for(self, token: int,
                         cost: float = 1.0) -> Optional[bytes]:
        """Honor the last reshard's placement for ``token`` when that worker
        is ready; fall back to the ordinary pick (FIFO / least-loaded)
        otherwise — a reshard preference is a balance hint, never a stall."""
        preferred = self._preferred_worker.get(token)
        if preferred is not None:
            key = self._worker_id_index.get(preferred)
            if key is not None and key in self._ready_workers:
                self._ready_workers.remove(key)
                return key
        return self._pick_worker(cost)

    def _pick_worker(self, cost: float = 1.0) -> Optional[bytes]:
        """The ready worker for one item: FIFO for ordinary items (the
        historical order), least-loaded — smallest (in-flight cost, retired
        cost) — for heavy ones, so consecutive heavy rowgroups spread across
        the fleet instead of piling onto whichever worker asked first."""
        if cost >= HEAVY_ITEM_COST and len(self._ready_workers) > 1:
            best_key: Optional[bytes] = None
            best_score: Optional[Tuple[float, float]] = None
            for key in self._ready_workers:
                worker = self._workers.get(key)
                if worker is None:
                    continue
                score = (worker.cost_in_flight, worker.cost_served)
                if best_score is None or score < best_score:
                    best_key, best_score = key, score
            if best_key is not None:
                self._ready_workers.remove(best_key)
                return best_key
            self._ready_workers.clear()
            return None
        while self._ready_workers:
            key = self._ready_workers.popleft()
            if key in self._workers:
                return key
        return None

    def _bump_or_requeue(self, token: int) -> Optional[Tuple[int, bytes,
                                                             bytes]]:
        """Shared re-delivery path (worker lacked the setup, client lost a
        shm segment): bump the attempt and re-queue at the front — or, once
        the attempt budget is spent, retire the item and return ``(token,
        client_key, client_token)`` for the caller to fail loudly. Called
        under ``_lock``."""
        state = self._tokens.get(token)
        if state is None:
            return None
        state.worker_key = None
        state.delivered = False
        state.attempt += 1
        self._assign_time.pop(token, None)
        client = self._clients.get(state.client_key)
        if client is None:
            del self._tokens[token]
            return None
        if state.attempt >= self.max_item_attempts:
            del self._tokens[token]
            self._preferred_worker.pop(token, None)
            client.assigned.discard(token)
            self.items_failed += 1
            self._journal('quarantined', token=token)
            return (token, state.client_key, state.client_token)
        client.assigned.discard(token)
        if token not in client.queue:
            client.queue.appendleft(token)
            if client.key not in self._active:
                self._active.appendleft(client.key)
        self.items_requeued += 1
        return None

    def forget_setups(self, worker_key: bytes,
                      token: int) -> Optional[Tuple[int, bytes, bytes]]:
        """A worker reported it lacks a setup the dispatcher believed it had
        (``w_need_setup`` — e.g. the blob raced its registration reset):
        clear its record and re-queue the item so the next dispatch re-ships
        it. Returns the failure route once the item's attempt budget is
        spent (a setup that can never be shipped must fail loudly, not spin
        between dispatcher and worker forever)."""
        with self._lock:
            worker = self._workers.get(worker_key)
            if worker is not None:
                worker.known_setups.clear()
                worker.assigned.discard(token)
                state = self._tokens.get(token)
                if state is not None:
                    worker.cost_in_flight = max(0.0, worker.cost_in_flight
                                                - state.cost)
            return self._bump_or_requeue(token)

    # --------------------------------------------------------- result flow

    def result_route(self, token: int) -> Optional[Tuple[bytes, bytes]]:
        """Where to forward a worker result: ``(client_key, client_token)``,
        or None when the token is retired/superseded (duplicate from a
        re-dispatched item whose first result already went out — dropped and
        counted, exactly like the pool's ``results_dropped``)."""
        with self._lock:
            state = self._tokens.get(token)
            if state is None:
                # includes tokens whose delivery the LEDGER remembers from a
                # previous dispatcher life: a pre-crash straggler result is a
                # duplicate even though no live record holds it
                self._replay_delivered.discard(token)
                self.results_dropped += 1
                return None
            if state.delivered:
                self.results_dropped += 1
                return None
            if self._clients.get(state.client_key) is None:
                self.results_dropped += 1
                return None
            state.delivered = True
            self._journal('delivered', token=token)
            return state.client_key, state.client_token

    def retire(self, token: int, attempt: Optional[int]) -> None:
        """A ``w_done`` ack: retire the item iff the echoed attempt is
        current (a stale ack from a since-removed worker must neither retire
        an undelivered redelivery nor double-retire one)."""
        with self._lock:
            state = self._tokens.get(token)
            if state is None:
                return
            if attempt is not None and attempt != state.attempt:
                return
            del self._tokens[token]
            self._assign_time.pop(token, None)
            self._preferred_worker.pop(token, None)
            client = self._clients.get(state.client_key)
            self._journal('retired', token=token,
                          client=client.name if client is not None else None)
            if client is not None:
                client.assigned.discard(token)
                client.served += 1
                self.items_served += 1
            if state.worker_key is not None:
                worker = self._workers.get(state.worker_key)
                if worker is not None:
                    worker.assigned.discard(token)
                    worker.cost_in_flight = max(0.0, worker.cost_in_flight
                                                - state.cost)
                    worker.cost_served += state.cost

    def fail(self, token: int) -> Optional[Tuple[bytes, bytes]]:
        """Terminal worker error for an item: retire it and return the owning
        ``(client_key, client_token)`` to forward the error to."""
        with self._lock:
            state = self._tokens.pop(token, None)
            self._assign_time.pop(token, None)
            self._preferred_worker.pop(token, None)
            if state is None:
                return None
            self._journal('failed', token=token)
            client = self._clients.get(state.client_key)
            if client is not None:
                client.assigned.discard(token)
            if state.worker_key is not None:
                worker = self._workers.get(state.worker_key)
                if worker is not None:
                    worker.assigned.discard(token)
                    worker.cost_in_flight = max(0.0, worker.cost_in_flight
                                                - state.cost)
            if client is None:
                return None
            return state.client_key, state.client_token

    def requeue_token(self, token: int) -> Optional[Tuple[int, bytes, bytes]]:
        """Client-requested redelivery (``shm_fail``: it could not attach or
        verify a co-located segment) — put the item back at the front of its
        queue, pinned to the plain-wire transport from now on (a false
        co-location match must converge to TCP, not loop). Returns the
        failure route once the attempt budget is spent."""
        with self._lock:
            state = self._tokens.get(token)
            if state is None:
                return None
            state.shm_ok = False
            if state.worker_key is not None:
                worker = self._workers.get(state.worker_key)
                if worker is not None:
                    worker.assigned.discard(token)
                    worker.cost_in_flight = max(0.0, worker.cost_in_flight
                                                - state.cost)
            return self._bump_or_requeue(token)

    # ------------------------------------------------------------ snapshot

    def worker_count(self) -> int:
        """Currently-registered decode workers."""
        with self._lock:
            return len(self._workers)

    def worker_keys(self) -> List[bytes]:
        """Identities of every registered worker (stop-broadcast routing)."""
        with self._lock:
            return list(self._workers)

    def worker_id_of(self, key: bytes) -> Optional[int]:
        """The registered worker id behind a socket identity (None when
        unknown) — how the dispatcher maps departures onto the fleet
        metrics-plane entries it should drop."""
        with self._lock:
            worker = self._workers.get(key)
            return worker.descriptor.worker_id if worker is not None else None

    def has_worker_id(self, worker_id: int) -> bool:
        """True while ``worker_id`` names a REGISTERED worker — the guard
        that keeps a departed worker's straggler ``w_metrics`` frame from
        resurrecting its entry on the scrape surface."""
        with self._lock:
            return worker_id in self._worker_id_index

    def state(self) -> Dict[str, Any]:
        """JSON-safe snapshot: clients (queue depth / in-flight / served /
        fair-share debt), workers (assigned / heartbeat age), and the
        aggregate admission + requeue counters — the ``state`` reply body."""
        with self._lock:
            now = self._clock()
            return {
                'workers': [{
                    'worker_id': w.descriptor.worker_id,
                    'pid': w.descriptor.pid,
                    'host': w.descriptor.host,
                    'shm_results': w.descriptor.shm_results,
                    'assigned': len(w.assigned),
                    'heartbeat_age_s': round(now - w.hb_changed_at, 3),
                    'cost_in_flight': round(w.cost_in_flight, 3),
                    'cost_served': round(w.cost_served, 3),
                } for w in self._workers.values()],
                'clients': [{
                    'name': c.name,
                    'host': c.host,
                    'window': c.window,
                    'queued': len(c.queue),
                    'in_flight': c.in_flight(),
                    'served': c.served,
                    'deficit': round(c.deficit, 3),
                    'busy_rejections': c.busy_rejections,
                } for c in self._clients.values()],
                'queue_depth': sum(len(c.queue)
                                   for c in self._clients.values()),
                'in_flight': len(self._tokens),
                'ready_workers': len(self._ready_workers),
                'busy_rejections': self.busy_rejections,
                'results_dropped': self.results_dropped,
                'items_requeued': self.items_requeued,
                'items_failed': self.items_failed,
                'items_served': self.items_served,
                'admission_window': self.admission_window,
                'workers_registered_total': self.workers_registered_total,
                'workers_departed': self.workers_departed,
                'resharded': self.resharded,
                'ledger_epoch': self.ledger_epoch,
            }


def choose_service_knob(prev: Dict[str, Any], cur: Dict[str, Any],
                        rate: float, eligible: List[Any]) -> Optional[str]:
    """The service controller's knob chooser (docs/autotuning.md): admission
    signals instead of stage histograms. A window with fresh ``busy``
    rejections while the queue is shallow means clients are throttled below
    what the fleet could absorb — retune the live client windows; a queue deep
    past the fleet's absorption rate points at the admission cap."""
    ids = {knob.knob_id for knob in eligible}
    busy_delta = (int((cur.get('counters') or {}).get('service_busy', 0))
                  - int((prev.get('counters') or {}).get('service_busy', 0)))
    gauges = cur.get('gauges') or {}
    queue_depth = float(gauges.get('service_queue_depth', 0.0))
    workers = max(1.0, float(gauges.get('service_workers', 1.0)))
    admission = float(gauges.get('service_admission_window', 0.0))
    client_window = float(gauges.get('service_client_window', admission))
    if busy_delta > 0 and queue_depth <= 2 * workers:
        # clients throttled below fleet capacity. The common fleet has every
        # client AT the admission cap (hello without a window = follow the
        # cap) — the client-window knob is pinned there, so the cap itself is
        # the knob to raise (follow-the-cap clients are lifted with it and
        # adopt it via the accept/busy piggyback).
        if client_window < admission and 'service_client_window' in ids:
            return 'service_client_window'
        if 'service_admission_window' in ids:
            return 'service_admission_window'
    if queue_depth > 8 * workers and 'service_admission_window' in ids:
        return 'service_admission_window'
    return None


class Dispatcher(object):
    """ZMQ front of the scheduler: binds the client + worker ROUTERs, pumps
    messages on a daemon thread, and translates scheduler decisions into
    ``work`` sends. All socket use stays on the dispatcher thread (ROUTER
    sends are not thread-safe); :meth:`state` reads the scheduler snapshot
    under its own lock from any thread.

    ``autotune`` (docs/autotuning.md): ``True`` or an
    :class:`~petastorm_tpu.autotune.AutotunePolicy` arms the same controller
    core the reader uses — driven from the pump thread (no extra thread), it
    retunes the admission window and live per-client in-flight depth from the
    scheduler's queue-depth/``service_busy`` signals, with the process breaker
    board as the interlock. Off (None) by default."""

    def __init__(self, host: str = '127.0.0.1', port: Optional[int] = None,
                 admission_window: int = DEFAULT_ADMISSION_WINDOW,
                 quantum: float = DEFAULT_QUANTUM,
                 stale_timeout_s: float = DEFAULT_STALE_TIMEOUT_S,
                 max_item_attempts: int = DEFAULT_MAX_ITEM_ATTEMPTS,
                 item_deadline_s: Optional[float] = None,
                 client_ttl_s: float = DEFAULT_CLIENT_TTL_S,
                 autotune: Any = None,
                 metrics_port: Optional[int] = None,
                 incidents: Any = None,
                 ledger: Optional[str] = None,
                 history: Any = None) -> None:
        self._host = host
        self._port = port
        #: durable token ledger (service/ledger.py): a journal path arms it;
        #: ``start`` replays the journal (behind the ledger-replay breaker)
        #: before the first frame is served
        self._ledger_path = ledger
        self._ledger: Any = None
        #: set by :meth:`crash` — the pump exits WITHOUT the stop broadcast
        #: or the heartbeat drain, exactly like a SIGKILL would leave things
        self._crashed = False
        # Fleet metrics plane (docs/observability.md "Live metrics plane"):
        # latest cumulative telemetry snapshot per worker (seq-guarded,
        # delivered as w_metrics frames on the heartbeat socket), merged at
        # scrape time into one fleet-wide surface. Guarded by its own lock —
        # the pump thread writes, the scrape threads read.
        self._metrics_port = metrics_port
        self._metrics_server: Any = None
        self._worker_metrics: Dict[int, Tuple[int, Dict[str, Any]]] = {}
        self._worker_metrics_lock = threading.Lock()
        # Fleet incident plane (docs/observability.md "Incident autopsy
        # plane"): the dispatcher owns its own recorder (stale-worker reaps
        # and attempt-budget exhaustion are dispatcher-observed edges),
        # adopts inline bundles shipped by workers as w_incident frames, and
        # correlates same-cause references across workers into one fleet
        # incident.
        self._incident_recorder: Any = None
        self._incident_registry: Any = None
        self._worker_incident_seq: Dict[int, int] = {}
        self._fleet_incidents: List[Dict[str, Any]] = []
        self._incident_lock = threading.Lock()
        from petastorm_tpu.telemetry.incident import resolve_incident_policy
        incident_policy = resolve_incident_policy(incidents)
        if incident_policy is not None:
            from petastorm_tpu.telemetry.incident import (
                IncidentRecorder, default_incident_home)
            from petastorm_tpu.telemetry.registry import MetricsRegistry
            self._incident_registry = MetricsRegistry()
            self._incident_recorder = IncidentRecorder(
                default_incident_home(None), incident_policy,
                registry=self._incident_registry)
            self._incident_recorder.add_source(
                'service_state', lambda: self.scheduler.state())
            self._incident_recorder.add_source(
                'metrics', self.fleet_metrics_snapshot)
        self.scheduler = FairShareScheduler(
            admission_window=admission_window, quantum=quantum,
            stale_timeout_s=stale_timeout_s,
            max_item_attempts=max_item_attempts,
            item_deadline_s=item_deadline_s, client_ttl_s=client_ttl_s)
        self._context: Any = None
        self._client_socket: Any = None
        self._worker_socket: Any = None
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self._next_stale_check = 0.0
        self._autotune: Any = None
        from petastorm_tpu.autotune.policy import resolve_policy
        autotune_policy = resolve_policy(autotune)
        if autotune_policy is not None:
            from petastorm_tpu.autotune.controller import AutotuneController
            from petastorm_tpu.autotune.knobs import (KnobCatalog,
                                                      build_service_knobs)
            scheduler = self.scheduler
            self._autotune = AutotuneController(
                KnobCatalog(build_service_knobs(scheduler)),
                metric_fn=lambda: float(scheduler.items_served),
                snapshot_fn=scheduler.autotune_snapshot,
                policy=autotune_policy,
                choose_fn=choose_service_knob,
                name='service')
        # Longitudinal observatory (docs/observability.md "Longitudinal
        # observatory"): one structured run record at stop() plus a live
        # regression sentinel over the pump's items-served series. The
        # dispatcher has no dataset home, so persisting records needs an
        # explicit store path (``history=HistoryPolicy(path=...)`` or a
        # path string); ``history=True`` still arms the sentinel.
        self._history: Any = None
        self._sentinel: Any = None
        self._history_written = False
        self._started_at: Optional[float] = None
        from petastorm_tpu.telemetry.history import resolve_history_policy
        self._history_policy = resolve_history_policy(history)
        if self._history_policy is not None:
            from petastorm_tpu.telemetry.sentinel import (
                RegressionSentinel, resolve_sentinel_policy)
            if self._history_policy.path:
                from petastorm_tpu.telemetry.history import RunHistorian
                self._history = RunHistorian(
                    self._history_policy.path,
                    policy=self._history_policy,
                    registry=self._incident_registry)
            sentinel_policy = resolve_sentinel_policy(
                self._history_policy.sentinel)
            if sentinel_policy is not None:
                self._sentinel = RegressionSentinel(
                    sentinel_policy, owner='dispatcher',
                    registry=self._incident_registry,
                    incidents=self._incident_recorder,
                    dataset_token=SERVICE_DATASET_TOKEN)
                if self._incident_recorder is not None:
                    self._incident_recorder.add_source(
                        'sentinel', self._sentinel.report)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> str:
        """Bind both ROUTERs and start the pump thread; returns the
        ``service_url`` clients connect to. When a ledger path is armed the
        journal is replayed FIRST (behind the ledger-replay breaker): token
        monotonicity and the delivered-dedup set are restored before any
        client or worker frame can race them."""
        import zmq
        from petastorm_tpu.service.wire import WORKER_PORT_OFFSET
        if self._ledger_path:
            self._arm_ledger()
        self._context = zmq.Context()
        self._client_socket = self._context.socket(zmq.ROUTER)
        self._worker_socket = self._context.socket(zmq.ROUTER)
        if self._port is not None:
            self._client_socket.bind('tcp://{}:{}'.format(self._host,
                                                          self._port))
            self._worker_socket.bind('tcp://{}:{}'.format(
                self._host, self._port + WORKER_PORT_OFFSET))
        else:
            # adjacent-port pair from the ephemeral range: retry until a port
            # P with P+1 also free is found (bounded — ranges are sparse)
            last_error: Optional[Exception] = None
            for _ in range(32):
                port = self._client_socket.bind_to_random_port(
                    'tcp://{}'.format(self._host))
                try:
                    self._worker_socket.bind('tcp://{}:{}'.format(
                        self._host, port + WORKER_PORT_OFFSET))
                    self._port = port
                    break
                except zmq.ZMQError as exc:
                    last_error = exc
                    self._client_socket.unbind('tcp://{}:{}'.format(
                        self._host, port))
            else:
                raise RuntimeError('could not find an adjacent free port '
                                   'pair: {!r}'.format(last_error))
        self._started_at = time.monotonic()
        self._thread = threading.Thread(target=self._pump, daemon=True,
                                        name='petastorm-tpu-dispatcher')
        self._thread.start()
        if self._metrics_port is not None:
            from petastorm_tpu.telemetry.http_exporter import (
                MetricsHttpServer, service_state_text)
            self._metrics_server = MetricsHttpServer(
                snapshot_fn=self.fleet_metrics_snapshot,
                labeled_fn=self.worker_metrics_snapshots,
                label='worker',
                extra_text_fn=lambda: service_state_text(
                    self.scheduler.state()),
                health_fn=lambda: {
                    'workers': self.scheduler.worker_count(),
                    'service_url': self.service_url},
                port=int(self._metrics_port), host=self._host)
            self._metrics_server.start()
        return self.service_url

    def _arm_ledger(self) -> None:
        """Open + replay the durable token ledger behind the ledger-replay
        breaker: a journal that corrupts consecutive replays must not wedge
        every restart — once the breaker opens, the journal is DISCARDED and
        the fleet degrades to replay-from-clients (loud: incident bundle +
        CRC drop counter), never to a wrong order."""
        from petastorm_tpu.resilience import (
            LEDGER_REPLAY_BREAKER_THRESHOLD, LEDGER_REPLAY_BREAKER_RECOVERY_S,
            default_board)
        from petastorm_tpu.service.ledger import TokenLedger
        from petastorm_tpu.telemetry.tracing import trace_instant
        breaker = default_board().breaker(
            'ledger:replay',
            failure_threshold=LEDGER_REPLAY_BREAKER_THRESHOLD,
            recovery_timeout_s=LEDGER_REPLAY_BREAKER_RECOVERY_S)
        self._ledger = TokenLedger(self._ledger_path)
        replay = self._ledger.open(discard=not breaker.allow())
        if replay.result == 'corrupt':
            breaker.record_failure()
            logger.error(
                'dispatcher: ledger journal %s failed CRC replay (%d '
                'frame(s) dropped, %d record(s) recovered); degrading to '
                'replay-from-clients', self._ledger_path,
                replay.frames_dropped, replay.records)
            if self._incident_registry is not None:
                self._incident_registry.inc('ledger_frames_dropped',
                                            replay.frames_dropped)
            if self._incident_recorder is not None:
                path = self._incident_recorder.trigger(
                    'ledger_corrupt', args=replay.as_dict())
                self._correlate_incident(
                    None, {'bundle': path, 'kind': 'ledger_corrupt',
                           'cause': 'corruption'})
        elif replay.result == 'ok' and replay.records:
            breaker.record_success()
        self.scheduler.adopt_replay(replay, self._ledger.epoch)
        self.scheduler.journal = self._ledger
        trace_instant('ledger_replay', args=replay.as_dict())

    @property
    def service_url(self) -> str:
        """The URL readers pass as ``make_reader(service_url=...)``."""
        return 'tcp://{}:{}'.format(self._host, self._port)

    def state(self) -> Dict[str, Any]:
        """The scheduler snapshot (same dict the ``state`` request returns),
        plus the ``autotune`` controller report when retuning is armed and
        the correlated ``incidents`` view when the incident plane is."""
        state = self.scheduler.state()
        # a reply at all means the pump is live — fetch_service_state's
        # hello-probe path reports 'starting' for a bound-but-silent socket
        state['state'] = 'serving'
        state['ledger'] = self.ledger_state()
        if self._autotune is not None:
            state['autotune'] = self._autotune.report()
        if self._incident_recorder is not None:
            state['incidents'] = self.incidents_state()
        if self._history is not None:
            state['history'] = self._history.state()
        if self._sentinel is not None:
            state['sentinel'] = self._sentinel.report()
        return state

    def ledger_state(self) -> Dict[str, Any]:
        """The durable-ledger status block for ``state()`` and doctor:
        armed flag, journal path/epoch, last replay result and the frames
        the CRC dropped."""
        if self._ledger is None:
            return {'armed': False}
        out: Dict[str, Any] = self._ledger.state()
        return out

    # ----------------------------------------------------- run history plane

    def build_history_record(self) -> Optional[Dict[str, Any]]:
        """The structured run record this dispatcher would append at
        ``stop()`` (docs/observability.md "Longitudinal observatory"):
        service config/knob fingerprints, items-served rate, incident
        counters. None when built without ``history``. Knob values are read
        live — call before the autotuner would restore anything."""
        if self._history_policy is None:
            return None
        from petastorm_tpu.telemetry.history import (build_run_record,
                                                     fingerprint)
        elapsed = 0.0
        if self._started_at is not None:
            elapsed = max(time.monotonic() - self._started_at, 0.0)
        scheduler = self.scheduler
        knobs: Dict[str, float] = {}
        try:
            from petastorm_tpu.autotune.knobs import build_service_knobs
            knobs = {knob.knob_id: float(knob.get())
                     for knob in build_service_knobs(scheduler)}
        except Exception:  # noqa: BLE001 - the record is advisory; a dead knob target must not fail stop()
            logger.debug('history: service knob capture failed',
                         exc_info=True)
        fingerprints: Dict[str, Optional[str]] = {
            'config': fingerprint({
                'admission_window': scheduler.admission_window,
                'quantum': scheduler.quantum,
                'stale_timeout_s': scheduler.stale_timeout_s,
                'max_item_attempts': scheduler.max_item_attempts,
                'item_deadline_s': scheduler.item_deadline_s,
                'client_ttl_s': scheduler.client_ttl_s,
                'ledger': bool(self._ledger_path),
            }),
            'knobs': fingerprint(knobs) if knobs else None,
        }
        incidents: Optional[Dict[str, Any]] = None
        if self._incident_recorder is not None:
            incidents = self._incident_recorder.report()
        return build_run_record(
            'dispatcher', SERVICE_DATASET_TOKEN, elapsed,
            int(scheduler.items_served),
            snapshot=self.fleet_metrics_snapshot(),
            fingerprints=fingerprints, knobs=knobs,
            incidents=incidents)

    def _write_history_record(self) -> None:
        """Append this run's record to the longitudinal store — idempotent,
        best-effort, skipped entirely without an explicit store path (the
        dispatcher has no dataset home to default into)."""
        if self._history is None or self._history_written:
            return
        self._history_written = True
        try:
            record = self.build_history_record()
            if record is not None:
                self._history.append(record)
        except Exception:  # noqa: BLE001 - history is advisory; a service that served must not fail over its memory
            logger.warning('dispatcher: could not record this run in the '
                           'history store', exc_info=True)

    def history_report(self) -> Optional[Dict[str, Any]]:
        """The historian's store status (path, appended count, dropped
        frames); None when built without a history store path."""
        if self._history is None:
            return None
        out: Dict[str, Any] = self._history.state()
        return out

    # -------------------------------------------------------- metrics plane

    def record_worker_metrics(self, worker_id: int, seq: int,
                              snapshot: Dict[str, Any]) -> None:
        """Adopt one worker's cumulative telemetry snapshot (``w_metrics``);
        a stale ``seq`` never rolls a fresher view backwards, and a frame
        from an UNREGISTERED worker (a departed worker's straggler, same as
        ``scheduler.heartbeat``'s unknown-id drop) never resurrects a
        popped entry."""
        if not self.scheduler.has_worker_id(worker_id):
            return
        with self._worker_metrics_lock:
            current = self._worker_metrics.get(worker_id)
            if current is not None and current[0] >= seq:
                return
            self._worker_metrics[worker_id] = (seq, snapshot)

    def worker_metrics_snapshots(self) -> Dict[str, Dict[str, Any]]:
        """Latest per-worker snapshots keyed by worker id (the per-worker
        labeled block of the fleet scrape)."""
        with self._worker_metrics_lock:
            return {str(worker_id): snapshot
                    for worker_id, (_seq, snapshot)
                    in self._worker_metrics.items()}

    def fleet_metrics_snapshot(self) -> Dict[str, Any]:
        """ONE fleet-wide registry snapshot: the scheduler's control-signal
        gauges/counters merged (additively, per worker) with every worker's
        latest heartbeat snapshot — plus the dispatcher-side incident
        counters when the incident plane is armed — what ``/metrics``
        renders as the aggregate block (docs/observability.md "Live metrics
        plane")."""
        from petastorm_tpu.telemetry.registry import merge_snapshots
        with self._worker_metrics_lock:
            snapshots = [snapshot for _seq, snapshot
                         in self._worker_metrics.values()]
        if self._incident_registry is not None:
            snapshots.append(self._incident_registry.snapshot())
        return merge_snapshots(self.scheduler.autotune_snapshot(), *snapshots)

    # ------------------------------------------------------- incident plane

    def record_worker_incident(self, worker_id: int, seq: int,
                               reference: Dict[str, Any]) -> None:
        """Adopt one worker-shipped incident reference (``w_incident``):
        unknown-worker stragglers are dropped (same guard as
        :meth:`record_worker_metrics` — a departed worker's late frame must
        not resurrect it), a stale ``seq`` is dropped, inline bundles are
        materialized into the dispatcher's home, and the reference joins the
        fleet correlation."""
        if self._incident_recorder is None:
            return
        if not self.scheduler.has_worker_id(worker_id):
            return
        with self._incident_lock:
            current = self._worker_incident_seq.get(worker_id)
            if current is not None and current >= seq:
                return
            self._worker_incident_seq[worker_id] = seq
        adopted = self._incident_recorder.adopt(reference)
        if adopted is not None:
            reference = dict(reference, bundle=adopted)
        self._correlate_incident(worker_id, reference)

    def _correlate_incident(self, worker_id: Optional[int],
                            reference: Dict[str, Any]) -> None:
        """Fold one incident reference into the fleet view: same-cause
        incidents landing within the correlation window collapse into ONE
        fleet incident spanning every reporting worker — a dataset-wide
        storage outage reads as one event, not workers-many."""
        cause = str(reference.get('cause') or 'unknown')
        kind = str(reference.get('kind') or 'unknown')
        bundle = reference.get('bundle')
        now = time.monotonic()
        with self._incident_lock:
            for entry in self._fleet_incidents:
                if (entry['cause'] == cause
                        and now - entry['_last_monotonic']
                        <= INCIDENT_CORRELATION_WINDOW_S):
                    entry['count'] += 1
                    entry['_last_monotonic'] = now
                    if kind not in entry['kinds']:
                        entry['kinds'].append(kind)
                    if (worker_id is not None
                            and worker_id not in entry['workers']):
                        entry['workers'].append(worker_id)
                    if bundle and len(entry['bundles']) < 8:
                        entry['bundles'].append(str(bundle))
                    return
            self._fleet_incidents.append({
                'cause': cause, 'kinds': [kind], 'count': 1,
                'workers': [worker_id] if worker_id is not None else [],
                'bundles': [str(bundle)] if bundle else [],
                '_first_monotonic': now, '_last_monotonic': now})
            del self._fleet_incidents[:-MAX_FLEET_INCIDENTS]

    def incidents_state(self) -> Dict[str, Any]:
        """The fleet incident view for ``state()``: correlated same-cause
        groups (ages on the dispatcher's clock) plus the capture/rate-limit
        counters and the dispatcher's retained-bundle summary."""
        now = time.monotonic()
        with self._incident_lock:
            fleet = [{'cause': entry['cause'], 'kinds': list(entry['kinds']),
                      'count': entry['count'],
                      'workers': list(entry['workers']),
                      'bundles': list(entry['bundles']),
                      'first_age_s': round(now - entry['_first_monotonic'], 3),
                      'last_age_s': round(now - entry['_last_monotonic'], 3)}
                     for entry in self._fleet_incidents]
        state: Dict[str, Any] = {'fleet': fleet}
        if self._incident_recorder is not None:
            state.update(self._incident_recorder.report())
        return state

    @property
    def metrics_url(self) -> Optional[str]:
        """The fleet scrape endpoint base URL, or None without
        ``metrics_port``."""
        if self._metrics_server is None:
            return None
        url: str = self._metrics_server.url
        return url

    def stop(self) -> None:
        """Stop the pump thread; ``w_stop`` is broadcast to registered
        workers from the pump thread on its way out."""
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        # record BEFORE the incident recorder closes so the record's
        # incident counters see the final capture totals, and before the
        # pump exits so items_served is this run's true total
        self._write_history_record()
        if self._incident_recorder is not None:
            self._incident_recorder.close()
        self._stop_event.set()

    def crash(self) -> None:
        """Crash simulation (chaos harness / tests): stop the pump WITHOUT
        the worker-tail drain or the ``w_stop`` broadcast — workers and
        clients are left exactly as a SIGKILL of the dispatcher process
        would leave them, except the sockets can be rebound in-process. The
        ledger handle closes abruptly (no terminal record — that is the
        crash-consistency property being exercised)."""
        self._crashed = True
        if self._metrics_server is not None:
            self._metrics_server.stop()
            self._metrics_server = None
        if self._incident_recorder is not None:
            self._incident_recorder.close()
        self._stop_event.set()
        self.join()
        if self._ledger is not None:
            self._ledger.close()
            self._ledger = None

    def join(self, timeout: float = 10.0) -> None:
        """Wait for the pump thread and release the sockets."""
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._ledger is not None:
            self._ledger.close()
        if self._context is not None:
            for sock in (self._client_socket, self._worker_socket):
                if sock is not None:
                    sock.close(linger=0)
            self._context.term()
            self._context = None

    # ----------------------------------------------------------------- pump

    def _pump(self) -> None:
        import zmq
        poller = zmq.Poller()
        poller.register(self._client_socket, zmq.POLLIN)
        poller.register(self._worker_socket, zmq.POLLIN)
        while not self._stop_event.is_set():
            events = dict(poller.poll(100))
            if self._client_socket in events:
                for _ in range(64):  # drain a bounded burst per tick
                    try:
                        frames = self._client_socket.recv_multipart(
                            zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    try:
                        self._handle_client(frames)
                    except Exception:  # noqa: BLE001 - one malformed client frame must not take the whole service down
                        logger.exception('dispatcher: dropping malformed '
                                         'client message')
            if self._worker_socket in events:
                for _ in range(64):
                    try:
                        frames = self._worker_socket.recv_multipart(
                            zmq.NOBLOCK)
                    except zmq.Again:
                        break
                    try:
                        self._handle_worker(frames)
                    except Exception:  # noqa: BLE001 - one malformed worker frame must not take the whole service down
                        logger.exception('dispatcher: dropping malformed '
                                         'worker message')
            self._check_stale()
            if self._autotune is not None:
                try:
                    # window-gated: the controller core decides at most once
                    # per policy window, the pump just offers it the tick
                    self._autotune.maybe_step()
                except Exception:  # noqa: BLE001 - the tuner must never kill the dispatch loop it tunes
                    logger.exception('dispatcher: autotune step failed; '
                                     'pump keeps dispatching')
            if self._sentinel is not None and self._started_at is not None:
                # items-served is the service's rows analog; between window
                # closes this costs one float compare per pump tick
                elapsed = time.monotonic() - self._started_at
                if self._sentinel.due(elapsed):
                    try:
                        self._sentinel.observe_sample(
                            elapsed, int(self.scheduler.items_served))
                        self._sentinel.export_gauges()
                    except Exception:  # noqa: BLE001 - the sentinel must never kill the dispatch loop it watches
                        logger.exception('dispatcher: sentinel window '
                                         'failed; pump keeps dispatching')
            self._dispatch_ready()
        if not self._crashed:
            self._drain_worker_tail()
            self._broadcast_stop()

    def _drain_worker_tail(self) -> None:
        """Final heartbeat-socket drain before the stop broadcast: a worker
        mid-``w_incident`` (or mid-metrics) ship when stop lands would
        otherwise lose those frames AND look like a straggler to the fleet
        reaper. Bounded — shutdown must not hang on a chatty socket."""
        import zmq
        deadline = time.monotonic() + 0.25
        while time.monotonic() < deadline:
            if not self._worker_socket.poll(50, zmq.POLLIN):
                break  # quiet socket: nothing is mid-flight
            for _ in range(64):
                try:
                    frames = self._worker_socket.recv_multipart(zmq.NOBLOCK)
                except zmq.ZMQError:
                    break
                try:
                    self._handle_worker(frames)
                except Exception:  # noqa: BLE001 - the drain is best-effort; a malformed tail frame must not block shutdown
                    pass

    def _broadcast_stop(self) -> None:
        for key in self.scheduler.worker_keys():
            try:
                self._worker_socket.send_multipart([key, MSG_W_STOP])
            except Exception:  # noqa: BLE001 - shutdown is best-effort; the workers' parent watchdog is the backstop
                pass

    # -------------------------------------------------------- client frames

    def _handle_client(self, frames: List[bytes]) -> None:
        if len(frames) < 2:
            return
        identity = frames[0]
        kind = bytes(frames[1])
        if kind == MSG_SUBMIT and len(frames) >= 5:
            if not self.scheduler.has_client(identity):
                # restart / TTL-collected idle client: busy would be a lie
                # (the client would back off forever) — tell it to rejoin
                self._client_socket.send_multipart(
                    [identity, MSG_REJOIN, frames[2]])
                return
            # optional 6th frame: the client scheduler's measured-cost hint
            # (docs/performance.md "Cost-aware scheduling"); absent => 1.0,
            # the historical uniform unit cost
            cost = decode_cost(bytes(frames[5])) if len(frames) >= 6 else 1.0
            token = self.scheduler.submit(identity, bytes(frames[2]),
                                          bytes(frames[3]), frames[4],
                                          cost=cost)
            # every submit reply carries the client's CURRENT window so live
            # clients adopt autotune retuning (a raised window admits more
            # in-flight work; a lowered one ends the busy churn immediately)
            window = b'%d' % self.scheduler.client_window(identity)
            if token is None:
                self._client_socket.send_multipart(
                    [identity, MSG_BUSY, frames[2], window])
            else:
                self._client_socket.send_multipart(
                    [identity, MSG_ACCEPT, frames[2], window])
            return
        if kind == MSG_HELLO and len(frames) >= 5:
            name = bytes(frames[2]).decode('utf-8', 'replace')
            host = bytes(frames[3]).decode('utf-8', 'replace')
            window = int(bytes(frames[4]))
            if name:
                effective = self.scheduler.add_client(identity, name, host,
                                                      window or None)
            else:
                # anonymous probe (fetch_service_state's starting-detector):
                # answer without registering a client record
                effective = self.scheduler.admission_window
            body = json.dumps({
                'workers': self.scheduler.worker_count(),
                'window': effective,
                'host': self._host,
                'ledger_epoch': self.scheduler.ledger_epoch,
            }).encode('utf-8')
            self._client_socket.send_multipart([identity, MSG_WELCOME, body])
            return
        if kind == MSG_LEDGER_SYNC:
            # ledger-epoch handshake: the client's starvation probe (and its
            # post-rejoin resync). 'known' False or a changed epoch tells
            # the client its in-flight tokens died with the previous
            # dispatcher incarnation — it re-arms them instead of waiting
            body = json.dumps({
                'known': self.scheduler.has_client(identity),
                'epoch': self.scheduler.ledger_epoch,
                'ledger': self.ledger_state(),
            }).encode('utf-8')
            self._client_socket.send_multipart(
                [identity, MSG_LEDGER_STATE, body])
            return
        if kind == MSG_OPEN and len(frames) >= 4:
            self.scheduler.add_setup(identity, bytes(frames[2]), frames[3])
            self._client_socket.send_multipart(
                [identity, MSG_OPENED, frames[2]])
            return
        if kind == MSG_STATE:
            body = json.dumps(self.state()).encode('utf-8')
            self._client_socket.send_multipart([identity, MSG_STATE, body])
            return
        if kind == MSG_SHM_FAIL and len(frames) >= 3:
            # the client could not attach a co-located segment — redeliver
            # (wire-pinned); past the attempt budget, fail it loudly
            failed = self.scheduler.requeue_token(int(bytes(frames[2])))
            if failed is not None:
                self._send_attempt_exhausted(failed[1], failed[2])
            return
        if kind == MSG_BYE:
            self.scheduler.remove_client(identity)
            return
        logger.debug('dispatcher: unknown client message kind %r', kind)

    # -------------------------------------------------------- worker frames

    def _handle_worker(self, frames: List[bytes]) -> None:
        if len(frames) < 2:
            return
        identity = frames[0]
        kind = bytes(frames[1])
        if kind == MSG_W_HEARTBEAT and len(frames) >= 4:
            self.scheduler.heartbeat(int(bytes(frames[2])),
                                     int(bytes(frames[3])))
            return
        if kind == MSG_W_METRICS and len(frames) >= 3:
            from petastorm_tpu.service.wire import WorkerMetricsUpdate
            update = WorkerMetricsUpdate.from_bytes(bytes(frames[2]))
            self.record_worker_metrics(update.worker_id, update.seq,
                                       update.snapshot)
            return
        if kind == MSG_W_INCIDENT and len(frames) >= 3:
            from petastorm_tpu.service.wire import WorkerIncidentUpdate
            incident = WorkerIncidentUpdate.from_bytes(bytes(frames[2]))
            self.record_worker_incident(incident.worker_id, incident.seq,
                                        incident.reference)
            return
        if kind == MSG_W_RESULT and len(frames) >= 4:
            token = int(bytes(frames[2]))
            route = self.scheduler.result_route(token)
            if route is not None:
                client_key, client_token = route
                self._client_socket.send_multipart(
                    [client_key, MSG_RESULT, client_token] + frames[4:])
            return
        if kind == MSG_W_RESULT_SHM and len(frames) >= 5:
            token = int(bytes(frames[2]))
            route = self.scheduler.result_route(token)
            if route is not None:
                client_key, client_token = route
                self._client_socket.send_multipart(
                    [client_key, MSG_RESULT_SHM, client_token, frames[4]])
            return
        if kind == MSG_W_DONE and len(frames) >= 4:
            self.scheduler.retire(int(bytes(frames[2])),
                                  int(bytes(frames[3])))
            return
        if kind == MSG_W_ERROR and len(frames) >= 5:
            route = self.scheduler.fail(int(bytes(frames[2])))
            if route is not None:
                client_key, client_token = route
                self._client_socket.send_multipart(
                    [client_key, MSG_ERROR, client_token, frames[4]])
            return
        if kind == MSG_W_READY:
            if not self.scheduler.worker_ready(identity):
                # a live worker from a previous dispatcher incarnation
                # (restart): tell it to re-register — fleet heals in place
                self._worker_socket.send_multipart([identity, MSG_W_REJOIN])
            return
        if kind == MSG_REGISTER and len(frames) >= 3:
            descriptor = WorkerDescriptor.from_bytes(bytes(frames[2]))
            newly = self.scheduler.add_worker(identity, descriptor)
            logger.info('dispatcher: worker %d (pid %d, host %s) registered',
                        descriptor.worker_id, descriptor.pid, descriptor.host)
            self._worker_socket.send_multipart([identity, MSG_REGISTERED])
            if newly:
                self._note_reshard('worker-join')
            return
        if kind == MSG_W_NEED_SETUP and len(frames) >= 3:
            failed = self.scheduler.forget_setups(identity,
                                                  int(bytes(frames[2])))
            if failed is not None:
                self._send_attempt_exhausted(failed[1], failed[2])
            return
        if kind == MSG_W_LEAVE:
            self._depart_worker(identity, reason='left')
            return
        logger.debug('dispatcher: unknown worker message kind %r', kind)

    # ------------------------------------------------------------ decisions

    def _send_attempt_exhausted(self, client_key: bytes,
                                client_token: bytes) -> None:
        """Fail one item loudly to its owning client: the item burned its
        whole re-delivery budget (worker deaths, unshippable setup, lost shm
        segments) and re-queuing it again would only poison the fleet."""
        from petastorm_tpu.errors import TransientIOError
        blob = pickle.dumps((
            TransientIOError(
                'work item re-dispatched {} times across service worker '
                'failures; giving up'.format(
                    self.scheduler.max_item_attempts)),
            'service dispatcher: attempt budget exhausted'))
        self._client_socket.send_multipart(
            [client_key, MSG_ERROR, client_token, blob])
        if self._incident_recorder is not None:
            path = self._incident_recorder.trigger(
                'service_poison_item',
                args={'max_item_attempts': self.scheduler.max_item_attempts})
            if path is not None:
                self._correlate_incident(
                    None, {'bundle': path, 'kind': 'service_poison_item',
                           'cause': 'hang'})

    def _depart_worker(self, key: bytes, reason: str) -> None:
        worker_id = self.scheduler.worker_id_of(key)
        if worker_id is not None:
            # the departed worker's series leave the scrape surface with it
            # (Prometheus convention: absent, not frozen-forever)
            with self._worker_metrics_lock:
                self._worker_metrics.pop(worker_id, None)
            with self._incident_lock:
                self._worker_incident_seq.pop(worker_id, None)
        if self._incident_recorder is not None and reason == 'went stale':
            # the dispatcher-side watchdog edge: a worker stopped stamping
            # (SIGKILL, hang, network partition) and its items re-queue
            path = self._incident_recorder.trigger(
                'watchdog_reap',
                args={'worker_id': worker_id, 'reason': reason})
            if path is not None:
                self._correlate_incident(
                    worker_id, {'bundle': path, 'kind': 'watchdog_reap',
                                'cause': 'hang'})
        failed = self.scheduler.remove_worker(key)
        if failed:
            logger.error('dispatcher: %d item(s) exhausted their attempt '
                         'budget when worker %s (%s)', len(failed),
                         key.hex(), reason)
        for _token, client_key, client_token in failed:
            self._send_attempt_exhausted(client_key, client_token)
        self._note_reshard('worker-leave' if reason == 'left'
                           else 'worker-stale')

    def _note_reshard(self, reason: str) -> None:
        """Re-split undelivered work after an elastic worker-set change and
        make the decision observable: a ``reshard`` trace instant on the
        flight recorder plus an incident-correlatable event — repeated
        membership churn then reads as ONE scheduling-skew incident, not
        scattered log lines."""
        summary = self.scheduler.reshard(reason)
        if summary is None:
            return
        from petastorm_tpu.telemetry.tracing import trace_instant
        trace_instant('reshard', args=summary)
        logger.info('dispatcher: resharded %d undelivered item(s) across %d '
                    'worker(s) (%s)', summary['undelivered'],
                    summary['workers'], reason)
        if self._incident_recorder is not None:
            path = self._incident_recorder.trigger('reshard', args=summary)
            self._correlate_incident(
                None, {'bundle': path, 'kind': 'reshard',
                       'cause': 'scheduling-skew'})

    def _check_stale(self) -> None:
        now = time.monotonic()
        if now < self._next_stale_check:
            return
        self._next_stale_check = now + 0.5
        for key in self.scheduler.stale_workers():
            logger.warning('dispatcher: worker %s heartbeat went stale (or '
                           'an item passed its deadline); deregistering and '
                           're-queuing its items', key.hex())
            self._depart_worker(key, reason='went stale')
        for key in self.scheduler.expired_clients():
            logger.info('dispatcher: collecting idle client %s (silent past '
                        'the %gs TTL)', key.hex(),
                        self.scheduler.client_ttl_s)
            self.scheduler.remove_client(key)

    def _dispatch_ready(self) -> None:
        while True:
            assignment = self.scheduler.next_assignment()
            if assignment is None:
                return
            self._worker_socket.send_multipart([
                assignment.worker_key, MSG_WORK,
                b'%d' % assignment.token, assignment.setup_id,
                assignment.blob, b'%d' % assignment.attempt,
                b'1' if assignment.colocated else b'0',
                assignment.setup_blob if assignment.setup_blob is not None
                else b''])
