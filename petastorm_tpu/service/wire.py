"""Service wire helpers: URL parsing and the registration / shm-result descriptors.

The dispatcher, the service worker and the client transport speak a
kind-literal-prefixed multipart protocol (the same style as the in-process
pool's ``process_pool.py``/``process_worker_main.py`` pair); the literals live
in the peer modules themselves so pipecheck's protocol-conformance rule can
set-match the three sides cross-file (docs/static-analysis.md). This module
holds what is genuinely shared and structural:

- :func:`parse_service_url` / :func:`worker_endpoint` — one URL names the
  whole service; the worker-registration ROUTER rides on ``port + 1``.
- :class:`WorkerDescriptor` — what a decode worker sends when it registers
  (``register`` message): identity, host token (co-location detection for the
  shm fast path), capacity, and its heartbeat cadence so the dispatcher can
  size the staleness window per worker.
- :class:`ShmResultDescriptor` — the one-shot shared-memory handoff for
  co-located clients: segment name, per-frame lengths, and a CRC-32 of the
  payload (:func:`petastorm_tpu.workers.integrity.payload_checksum`) verified
  before deserialization, exactly like the in-process shm ring's frames.
- :class:`WorkerMetricsUpdate` — the fleet metrics-plane piggyback
  (docs/observability.md "Live metrics plane"): a worker's CUMULATIVE
  telemetry registry snapshot riding its heartbeat socket as ``w_metrics``
  frames; the dispatcher keeps the latest per worker (``seq``-guarded) and
  merges them at scrape time, so a dropped update loses freshness, never
  data.

Both descriptors serialize via ``to_bytes``/``from_bytes`` JSON specs —
pipecheck cross-checks the written and read key sets the same way it does for
``workers/shm_ring.py``."""

from __future__ import annotations

import json
import socket
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: offset of the worker-registration ROUTER port from the client port: one
#: ``service_url`` names the whole service
WORKER_PORT_OFFSET = 1

#: accepted URL schemes for ``service_url``
_SCHEMES = ('tcp://', 'petastorm-service://')


def parse_service_url(service_url: str) -> Tuple[str, int]:
    """``'tcp://host:port'`` (or ``petastorm-service://``) -> ``(host, port)``.

    The port is the CLIENT endpoint; workers register on
    ``port + WORKER_PORT_OFFSET`` (:func:`worker_endpoint`)."""
    rest = None
    for scheme in _SCHEMES:
        if service_url.startswith(scheme):
            rest = service_url[len(scheme):]
            break
    if rest is None or ':' not in rest:
        raise ValueError(
            'service_url must look like tcp://host:port, got {!r}'
            .format(service_url))
    host, _, port_text = rest.rpartition(':')
    try:
        port = int(port_text)
    except ValueError:
        raise ValueError('service_url port is not an integer: {!r}'
                         .format(service_url))
    if not host:
        raise ValueError('service_url has no host: {!r}'.format(service_url))
    return host, port


def client_endpoint(service_url: str) -> str:
    """The ZMQ endpoint reader clients connect to."""
    host, port = parse_service_url(service_url)
    return 'tcp://{}:{}'.format(host, port)


def worker_endpoint(service_url: str) -> str:
    """The ZMQ endpoint decode workers register on (``client port + 1``)."""
    host, port = parse_service_url(service_url)
    return 'tcp://{}:{}'.format(host, port + WORKER_PORT_OFFSET)


#: clamp for submit cost hints, shared by BOTH sides of the wire: the client
#: scheduler prices items into this range and the dispatcher re-clamps and
#: sizes its DRR guard from the same bound — one constant, so the two sides
#: cannot drift apart (docs/performance.md "Cost-aware scheduling")
MIN_COST_HINT = 0.25
MAX_COST_HINT = 4.0


def encode_cost(cost: float) -> bytes:
    """Wire form of a ``submit``'s measured-cost hint (docs/performance.md
    "Cost-aware scheduling"): the client's cost-aware scheduler prices each
    work item in median-relative units and the dispatcher's DRR charges that
    instead of a uniform unit cost. Plain decimal text, like the token and
    attempt frames."""
    return ('%.6f' % float(cost)).encode('ascii')


def decode_cost(blob: bytes, default: float = 1.0) -> float:
    """Parse a :func:`encode_cost` frame; a malformed or non-positive value
    degrades to ``default`` (uniform cost) — a bad hint must never reject
    the work item it rides on."""
    try:
        cost = float(blob)
    except ValueError:
        return default
    if not cost > 0.0:
        return default
    return cost


def host_token() -> str:
    """Co-location token compared between a client's hello and a worker's
    registration: equal tokens mean same host, so the one-shot shm result
    path is usable (a false match is survivable — the client falls back to
    re-submitting the item when the segment cannot be attached)."""
    return socket.gethostname()


class WorkerDescriptor(object):
    """Registration record a decode worker sends to the dispatcher."""

    __slots__ = ('worker_id', 'pid', 'host', 'capacity',
                 'heartbeat_interval_s', 'shm_results')

    def __init__(self, worker_id: int, pid: int, host: str, capacity: int = 1,
                 heartbeat_interval_s: float = 0.5,
                 shm_results: bool = False) -> None:
        self.worker_id = worker_id
        self.pid = pid
        self.host = host
        self.capacity = capacity
        self.heartbeat_interval_s = heartbeat_interval_s
        self.shm_results = shm_results

    def to_bytes(self) -> bytes:
        """JSON spec for the ``register`` message."""
        spec: Dict[str, Any] = {
            'worker_id': self.worker_id,
            'pid': self.pid,
            'host': self.host,
            'capacity': self.capacity,
            'heartbeat_interval_s': self.heartbeat_interval_s,
            'shm_results': self.shm_results,
        }
        return json.dumps(spec).encode('utf-8')

    @classmethod
    def from_bytes(cls, blob: bytes) -> 'WorkerDescriptor':
        """Decode a :meth:`to_bytes` spec."""
        spec = json.loads(blob.decode('utf-8'))
        return cls(worker_id=int(spec['worker_id']), pid=int(spec['pid']),
                   host=str(spec['host']), capacity=int(spec['capacity']),
                   heartbeat_interval_s=float(spec['heartbeat_interval_s']),
                   shm_results=bool(spec['shm_results']))


class ShmResultDescriptor(object):
    """One-shot shared-memory result handoff (co-located client fast path).

    The worker writes the serialized result frames back-to-back into a fresh
    ``multiprocessing.shared_memory`` segment and ships only this descriptor;
    the client maps the segment, verifies ``crc`` over the payload, copies the
    columns out during deserialization, and unlinks the segment. ``crc`` is
    ``None`` only when checksumming is disabled."""

    __slots__ = ('name', 'frame_lengths', 'crc')

    def __init__(self, name: str, frame_lengths: Sequence[int],
                 crc: Optional[int]) -> None:
        self.name = name
        self.frame_lengths = list(frame_lengths)
        self.crc = crc

    @property
    def total_bytes(self) -> int:
        """Payload size across all frames."""
        return sum(self.frame_lengths)

    def to_bytes(self) -> bytes:
        """JSON spec for the ``w_result_shm`` message."""
        spec: Dict[str, Any] = {
            'name': self.name,
            'frame_lengths': self.frame_lengths,
            'crc': self.crc,
        }
        return json.dumps(spec).encode('utf-8')

    @classmethod
    def from_bytes(cls, blob: bytes) -> 'ShmResultDescriptor':
        """Decode a :meth:`to_bytes` spec."""
        spec = json.loads(blob.decode('utf-8'))
        lengths: List[int] = [int(n) for n in spec['frame_lengths']]
        crc = spec['crc']
        return cls(name=str(spec['name']), frame_lengths=lengths,
                   crc=int(crc) if crc is not None else None)


class WorkerMetricsUpdate(object):
    """One worker's cumulative telemetry snapshot for the fleet metrics
    plane (``w_metrics`` message body — module docstring). ``seq`` orders
    updates so a late-delivered older snapshot can never roll a worker's
    fleet view backwards."""

    __slots__ = ('worker_id', 'seq', 'snapshot')

    def __init__(self, worker_id: int, seq: int,
                 snapshot: Dict[str, Any]) -> None:
        self.worker_id = worker_id
        self.seq = seq
        self.snapshot = snapshot

    def to_bytes(self) -> bytes:
        """JSON spec for the ``w_metrics`` message."""
        spec: Dict[str, Any] = {
            'worker_id': self.worker_id,
            'seq': self.seq,
            'snapshot': self.snapshot,
        }
        return json.dumps(spec).encode('utf-8')

    @classmethod
    def from_bytes(cls, blob: bytes) -> 'WorkerMetricsUpdate':
        """Decode a :meth:`to_bytes` spec."""
        spec = json.loads(blob.decode('utf-8'))
        snapshot = spec['snapshot']
        return cls(worker_id=int(spec['worker_id']), seq=int(spec['seq']),
                   snapshot=dict(snapshot) if snapshot else {})


class WorkerIncidentUpdate(object):
    """One worker-captured incident-bundle reference for the fleet incident
    plane (``w_incident`` message body — telemetry/incident.py,
    docs/observability.md "Incident autopsy plane"). ``reference`` is the
    :func:`~petastorm_tpu.telemetry.incident.bundle_reference` dict — kind,
    cause, context, size, and the inlined bundle files when the bundle fit
    under the shipping cap. ``seq`` orders ships so a late-delivered older
    incident can never be double-adopted after a newer one."""

    __slots__ = ('worker_id', 'seq', 'reference')

    def __init__(self, worker_id: int, seq: int,
                 reference: Dict[str, Any]) -> None:
        self.worker_id = worker_id
        self.seq = seq
        self.reference = reference

    def to_bytes(self) -> bytes:
        """JSON spec for the ``w_incident`` message."""
        spec: Dict[str, Any] = {
            'worker_id': self.worker_id,
            'seq': self.seq,
            'reference': self.reference,
        }
        return json.dumps(spec).encode('utf-8')

    @classmethod
    def from_bytes(cls, blob: bytes) -> 'WorkerIncidentUpdate':
        """Decode a :meth:`to_bytes` spec."""
        spec = json.loads(blob.decode('utf-8'))
        reference = spec['reference']
        return cls(worker_id=int(spec['worker_id']), seq=int(spec['seq']),
                   reference=dict(reference) if reference else {})
