"""Client transport for the disaggregated input service: the ``ServicePool``.

:class:`ServicePool` implements the same pool interface as
:class:`~petastorm_tpu.workers.process_pool.ProcessPool` (``start`` /
``ventilate`` / ``get_results`` / ``stop`` / ``join`` / ``diagnostics`` /
``workers_count`` / ``telemetry``), so the ``Reader`` runtime — resilience
``on_error`` modes, the quarantine ledger, telemetry and trace sidecars,
checkpoint/resume accounting — works unchanged when ``make_reader`` points at
a ``service_url`` instead of building an in-process pool. That client-side
transparency is the tf.data design goal (arXiv 2106.xxxxx "tf.data: A Machine
Learning Data Processing Framework"): the same call, a different placement.

Transport: one DEALER socket to the dispatcher's client ROUTER, driven
entirely from the consumer thread (``ventilate`` only enqueues locally — ZMQ
sockets are not thread-safe). The client:

- ``hello``s at construction (learns the fleet size and its admission
  window; an unreachable dispatcher raises
  :class:`~petastorm_tpu.errors.TransientIOError` immediately);
- ``open``s its dilled worker setup once per reader at ``start``;
- ``submit``s work items up to its admission window, honoring explicit
  ``busy`` rejections with a short backoff (the dispatcher's admission
  control is the real backpressure — the client never spins on it);
- receives ``result`` frames (the shared wire codec deserializes them — all
  batch sidecars arrive intact) or ``result_shm`` descriptors on the
  co-located fast path (map, CRC-verify, copy out, unlink; an unattachable
  or corrupt segment triggers a ``shm_fail`` redelivery request instead of a
  lost row);
- re-arms submits that the dispatcher never acknowledged and records the
  failures on a transport :class:`~petastorm_tpu.resilience.CircuitBreaker`
  — a dead dispatcher fails the read loudly once the breaker opens, instead
  of hanging forever.

Worker death mid-item needs nothing here: the dispatcher re-queues the dead
worker's items and a fresh result arrives on the same token (duplicate
results are dropped dispatcher-side, stale acks cannot retire redeliveries —
the in-process pool's exact protocol, now across the network)."""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Deque, Dict, List, Optional, Set, Tuple

from petastorm_tpu.errors import TransientIOError
from petastorm_tpu.service.wire import (ShmResultDescriptor, client_endpoint,
                                        encode_cost, host_token)
from petastorm_tpu.telemetry.registry import (MetricsRegistry,
                                              telemetry_enabled)
from petastorm_tpu.workers import EmptyResultError, TimeoutWaitingForResultError

logger = logging.getLogger(__name__)

#: how long the constructor waits for the dispatcher's ``welcome``
DEFAULT_CONNECT_TIMEOUT_S = 5.0
#: an unacknowledged ``submit`` older than this is re-armed and counts a
#: transport-breaker failure
DEFAULT_RESPONSE_TIMEOUT_S = 10.0
#: pause after a ``busy`` rejection before the next submit attempt
BUSY_BACKOFF_S = 0.05
#: transport breaker: consecutive unacknowledged requests before the read
#: fails fast, and the cooldown before a retry probe
TRANSPORT_BREAKER_THRESHOLD = 3
TRANSPORT_BREAKER_RECOVERY_S = 30.0
#: env override for the response timeout (chaos harness / tests shrink it
#: so dispatcher-crash recovery is detected in seconds, not the 10s default)
RESPONSE_TIMEOUT_ENV = 'PETASTORM_TPU_SERVICE_RESPONSE_TIMEOUT_S'


def fetch_service_state(service_url: str,
                        timeout_s: float = 2.0) -> Dict[str, Any]:
    """One ``state`` request/reply against a dispatcher: the scheduler
    snapshot (clients, workers, queue depths, fair-share debts). Raises
    :class:`TransientIOError` when the service does not answer in time —
    doctor turns that into its unreachable WARNING.

    A HALF-UP dispatcher — socket bound, pump not started yet (the
    start-sequence window, or a wedged pump thread) — accepts the TCP
    connection but answers nothing. Instead of blocking the full timeout,
    an anonymous ``hello`` probe rides behind the ``state`` request after a
    short grace; if the TCP link is up but both stay unanswered at the
    deadline, the caller gets ``{'state': 'starting'}`` rather than an
    exception (doctor renders that as a starting service, not a dead
    one)."""
    import zmq
    context = zmq.Context()
    socket = context.socket(zmq.DEALER)
    socket.setsockopt(zmq.LINGER, 0)
    monitor = socket.get_monitor_socket(
        zmq.EVENT_CONNECTED | zmq.EVENT_CONNECT_DELAYED
        | zmq.EVENT_CONNECT_RETRIED)
    connected = False
    probe_sent = False
    try:
        socket.connect(client_endpoint(service_url))
        socket.send_multipart([b'state'])
        deadline = time.monotonic() + timeout_s
        probe_at = time.monotonic() + min(0.5, timeout_s / 2.0)
        while time.monotonic() < deadline:
            if not connected and monitor.poll(0, zmq.POLLIN):
                event = monitor.recv_multipart()
                if int.from_bytes(event[0][:2], 'little') \
                        == zmq.EVENT_CONNECTED:
                    connected = True
            if connected and not probe_sent \
                    and time.monotonic() >= probe_at:
                # cheap liveness probe: an empty-name hello is answered
                # without registering a client (dispatcher probe path)
                socket.send_multipart([b'hello', b'',
                                       host_token().encode('utf-8'), b'0'])
                probe_sent = True
            if not socket.poll(50, zmq.POLLIN):
                continue
            frames = socket.recv_multipart()
            kind = frames[0]
            if kind == b'state' and len(frames) >= 2:
                out = json.loads(frames[1].decode('utf-8'))
                assert isinstance(out, dict)
                return out
            if kind == b'welcome':
                # the probe answered but state has not: keep waiting for it
                continue
        if connected:
            return {'state': 'starting', 'service_url': service_url}
        raise TransientIOError(
            'input service at {} did not answer a state request within {}s'
            .format(service_url, timeout_s))
    finally:
        try:
            socket.disable_monitor()
        except Exception:  # noqa: BLE001 - monitor teardown is best-effort across pyzmq versions
            pass
        monitor.close(linger=0)
        socket.close(linger=0)
        context.term()


class ServicePool(object):
    """Pool-interface adapter over the service dispatcher (module docstring).

    Build one per reader — ``make_reader(..., service_url=...)`` does — and
    use it exactly like a :class:`~petastorm_tpu.workers.process_pool.
    ProcessPool`. ``window`` caps this client's in-flight items (the
    dispatcher clamps it to its own admission window); ``payload_serializer``
    must match what the service workers publish with (the default
    :class:`~petastorm_tpu.workers.serializers.ArrowIpcSerializer` — it is
    shipped to the workers inside the ``open`` blob, so they always agree)."""

    def __init__(self, service_url: str, window: Optional[int] = None,
                 payload_serializer: Any = None, client_name: Optional[str] = None,
                 connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
                 response_timeout_s: float = DEFAULT_RESPONSE_TIMEOUT_S,
                 breaker: Any = None) -> None:
        from petastorm_tpu.resilience import default_board
        from petastorm_tpu.workers.serializers import ArrowIpcSerializer
        self.service_url = service_url
        self._serializer = (payload_serializer if payload_serializer is not None
                            else ArrowIpcSerializer())
        self._client_name = client_name or 'reader-{}-{}'.format(
            os.getpid(), uuid.uuid4().hex[:6])
        env_timeout = os.environ.get(RESPONSE_TIMEOUT_ENV)
        if env_timeout:
            try:
                response_timeout_s = float(env_timeout)
            except ValueError:
                logger.warning('ignoring non-numeric %s=%r',
                               RESPONSE_TIMEOUT_ENV, env_timeout)
        self._response_timeout_s = response_timeout_s
        # On the process-global board (not instance-owned like the pool's shm
        # breaker): its tripped state then rides the existing breakers
        # plumbing into Reader.diagnostics['breakers'] and doctor's
        # resilience block with zero extra wiring.
        self._breaker = breaker if breaker is not None else \
            default_board().breaker(
                'service:{}'.format(service_url),
                failure_threshold=TRANSPORT_BREAKER_THRESHOLD,
                recovery_timeout_s=TRANSPORT_BREAKER_RECOVERY_S)
        self.telemetry = MetricsRegistry()
        self._lock = threading.Lock()
        self._ventilator: Any = None
        self._stopped = False
        self._setup_id = uuid.uuid4().hex.encode('ascii')
        self._setup_opened = False
        #: kept for ``rejoin``: a restarted (or TTL-collecting) dispatcher
        #: lost our registration and setup — we re-``hello``/``open`` from
        #: these and resubmit, so an epoch survives a dispatcher restart
        self._open_blob: Optional[bytes] = None
        self._hello_window = window or 0
        self._last_rejoin = 0.0
        self._next_token = 0
        #: token -> dilled kwargs; kept until the result is delivered so the
        #: item can be re-armed after transport failures
        self._items: Dict[int, bytes] = {}
        #: optional measured-cost pricer installed by a cost-scheduled reader
        #: (docs/performance.md "Cost-aware scheduling"); None => submits
        #: carry no cost frame, the dispatcher charges the uniform unit
        self._cost_hint_fn: Optional[Any] = None
        #: token -> cost hint, dropped with the item
        self._item_costs: Dict[int, float] = {}
        self._pending: Deque[int] = collections.deque()
        #: tokens submitted and not yet resolved by a result
        self._inflight: Set[int] = set()
        #: token -> deadline for the dispatcher's accept/busy ack
        self._await_ack: Dict[int, float] = {}
        self._busy_until = 0.0
        #: reply-starvation watchdog (see ``_check_starvation``): when the
        #: dispatcher goes silent while we hold in-flight work, probe it,
        #: then re-arm the in-flight items and record a breaker failure
        self._last_reply = time.monotonic()
        self._starvation_probe_sent = False
        # ------------------------------------------------------- counters
        self._busy_rejections = 0
        self._results_dropped = 0
        self._resubmitted = 0
        self._shm_batches = 0
        self._wire_batches = 0
        self._unacked_timeouts = 0
        self._starvation_resubmits = 0
        self._rejoins = 0
        #: ledger-epoch handshake state (docs/service.md "Dispatcher crash
        #: with a ledger"): the epoch the dispatcher reported at welcome;
        #: a ``ledger_state`` reply with a DIFFERENT epoch (or known=False)
        #: means our in-flight tokens died with a previous incarnation
        self._ledger_epoch: Optional[int] = None
        self._ledger_rearms = 0

        import zmq
        self._context = zmq.Context()
        self._socket = self._context.socket(zmq.DEALER)
        self._socket.setsockopt(zmq.LINGER, 0)
        self._socket.connect(client_endpoint(service_url))
        self._socket.send_multipart([
            b'hello', self._client_name.encode('utf-8'),
            host_token().encode('utf-8'), b'%d' % (window or 0)])
        welcome = self._await_reply(b'welcome', connect_timeout_s)
        if welcome is None:
            self._socket.close(linger=0)
            self._context.term()
            raise TransientIOError(
                'input service at {} did not answer hello within {}s — is '
                'the dispatcher running?'.format(service_url,
                                                 connect_timeout_s))
        body = json.loads(welcome[1].decode('utf-8'))
        self._window = int(body['window'])
        if 'ledger_epoch' in body:
            self._ledger_epoch = int(body['ledger_epoch'])
        #: registered decode workers at hello time (fleet may grow/shrink);
        #: the Reader sizes its in-flight ventilation window from this
        self.workers_count = max(1, int(body['workers']))

    # ------------------------------------------------------------ messaging

    def _learn_window(self, window: int) -> None:
        """Adopt the dispatcher-side window piggybacked on accept/busy
        replies: the service autotuner retunes per-client windows live
        (docs/autotuning.md), and without re-learning it a raised window
        could never admit more in-flight work from this client (nor a
        lowered one end the busy churn before the next hello). Consumer
        thread only, like every other socket-path mutation here."""
        if window > 0 and window != self._window:
            self._window = window

    def _await_reply(self, expected_kind: bytes,
                     timeout_s: float) -> Optional[List[bytes]]:
        """Wait for one message of ``expected_kind`` (construction/start
        handshakes only — anything else arriving this early is dropped)."""
        import zmq
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self._socket.poll(100, zmq.POLLIN):
                continue
            frames = self._socket.recv_multipart()
            kind = frames[0]
            if kind == expected_kind:
                return frames
        return None

    # ------------------------------------------------------------ lifecycle

    def start(self, worker_class: Any, worker_args: Any = None,
              ventilator: Any = None) -> None:
        """Ship the dilled worker setup (``open``) and start the ventilator.
        No processes are spawned — the fleet already runs server-side."""
        import dill
        blob = dill.dumps({'worker_class': worker_class,
                           'worker_args': worker_args,
                           'serializer': self._serializer})
        self._open_blob = blob
        self._socket.send_multipart([b'open', self._setup_id, blob])
        if self._await_reply(b'opened', self._response_timeout_s) is None:
            raise TransientIOError(
                'input service at {} did not acknowledge the worker setup '
                'within {}s'.format(self.service_url,
                                    self._response_timeout_s))
        self._setup_opened = True
        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    def set_cost_hint_fn(self, fn: Any) -> None:
        """Install the reader's cost pricer: ``fn(item_kwargs) -> float``
        (median-relative measured cost). Every later submit ships the hint
        so the dispatcher's DRR charges real cost and routes heavy items
        least-loaded (docs/performance.md "Cost-aware scheduling"). Call
        before ``start`` — pricing is read on the ventilation path."""
        self._cost_hint_fn = fn

    def ventilate(self, **kwargs: Any) -> None:
        """Enqueue one work item locally; the consumer thread submits it to
        the dispatcher inside ``get_results`` (single-threaded socket use)."""
        if self._stopped:
            raise RuntimeError('ServicePool is stopped')
        import dill
        blob = dill.dumps(kwargs)
        cost: Optional[float] = None
        if self._cost_hint_fn is not None:
            try:
                cost = float(self._cost_hint_fn(kwargs))
            except Exception:  # noqa: BLE001 - a broken pricer must not drop the work item; it just rides uncosted
                logger.warning('cost hint fn failed for piece %r; submitting '
                               'uncosted', kwargs.get('piece_index'),
                               exc_info=True)
                cost = None
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._items[token] = blob
            if cost is not None:
                self._item_costs[token] = cost
            self._pending.append(token)

    # -------------------------------------------------------------- submits

    def _flush_submits(self) -> None:
        """Send pending items up to the admission window (consumer thread).
        A ``busy`` backoff pauses all submits briefly — the dispatcher told
        us the window is full, so hammering it only burns cycles."""
        now = time.monotonic()
        if now < self._busy_until:
            return
        while True:
            with self._lock:
                if not self._pending:
                    return
                if len(self._inflight) >= self._window:
                    return
                token = self._pending.popleft()
                blob = self._items.get(token)
                if blob is None:
                    continue
                self._inflight.add(token)
                self._await_ack[token] = now + self._response_timeout_s
                cost = self._item_costs.get(token)
            frames = [b'submit', b'%d' % token, self._setup_id, blob]
            if cost is not None:
                frames.append(encode_cost(cost))
            self._socket.send_multipart(frames)

    def _check_unacked(self) -> None:
        """Re-arm submits the dispatcher never acknowledged and record the
        failure on the transport breaker; an open breaker fails the read
        fast instead of waiting out a dead dispatcher forever."""
        now = time.monotonic()
        overdue = []
        with self._lock:
            for token, deadline in list(self._await_ack.items()):
                if now > deadline:
                    overdue.append(token)
                    del self._await_ack[token]
                    self._inflight.discard(token)
                    if token in self._items:
                        self._pending.appendleft(token)
        for _ in overdue:
            self._unacked_timeouts += 1
            self._breaker.record_failure()
        if overdue and not self._breaker.allow():
            raise TransientIOError(
                'input service at {} stopped acknowledging submissions '
                '({} unacknowledged); transport breaker is {}'.format(
                    self.service_url, len(overdue), self._breaker.state))

    def _rearm_inflight(self) -> None:
        """Re-pend every in-flight token (front of the queue, ventilation
        order preserved). The dispatcher restart / starvation paths call
        this when those tokens died with a previous dispatcher incarnation;
        a straggler result for an old token is dropped by the token dedup
        on whichever side sees it first, so re-arming is duplicate-safe."""
        with self._lock:
            for token in sorted(self._inflight, reverse=True):
                if token in self._items:
                    self._pending.appendleft(token)
            self._inflight.clear()
            self._await_ack.clear()

    def _check_starvation(self) -> None:
        """Dead-dispatcher detector for the post-accept phase: submit acks
        alone cannot see a dispatcher that died (or restarted) AFTER
        accepting our window. When nothing at all has arrived for one
        response window while we hold in-flight work, send a ``ledger_sync``
        probe — a RESTARTED dispatcher's ``ledger_state`` reply says it does
        not know us (or serves a new ledger epoch) and triggers the precise
        re-arm in ``get_results``, while a merely-slow dispatcher's reply
        resets the clock. After a second fully-silent window (a DEAD
        dispatcher answers nothing, not even the probe), assume the
        in-flight items are lost: re-arm them (duplicates are dropped
        server-side), record a transport-breaker failure, and fail the read
        fast once the breaker opens."""
        with self._lock:
            inflight = len(self._inflight)
        if not inflight:
            self._starvation_probe_sent = False
            return
        now = time.monotonic()
        silent = now - self._last_reply
        if silent <= self._response_timeout_s:
            return
        if not self._starvation_probe_sent:
            self._socket.send_multipart([b'ledger_sync'])
            self._starvation_probe_sent = True
            return
        if silent <= 2 * self._response_timeout_s:
            return
        self._rearm_inflight()
        self._starvation_resubmits += 1
        self._starvation_probe_sent = False
        self._last_reply = now
        self._breaker.record_failure()
        if not self._breaker.allow():
            raise TransientIOError(
                'input service at {} went silent with {} item(s) in flight; '
                'transport breaker is {}'.format(self.service_url, inflight,
                                                 self._breaker.state))

    # -------------------------------------------------------------- results

    def get_results(self, timeout: Optional[float] = None) -> Any:
        """Next result batch; raises ``EmptyResultError`` when all ventilated
        work completed, re-raises worker exceptions shipped over the wire."""
        import zmq
        deadline = None if timeout is None else time.monotonic() + timeout
        wait_start = time.perf_counter()
        while True:
            if self._stopped:
                raise RuntimeError('ServicePool is stopped')
            self._flush_submits()
            if not self._socket.poll(100, zmq.POLLIN):
                self._check_unacked()
                self._check_starvation()
                if self._ventilator is not None and getattr(
                        self._ventilator, 'error', None):
                    self.stop()
                    raise self._ventilator.error
                with self._lock:
                    drained = (not self._pending and not self._inflight)
                if drained and self._ventilator is not None \
                        and self._ventilator.completed():
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            frames = self._socket.recv_multipart()
            kind = frames[0]
            self._last_reply = time.monotonic()
            self._starvation_probe_sent = False
            if kind == b'accept':
                with self._lock:
                    self._await_ack.pop(int(bytes(frames[1])), None)
                if len(frames) >= 3:
                    self._learn_window(int(bytes(frames[2])))
                self._breaker.record_success()
                continue
            if kind == b'busy':
                token = int(bytes(frames[1]))
                with self._lock:
                    self._await_ack.pop(token, None)
                    self._inflight.discard(token)
                    if token in self._items:
                        self._pending.appendleft(token)
                if len(frames) >= 3:
                    self._learn_window(int(bytes(frames[2])))
                self._busy_until = time.monotonic() + BUSY_BACKOFF_S
                self._busy_rejections += 1
                if telemetry_enabled():
                    self.telemetry.inc('service_busy')
                continue
            if kind == b'rejoin':
                # the dispatcher does not know us (restart / TTL collection).
                # A dispatcher that does not know us cannot hold ANY of our
                # tokens (TTL collection requires an empty in-flight set;
                # a restart lost them all) — re-arm every in-flight item,
                # not just the bounced one, then re-hello + re-open before
                # the resubmits flush
                self._rearm_inflight()
                self._rejoin()
                continue
            if kind == b'ledger_state' and len(frames) >= 2:
                # ledger-epoch handshake reply (our starvation probe): a
                # dispatcher that does not know us, or one serving a new
                # ledger epoch, is a fresh incarnation — its predecessor
                # took our in-flight tokens with it
                body = json.loads(frames[1].decode('utf-8'))
                epoch = body.get('epoch')
                restarted = (not body.get('known')
                             or (self._ledger_epoch is not None
                                 and epoch != self._ledger_epoch))
                if epoch is not None:
                    self._ledger_epoch = int(epoch)
                if restarted:
                    self._ledger_rearms += 1
                    self._rearm_inflight()
                    self._rejoin()
                continue
            if kind == b'result':
                result = self._handle_result(int(bytes(frames[1])),
                                             frames[2:])
                if result is None:
                    continue
                if telemetry_enabled():
                    self.telemetry.observe('pool_wait',
                                           time.perf_counter() - wait_start)
                return result[0]
            if kind == b'result_shm':
                result = self._handle_shm_result(int(bytes(frames[1])),
                                                 frames[2])
                if result is None:
                    continue
                if telemetry_enabled():
                    self.telemetry.observe('pool_wait',
                                           time.perf_counter() - wait_start)
                return result[0]
            if kind == b'error':
                import pickle
                exc, tb = pickle.loads(frames[2])
                logger.error('Service worker failure re-raised in consumer:'
                             '\n%s', tb)
                self.stop()
                raise exc
            # welcome/opened/state stragglers from handshake retries: ignore
            # (but adopt a straggler welcome's ledger epoch — it is the
            # freshest statement of which dispatcher incarnation we talk to)
            if kind == b'welcome' or kind == b'opened' or kind == b'state':
                if kind == b'welcome' and len(frames) >= 2:
                    try:
                        body = json.loads(frames[1].decode('utf-8'))
                        if 'ledger_epoch' in body:
                            self._ledger_epoch = int(body['ledger_epoch'])
                    except (ValueError, KeyError):
                        pass
                continue

    def _resolve_token(self, token: int) -> bool:
        """Retire a token on result delivery; False = duplicate, drop it."""
        with self._lock:
            if token not in self._items:
                self._results_dropped += 1
                return False
            del self._items[token]
            self._item_costs.pop(token, None)
            self._inflight.discard(token)
            self._await_ack.pop(token, None)
        if self._ventilator is not None:
            self._ventilator.processed_item()
        return True

    def _handle_result(self, token: int,
                       payload: List[bytes]) -> Optional[Tuple[Any]]:
        if not self._resolve_token(token):
            return None
        self._wire_batches += 1
        self._breaker.record_success()
        return (self._serializer.deserialize(payload),)

    def _handle_shm_result(self, token: int,
                           descriptor_blob: bytes) -> Optional[Tuple[Any]]:
        """Co-located fast path: map the one-shot segment, CRC-verify, copy
        out during deserialize, unlink. Failure to attach or verify requests
        a redelivery (``shm_fail``) — a lost segment is never a lost row."""
        descriptor = ShmResultDescriptor.from_bytes(descriptor_blob)
        from multiprocessing import shared_memory
        try:
            segment = shared_memory.SharedMemory(name=descriptor.name)
        except (FileNotFoundError, OSError):
            logger.warning('could not attach one-shot shm segment %s; '
                           'requesting redelivery', descriptor.name)
            self._request_redelivery(token)
            return None
        views: List[memoryview] = []
        buf: Optional[memoryview] = None
        try:
            buf = memoryview(segment.buf)
            offset = 0
            for length in descriptor.frame_lengths:
                views.append(buf[offset:offset + length])
                offset += length
            if descriptor.crc is not None:
                from petastorm_tpu.workers.integrity import payload_checksum
                if payload_checksum(views) != descriptor.crc:
                    logger.error('one-shot shm segment %s failed CRC '
                                 'verification; requesting redelivery',
                                 descriptor.name)
                    self._request_redelivery(token)
                    return None
            if not self._resolve_token(token):
                return None
            result = self._serializer.deserialize(views)
            self._shm_batches += 1
            self._breaker.record_success()
            return (result,)
        finally:
            # writable-receive contract (serializers.ArrowIpcSerializer):
            # nothing may keep aliasing the segment after deserialize, so
            # every view releases before the close + unlink
            for view in views:
                try:
                    view.release()
                except BufferError:  # pragma: no cover - a consumer kept a ref
                    pass
            if buf is not None:
                try:
                    buf.release()
                except BufferError:  # pragma: no cover
                    pass
            segment.close()
            try:
                segment.unlink()
            except FileNotFoundError:
                pass

    def _rejoin(self) -> None:
        """Re-register with a dispatcher that lost our state (throttled:
        many bounced submits must not trigger a hello storm). Ordering on
        the one DEALER socket guarantees the re-submits flushed afterwards
        arrive after the hello/open."""
        now = time.monotonic()
        if now - self._last_rejoin < 1.0:
            return
        self._last_rejoin = now
        self._rejoins += 1
        logger.warning('input service at %s lost this client\'s '
                       'registration (restart?); re-joining', self.service_url)
        self._socket.send_multipart([
            b'hello', self._client_name.encode('utf-8'),
            host_token().encode('utf-8'), b'%d' % self._hello_window])
        if self._open_blob is not None:
            self._socket.send_multipart([b'open', self._setup_id,
                                         self._open_blob])

    def _request_redelivery(self, token: int) -> None:
        """Ask the dispatcher to redeliver (wire-pinned) after a failed shm
        handoff. The token stays in-flight HERE: either the dispatcher still
        owns it (requeue delivers a fresh result) or it was already retired
        by the racing ``w_done`` — then the starvation watchdog re-arms it.
        Re-pending locally as well would decode the item twice."""
        self._socket.send_multipart([b'shm_fail', b'%d' % token])
        self._resubmitted += 1
        if telemetry_enabled():
            self.telemetry.inc('service_resubmit')

    # ------------------------------------------------------------- shutdown

    def stop(self) -> None:
        """Stop consuming; the dispatcher learns of our departure in
        ``join`` (``bye``) — nothing server-side needs tearing down."""
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()

    def join(self) -> None:
        """Say ``bye`` (the dispatcher drops our queue) and release the
        socket. The fleet itself outlives every client by design."""
        if self._context is None:
            return
        try:
            self._socket.send_multipart([b'bye'])
        except Exception:  # noqa: BLE001 - departure is best-effort; the dispatcher GCs silent clients via its own accounting
            pass
        self._socket.close(linger=200)
        self._context.term()
        self._context = None

    # ---------------------------------------------------------- diagnostics

    @property
    def diagnostics(self) -> Dict[str, Any]:
        """Client-transport counters plus a fresh dispatcher ``state``
        snapshot under ``'service'`` (``{'reachable': False}`` when the
        dispatcher stops answering) — how fleet-wide queue depths and
        fair-share debts surface in ``Reader.diagnostics``."""
        serializer_stats = dict(getattr(self._serializer, 'stats', None) or {})
        with self._lock:
            diag: Dict[str, Any] = {
                'service_url': self.service_url,
                'workers_alive': self.workers_count,
                'in_flight_items': len(self._items),
                'busy_rejections': self._busy_rejections,
                'results_dropped': self._results_dropped,
                'service_resubmitted': self._resubmitted,
                'service_shm_batches': self._shm_batches,
                'wire_batches': self._wire_batches,
                'unacked_timeouts': self._unacked_timeouts,
                'starvation_resubmits': self._starvation_resubmits,
                'rejoins': self._rejoins,
                'ledger_epoch': self._ledger_epoch,
                'ledger_rearms': self._ledger_rearms,
                'service_breaker': self._breaker.as_dict(),
                'sidecar_columns': serializer_stats.get('sidecar_columns', 0),
            }
        try:
            state = fetch_service_state(self.service_url, timeout_s=1.0)
            state['reachable'] = True
            workers = state.get('workers')
            if isinstance(workers, list):
                diag['workers_alive'] = len(workers)
        except Exception as exc:  # noqa: BLE001 - diagnostics must describe an unreachable service, not raise on it
            state = {'reachable': False, 'detail': repr(exc)}
        diag['service'] = state
        return diag
