"""Durable token ledger for the service dispatcher (docs/service.md
"Dispatcher crash with a ledger").

The :class:`~petastorm_tpu.service.dispatcher.FairShareScheduler` journals
every token lifecycle edge — issued / delivered / retired / failed /
quarantined, plus client registrations, setup-blob digests and reshard
decisions — to an append-only sidecar of CRC-framed JSON records. A
restarted dispatcher replays the journal before it serves a single frame:
the replay restores token-counter monotonicity (a straggler ``w_result``
for a pre-crash token can never collide with a fresh one), the
delivered-token set (dispatcher-side duplicate suppression survives the
restart — the client-side dedup is no longer the only line) and the
per-client cursors the ledger-epoch handshake reports back to re-adopting
clients.

Frame format (one per record)::

    >II header: payload length, CRC32(payload)
    payload:    UTF-8 JSON object with a 'kind' field

Append-only with atomic rotation: once the journal passes ``rotate_bytes``
the writer compacts its live state into ONE snapshot-carrying ``epoch``
record in a temp file and ``os.replace``s it over the journal — the same
atomic-publish discipline every sidecar in this repo uses
(``dataset_state.py`` homes; the manifest writer in
``telemetry/lineage.py``). A torn tail or a flipped byte fails its frame's
CRC; replay stops at the first bad frame (everything after an unreadable
frame is untrusted), counts it in ``frames_dropped`` and reports
``result='corrupt'`` — the dispatcher degrades LOUDLY to
replay-from-clients (incident bundle + breaker), never to a wrong order.

Durability is process-crash-level by design: frames are flushed to the OS
on every append (they survive any SIGKILL of the dispatcher process) but
not fsync'd — host power loss may cost tail frames, which replay treats
exactly like a torn tail. That keeps the armed overhead within the bench
guard (<=3%) while covering the fault model the chaos harness drives.
"""

from __future__ import annotations

import json
import logging
import os
import struct
import tempfile
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: journal basename inside a fleet cache dir / dataset local state home
#: (``dataset_state.local_state_home`` — the underscore prefix keeps it out
#: of Parquet directory listings, like every other sidecar)
LEDGER_BASENAME = '_petastorm_tpu_dispatcher_ledger.bin'

#: every record kind the journal may carry — the two-sided contract between
#: the scheduler's journal hooks and :func:`replay_journal`; pipecheck's
#: protocol rule validates both sides against this tuple (a typo'd kind
#: fails tier-1 instead of silently never replaying)
LEDGER_RECORD_KINDS = ('epoch', 'client', 'setup', 'issued', 'delivered',
                       'retired', 'failed', 'quarantined', 'reshard')

#: frame header: payload length + CRC32(payload)
_FRAME_HEADER = struct.Struct('>II')

#: journal size that triggers a compacting rotation
DEFAULT_ROTATE_BYTES = 4 << 20


def default_ledger_path(state_home: str) -> str:
    """The journal path inside a fleet cache dir or dataset state home."""
    return os.path.join(state_home, LEDGER_BASENAME)


def dataset_ledger_path(dataset_url_or_path: str,
                        cache_location: Optional[str] = None) -> Optional[str]:
    """The journal path for a dataset's local state home
    (``dataset_state.sidecar_path`` — the same placement the cost ledger and
    lineage manifest use); None when the dataset has no local home."""
    from petastorm_tpu.dataset_state import sidecar_path
    return sidecar_path(dataset_url_or_path, LEDGER_BASENAME, cache_location)


class LedgerReplay(object):
    """What one journal replay recovered (plus how trustworthy it is).

    ``result`` is ``'absent'`` (no journal — first start), ``'ok'`` (every
    frame verified) or ``'corrupt'`` (replay stopped at a bad frame;
    ``frames_dropped`` counts it and the caller must degrade loudly).
    ``'discarded'`` means the caller skipped replay on purpose (open
    ledger-replay breaker)."""

    __slots__ = ('result', 'epoch', 'next_token', 'delivered', 'served',
                 'clients', 'setups', 'frames_dropped', 'records',
                 'resharded')

    def __init__(self) -> None:
        self.result = 'absent'
        self.epoch = 0
        self.next_token = 0
        #: tokens whose result already went out to a client pre-crash —
        #: the dispatcher-side dedup set the restart must not forget
        self.delivered: set = set()
        #: per-client delivered-item cursors, keyed by client name
        self.served: Dict[str, int] = {}
        #: client name -> {host, window} as last hello'd
        self.clients: Dict[str, Dict[str, Any]] = {}
        #: setup id (hex str) -> blob digest — enough to verify a re-opened
        #: setup matches what the fleet was serving pre-crash
        self.setups: Dict[str, str] = {}
        self.frames_dropped = 0
        self.records = 0
        self.resharded = 0

    def apply(self, record: Dict[str, Any]) -> None:
        """Fold one verified record into the recovered state."""
        kind = record.get('kind')
        if kind == 'epoch':
            self.epoch = int(record.get('epoch', self.epoch))
            if 'next_token' in record:
                # a rotation snapshot is authoritative at its position
                self.next_token = int(record['next_token'])
                self.delivered = set(record.get('delivered') or ())
                self.served = dict(record.get('served') or {})
                self.clients = dict(record.get('clients') or {})
                self.setups = dict(record.get('setups') or {})
                self.resharded = int(record.get('resharded') or 0)
        elif kind == 'issued':
            token = int(record['token'])
            self.next_token = max(self.next_token, token + 1)
        elif kind == 'delivered':
            self.delivered.add(int(record['token']))
        elif kind == 'retired':
            client = record.get('client')
            if client is not None:
                self.served[client] = self.served.get(client, 0) + 1
            self.delivered.discard(int(record['token']))
        elif kind == 'failed' or kind == 'quarantined':
            self.delivered.discard(int(record['token']))
        elif kind == 'client':
            self.clients[str(record.get('name'))] = {
                'host': record.get('host'), 'window': record.get('window')}
        elif kind == 'setup':
            self.setups[str(record.get('setup'))] = str(record.get('digest'))
        elif kind == 'reshard':
            self.resharded += 1

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe summary for ``state()['ledger']`` and doctor."""
        return {'result': self.result, 'epoch': self.epoch,
                'next_token': self.next_token,
                'delivered': len(self.delivered),
                'clients': len(self.clients), 'setups': len(self.setups),
                'frames_dropped': self.frames_dropped,
                'records': self.records, 'resharded': self.resharded}


def read_frames(path: str) -> Tuple[List[Dict[str, Any]], int]:
    """Every CRC-verified record in journal order, plus the dropped-frame
    count. Stops at the FIRST bad frame (short header, short payload, CRC
    mismatch, non-JSON payload): framing after an unreadable frame cannot be
    trusted, so the suffix is abandoned — counted, never guessed at."""
    records: List[Dict[str, Any]] = []
    dropped = 0
    with open(path, 'rb') as f:
        while True:
            header = f.read(_FRAME_HEADER.size)
            if not header:
                break
            if len(header) < _FRAME_HEADER.size:
                dropped += 1
                break
            length, crc = _FRAME_HEADER.unpack(header)
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                dropped += 1
                break
            try:
                record = json.loads(payload.decode('utf-8'))
            except (UnicodeDecodeError, ValueError):
                dropped += 1
                break
            if isinstance(record, dict):
                records.append(record)
    return records, dropped


def replay_journal(path: str) -> LedgerReplay:
    """Recover a :class:`LedgerReplay` from the journal at ``path``
    (``result='absent'`` when there is none)."""
    replay = LedgerReplay()
    if not os.path.exists(path):
        return replay
    try:
        records, dropped = read_frames(path)
    except OSError as exc:
        logger.error('ledger: journal %s is unreadable (%s); degrading to '
                     'replay-from-clients', path, exc)
        replay.result = 'corrupt'
        replay.frames_dropped = 1
        return replay
    for record in records:
        replay.apply(record)
    replay.records = len(records)
    replay.frames_dropped = dropped
    replay.result = 'corrupt' if dropped else 'ok'
    return replay


class TokenLedger(object):
    """Append-only CRC-framed journal writer with atomic compaction.

    The writer mirrors just enough live state (token counter, delivered
    set, per-client cursors, setup digests) to emit a self-contained
    snapshot record at rotation — so the journal's size is bounded by the
    LIVE state, not by epoch length. All appends are serialized by an
    internal lock (the scheduler journals from the pump thread, but the
    guarantee should not depend on that)."""

    def __init__(self, path: str,
                 rotate_bytes: int = DEFAULT_ROTATE_BYTES) -> None:
        self.path = path
        self.rotate_bytes = rotate_bytes
        self._lock = threading.Lock()
        self._file: Any = None
        self._epoch = 0
        self._next_token = 0
        self._delivered: set = set()
        self._served: Dict[str, int] = {}
        self._clients: Dict[str, Dict[str, Any]] = {}
        self._setups: Dict[str, str] = {}
        self._resharded = 0
        self._appended = 0
        self._replay: Optional[LedgerReplay] = None

    # ------------------------------------------------------------ lifecycle

    def open(self, discard: bool = False) -> LedgerReplay:
        """Replay the existing journal (unless ``discard``), bump the ledger
        epoch, and start appending. Returns the replay — the caller feeds it
        to ``FairShareScheduler.adopt_replay``. ``discard=True`` (open
        ledger-replay breaker: the journal corrupted the last replays too)
        truncates the journal and starts fresh — the degrade-to-
        replay-from-clients path, loud by construction."""
        with self._lock:
            if discard:
                replay = LedgerReplay()
                replay.result = 'discarded'
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            else:
                replay = replay_journal(self.path)
            self._replay = replay
            self._epoch = replay.epoch + 1
            self._next_token = replay.next_token
            self._delivered = set(replay.delivered)
            self._served = dict(replay.served)
            self._clients = dict(replay.clients)
            self._setups = dict(replay.setups)
            self._resharded = replay.resharded
            parent = os.path.dirname(self.path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._file = open(self.path, 'ab')
        self.append_record('epoch', epoch=self._epoch)
        return replay

    def close(self) -> None:
        """Flush and release the journal handle (no terminal record — a
        clean stop and a crash replay identically, which is the point)."""
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                finally:
                    self._file.close()
                    self._file = None

    # -------------------------------------------------------------- appends

    def append_record(self, kind: str, **fields: Any) -> None:
        """Append one CRC-framed record and mirror it into the live state
        the next rotation snapshot will carry. Journal write failures are
        logged, not raised — durability is an upgrade, never a new way to
        take the data plane down."""
        record = dict(fields, kind=kind)
        payload = json.dumps(record, sort_keys=True).encode('utf-8')
        frame = _FRAME_HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._file is None:
                return
            self._mirror(kind, record)
            try:
                self._file.write(frame)
                self._file.flush()
                self._appended += 1
                if self._file.tell() >= self.rotate_bytes:
                    self._rotate()
            except OSError:
                logger.exception('ledger: append to %s failed; the journal '
                                 'is degraded until the next rotation',
                                 self.path)

    def _mirror(self, kind: str, record: Dict[str, Any]) -> None:
        # called under _lock
        if kind == 'issued':
            self._next_token = max(self._next_token,
                                   int(record['token']) + 1)
        elif kind == 'delivered':
            self._delivered.add(int(record['token']))
        elif kind == 'retired':
            client = record.get('client')
            if client is not None:
                self._served[client] = self._served.get(client, 0) + 1
            self._delivered.discard(int(record['token']))
        elif kind == 'failed' or kind == 'quarantined':
            self._delivered.discard(int(record['token']))
        elif kind == 'client':
            self._clients[str(record.get('name'))] = {
                'host': record.get('host'), 'window': record.get('window')}
        elif kind == 'setup':
            self._setups[str(record.get('setup'))] = str(record.get('digest'))
        elif kind == 'reshard':
            self._resharded += 1

    def _rotate(self) -> None:
        """Compact the journal to ONE snapshot-carrying epoch record,
        published atomically (temp file + ``os.replace``). Called under
        ``_lock``."""
        snapshot = {'kind': 'epoch', 'epoch': self._epoch,
                    'next_token': self._next_token,
                    'delivered': sorted(self._delivered),
                    'served': self._served, 'clients': self._clients,
                    'setups': self._setups, 'resharded': self._resharded}
        payload = json.dumps(snapshot, sort_keys=True).encode('utf-8')
        frame = _FRAME_HEADER.pack(len(payload),
                                   zlib.crc32(payload)) + payload
        parent = os.path.dirname(self.path) or '.'
        fd, tmp_path = tempfile.mkstemp(dir=parent,
                                        prefix='.ledger-rotate-')
        try:
            with os.fdopen(fd, 'wb') as tmp:
                tmp.write(frame)
                tmp.flush()
                os.fsync(tmp.fileno())
            self._file.close()
            os.replace(tmp_path, self.path)
            self._file = open(self.path, 'ab')
        except OSError:
            logger.exception('ledger: rotation of %s failed; journal keeps '
                             'growing until the next attempt', self.path)
            if self._file is None or self._file.closed:
                self._file = open(self.path, 'ab')
        finally:
            # no-op after a successful os.replace; on ANY failure path
            # (OSError or not) the orphaned temp file is removed
            try:
                os.unlink(tmp_path)
            except OSError:
                pass

    # ------------------------------------------------------------- snapshot

    @property
    def epoch(self) -> int:
        """The CURRENT ledger epoch (bumped on every ``open``) — what the
        ledger-epoch handshake reports to clients."""
        return self._epoch

    def state(self) -> Dict[str, Any]:
        """JSON-safe journal status for ``state()['ledger']`` / doctor."""
        with self._lock:
            out: Dict[str, Any] = {
                'armed': self._file is not None, 'path': self.path,
                'epoch': self._epoch, 'appended': self._appended,
                'delivered': len(self._delivered),
            }
            if self._replay is not None:
                out['last_replay'] = self._replay.result
                out['frames_dropped'] = self._replay.frames_dropped
                out['records_replayed'] = self._replay.records
            return out
