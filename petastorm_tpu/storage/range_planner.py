"""Footer-planned byte-range planning for Parquet column chunks.

Given a parsed Parquet footer (``pyarrow.parquet.FileMetaData``), a set of
row groups and the top-level storage columns a read needs, emit exactly the
byte ranges of the matching column chunks — then **coalesce** ranges whose
gap is at most ``gap_bytes`` into merged GETs: on an object store the gap
bytes are cheaper to over-read than a second request round-trip is to pay.
Pure planning — no I/O, no clocks — so the unit matrix in
``tests/test_storage.py`` can cover the merge geometry exhaustively
(docs/performance.md "Object-store ingest engine").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, NamedTuple, Sequence, Tuple

from petastorm_tpu.errors import MetadataError


class ByteRange(NamedTuple):
    """A half-open ``[start, stop)`` byte span of the Parquet file."""

    start: int
    stop: int

    @property
    def length(self) -> int:
        return self.stop - self.start


@dataclass(frozen=True)
class RangePlan:
    """One planned fetch: the coalesced ranges plus the accounting the
    telemetry/cost plumbing reports (raw range count before coalescing,
    total bytes the merged GETs will move, the columns covered)."""

    ranges: Tuple[ByteRange, ...]
    raw_ranges: int
    total_bytes: int
    columns: Tuple[str, ...]

    @property
    def coalesced_away(self) -> int:
        """Raw ranges merged away by coalescing (>= 0)."""
        return self.raw_ranges - len(self.ranges)


def _chunk_range(column_chunk: Any) -> ByteRange:
    """The byte span of one column chunk: dictionary page (when present)
    through the end of the compressed data pages. Offset 0 is never a valid
    chunk start (the 4-byte magic lives there) — pyarrow reports 0 for an
    absent dictionary page on some writers, so it is filtered alongside
    None."""
    offsets = [off for off in (column_chunk.dictionary_page_offset,
                               column_chunk.data_page_offset)
               if off is not None and off > 0]
    if not offsets:
        raise MetadataError(
            'column chunk {!r} has no page offsets in the footer — the '
            'file metadata is unreadable by the range planner'.format(
                column_chunk.path_in_schema))
    start = min(offsets)
    return ByteRange(start, start + column_chunk.total_compressed_size)


def column_chunk_ranges(metadata: Any, row_group_ids: Sequence[int],
                        columns: Sequence[str]) -> List[ByteRange]:
    """Raw (uncoalesced) byte ranges of every column chunk in
    ``row_group_ids`` whose top-level field name is in ``columns``
    (``path_in_schema`` is dotted for nested fields; one top-level column
    may map to several chunks). Raises :class:`MetadataError` when a
    requested column matches no chunk — a planner/projection bug must
    surface, not silently fetch nothing."""
    wanted = {str(name) for name in columns}
    seen = set()
    ranges: List[ByteRange] = []
    for row_group_id in row_group_ids:
        row_group = metadata.row_group(row_group_id)
        for index in range(row_group.num_columns):
            chunk = row_group.column(index)
            top_level = chunk.path_in_schema.split('.')[0]
            if top_level in wanted:
                seen.add(top_level)
                ranges.append(_chunk_range(chunk))
    missing = wanted - seen
    if missing and row_group_ids:
        raise MetadataError(
            'columns {} matched no column chunk in row groups {} — '
            'projection and footer disagree'.format(
                sorted(missing), list(row_group_ids)))
    return ranges


def coalesce_ranges(ranges: Sequence[ByteRange],
                    gap_bytes: int) -> Tuple[ByteRange, ...]:
    """Merge overlapping/adjacent/near-adjacent ranges: any two whose gap
    is at most ``gap_bytes`` become one. Output is sorted and disjoint."""
    if not ranges:
        return ()
    merged: List[ByteRange] = []
    for current in sorted(ranges):
        if merged and current.start - merged[-1].stop <= max(gap_bytes, 0):
            previous = merged[-1]
            merged[-1] = ByteRange(previous.start,
                                   max(previous.stop, current.stop))
        else:
            merged.append(current)
    return tuple(merged)


def plan_ranges(metadata: Any, row_group_ids: Sequence[int],
                columns: Sequence[str], gap_bytes: int) -> RangePlan:
    """Plan one fetch: raw chunk ranges for ``columns`` over
    ``row_group_ids``, coalesced under ``gap_bytes``. An empty projection
    plans an empty fetch (zero ranges) rather than erroring — the
    two-phase predicate path legitimately asks for nothing when every
    field was already read."""
    raw = column_chunk_ranges(metadata, row_group_ids, columns)
    merged = coalesce_ranges(raw, gap_bytes)
    return RangePlan(ranges=merged, raw_ranges=len(raw),
                     total_bytes=sum(r.length for r in merged),
                     columns=tuple(str(name) for name in columns))
