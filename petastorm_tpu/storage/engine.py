"""The assembled ingest engine: a planned, sparse rowgroup source.

:class:`RowGroupSource` is what the worker read path
(``reader_worker._load_and_decode`` / ``_two_phase_load``) consumes in
place of ``fragment.to_table()`` when a storage policy is armed. Per
``read_columns`` call it:

1. plans the column-chunk byte ranges the NEW columns need (footer from the
   shared :class:`~petastorm_tpu.storage.metadata_cache.MetadataCache`,
   coalesced under the policy's gap threshold);
2. executes the plan through the hedged
   :class:`~petastorm_tpu.storage.fetcher.RangeFetcher` (one ``range_fetch``
   stage span per executed plan, its trace args carrying bytes/ranges/hedge
   totals into the cost ledger);
3. parses the rowgroup out of a **sparse segmented file** — a file-like
   view of the real file that serves the fetched segments plus the cached
   footer from memory (``rowgroup_read`` therefore times ONLY the Parquet
   decode, disjoint from ``range_fetch``). Reads pyarrow makes outside the
   plan (page indexes, bloom filters) fall back to serial ranged reads of
   the real file, so correctness never depends on planner completeness.

Columns already fetched by an earlier call are never re-fetched — the
two-phase predicate path reads every storage column exactly once, same as
the seed path (docs/performance.md "Object-store ingest engine").
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import time

import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.errors import TransientIOError
from petastorm_tpu.storage import StoragePolicy, storage_metrics
from petastorm_tpu.storage.fetcher import RangeFetcher
from petastorm_tpu.storage.metadata_cache import FooterEntry, MetadataCache
from petastorm_tpu.storage.range_planner import plan_ranges
from petastorm_tpu.telemetry.spans import record_stage, stage_span


class _SegmentedFile(object):
    """Read-only file-like view of a remote file assembled from in-memory
    segments, with a serial ranged-read fallback for unplanned regions.
    Wrapped in ``pa.PythonFile`` and handed to ``pq.ParquetFile`` — pyarrow
    sees an ordinary seekable file of the true size while almost every read
    is served from memory. Single-threaded by contract (pyarrow drives it
    from the calling thread)."""

    def __init__(self, size: int, segments: Sequence[Tuple[int, bytes]],
                 fallback_read: Any) -> None:
        self._size = size
        self._segments = sorted(segments)
        self._fallback_read = fallback_read
        self._pos = 0
        self.fallback_reads = 0
        self.closed = False

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def size(self) -> int:
        return self._size

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def close(self) -> None:
        self.closed = True

    def flush(self) -> None:
        return None

    def read(self, nbytes: int = -1) -> bytes:
        if nbytes is None or nbytes < 0:
            nbytes = self._size - self._pos
        start = self._pos
        stop = min(start + nbytes, self._size)
        self._pos = stop
        if stop <= start:
            return b''
        out = bytearray(stop - start)
        covered: List[Tuple[int, int]] = []
        for seg_start, data in self._segments:
            seg_stop = seg_start + len(data)
            lo, hi = max(seg_start, start), min(seg_stop, stop)
            if lo < hi:
                out[lo - start:hi - start] = data[lo - seg_start:
                                                 hi - seg_start]
                covered.append((lo, hi))
        for gap_start, gap_stop in _uncovered(start, stop, covered):
            self.fallback_reads += 1
            filled = self._fallback_read(gap_start, gap_stop - gap_start)
            if len(filled) != gap_stop - gap_start:
                raise TransientIOError(
                    'short fallback read at [{}, {})'.format(gap_start,
                                                             gap_stop))
            out[gap_start - start:gap_stop - start] = filled
        return bytes(out)


def _uncovered(start: int, stop: int,
               covered: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """The sub-ranges of ``[start, stop)`` not covered by ``covered``
    (sorted, possibly-overlapping spans)."""
    gaps: List[Tuple[int, int]] = []
    cursor = start
    for lo, hi in sorted(covered):
        if lo > cursor:
            gaps.append((cursor, lo))
        cursor = max(cursor, hi)
    if cursor < stop:
        gaps.append((cursor, stop))
    return gaps


class RowGroupSource(object):
    """Planned reader for one fragment file (module docstring).

    ``row_group_id`` None means the whole file (the unsplit-piece case);
    otherwise the single rowgroup the work item names. One instance serves
    every ``read_columns`` call of one work item, accumulating fetched
    segments so no storage column is fetched twice."""

    def __init__(self, path: str, filesystem: Any, policy: StoragePolicy,
                 row_group_id: Optional[int],
                 metadata_cache: MetadataCache,
                 clock: Any = time.monotonic) -> None:
        self._path = path
        self._filesystem = filesystem
        self._policy = policy
        self._row_group_id = row_group_id
        self._entry: FooterEntry = metadata_cache.get(
            filesystem, path, policy.footer_read_bytes)
        self._fetcher = RangeFetcher(self._open, policy, clock=clock)
        footer_start = self._entry.file_size - len(self._entry.footer_bytes)
        self._segments: List[Tuple[int, bytes]] = [
            (footer_start, self._entry.footer_bytes)]
        self._have: Set[str] = set()
        self._fallback_lock = threading.Lock()
        self._fallback_handle: Optional[Any] = None

    # ------------------------------------------------------------ plumbing

    def _open(self) -> Any:
        return self._filesystem.open_input_file(self._path)

    def _row_group_ids(self) -> List[int]:
        if self._row_group_id is None:
            return list(range(self._entry.metadata.num_row_groups))
        return [int(self._row_group_id)]

    def _fallback_read(self, start: int, length: int) -> bytes:
        """Serial ranged read of the REAL file for a region the plan did
        not cover — the correctness net under pyarrow internals."""
        with self._fallback_lock:
            if self._fallback_handle is None:
                # the blocking open stays under the lock on purpose: fallback
                # reads share one seek+read handle, so they are serialized by
                # design, and opening outside the lock would race a second
                # open of the same file
                self._fallback_handle = self._open()  # pipecheck: disable=lock-discipline -- serialized-by-design shared handle; the blocking chain is chaos-injected open latency (test_util)
            self._fallback_handle.seek(start)
            return bytes(self._fallback_handle.read(length))

    @property
    def metadata(self) -> Any:
        """The cached ``pyarrow.parquet.FileMetaData`` footer."""
        return self._entry.metadata

    def schema_arrow(self) -> pa.Schema:
        """The file's Arrow schema (from the cached footer — what the
        empty-survivor predicate path builds its zero-row table from)."""
        schema: pa.Schema = self._entry.metadata.schema.to_arrow_schema()
        return schema

    # ----------------------------------------------------------- main read

    def read_columns(self, columns: Sequence[str]) -> pa.Table:
        """Read ``columns`` of the source's rowgroup(s) as an Arrow table
        (requested column order). Only columns not fetched by an earlier
        call are planned and fetched; the Parquet decode itself is timed as
        ``rowgroup_read``, disjoint from ``range_fetch``."""
        names = [str(name) for name in columns]
        fresh = [name for name in names if name not in self._have]
        if fresh:
            plan = plan_ranges(self._entry.metadata, self._row_group_ids(),
                               fresh, self._policy.coalesce_gap_bytes)
            if plan.coalesced_away > 0:
                storage_metrics().inc('storage_ranges_coalesced',
                                      plan.coalesced_away)
            fetched = self._fetcher.fetch(plan)
            record_stage('range_fetch', fetched.seconds,
                         trace_args=fetched.trace_args())
            for byte_range, data in fetched.segments.items():
                self._segments.append((byte_range.start, data))
            self._have.update(fresh)
        with stage_span('rowgroup_read'):
            sparse = _SegmentedFile(self._entry.file_size, self._segments,
                                    self._fallback_read)
            parquet_file = pq.ParquetFile(pa.PythonFile(sparse, mode='r'),
                                          metadata=self._entry.metadata)
            if self._row_group_id is None:
                table = parquet_file.read(columns=names)
            else:
                table = parquet_file.read_row_group(int(self._row_group_id),
                                                    columns=names)
        return table.select(names)
