"""Shared Parquet footer / row-group-metadata cache.

A reader fleet pointed at one dataset re-reads the same footers once per
worker per rowgroup on the seed path. This cache amortizes them twice over:

- an **in-process LRU** (``cache_capacity`` entries) serves every rowgroup
  piece of the same file from one footer read;
- an optional **atomic disk sidecar** (``cache_dir`` — the reader wires the
  dataset's local state home / shared disk-cache directory here, which is
  exactly the directory a co-located service fleet already shares) makes
  footers survive across processes and runs, so N clients of one dataset
  never re-read the same footers.

Entries are keyed ``(path, mtime_ns, size)``: a rewritten file (new mtime
or size) misses and refetches — the invalidation contract
``tests/test_storage.py`` pins down. Sidecar writes are atomic (temp file +
``os.replace``); a corrupt or truncated sidecar is treated as a miss, never
an error. No clocks — freshness derives entirely from filesystem stat
metadata (docs/performance.md "Object-store ingest engine").
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Any, NamedTuple, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.errors import MetadataError
from petastorm_tpu.storage import storage_metrics

#: sidecar basename pattern (one file per dataset file, keyed by path hash)
SIDECAR_BASENAME = '_petastorm_tpu_footer_{digest}.bin'

#: tail bytes read first when the footer length is unknown (a policy can
#: widen this; one extra ranged read recovers from an under-estimate)
DEFAULT_FOOTER_READ_BYTES = 64 * 1024

_FOOTER_MAGIC = b'PAR1'


class FooterEntry(NamedTuple):
    """One cached footer: the parsed metadata, the raw footer tail bytes
    (thrift + 8-byte trailer — exactly what a planned sparse file must
    serve at ``[file_size - len(footer_bytes), file_size)``), and the file
    size the footer was read at."""

    metadata: Any
    footer_bytes: bytes
    file_size: int


def _stat_key(filesystem: Any, path: str) -> Tuple[str, int, int]:
    """The cache key ``(path, mtime_ns, size)`` from one filesystem stat.
    Filesystems that report no mtime key on 0 — size changes still
    invalidate."""
    info = filesystem.get_file_info(path)
    if isinstance(info, list):
        info = info[0]
    mtime_ns = getattr(info, 'mtime_ns', None)
    return str(path), int(mtime_ns or 0), int(info.size)


def read_footer_bytes(filesystem: Any, path: str, file_size: int,
                      footer_read_bytes: int = DEFAULT_FOOTER_READ_BYTES
                      ) -> bytes:
    """Read exactly the footer tail of ``path`` (thrift metadata + 8-byte
    trailer): one speculative tail read of ``footer_read_bytes``, one exact
    re-read only when the footer is larger than the guess."""
    handle = filesystem.open_input_file(path)
    try:
        guess = min(max(int(footer_read_bytes), 16), file_size)
        handle.seek(file_size - guess)
        tail = handle.read(guess)
        if len(tail) < 8 or tail[-4:] != _FOOTER_MAGIC:
            raise MetadataError(
                '{!r} is not a Parquet file (missing PAR1 trailer)'.format(
                    path))
        footer_len = int.from_bytes(tail[-8:-4], 'little')
        need = footer_len + 8
        if need > file_size:
            raise MetadataError(
                '{!r} declares a {}-byte footer larger than the {}-byte '
                'file — corrupt trailer'.format(path, footer_len, file_size))
        if need > len(tail):
            handle.seek(file_size - need)
            tail = handle.read(need)
        return tail[-need:]
    finally:
        handle.close()


class MetadataCache(object):
    """In-process LRU + disk-sidecar footer cache (module docstring).

    Thread-safe; one instance is shared by every rowgroup piece a worker
    process loads. Counters ``storage_footer_cache_hit`` / ``..._miss``
    count LRU-level lookups (a disk-sidecar fill counts as a miss — storage
    was spared, but a footer still had to be deserialized)."""

    def __init__(self, capacity: int = 256,
                 disk_dir: Optional[str] = None) -> None:
        self._capacity = max(int(capacity), 1)
        self._disk_dir = disk_dir
        self._lock = threading.Lock()
        self._entries: 'OrderedDict[Tuple[str, int, int], FooterEntry]' = \
            OrderedDict()

    # ------------------------------------------------------------- lookups

    def get(self, filesystem: Any, path: str,
            footer_read_bytes: int = DEFAULT_FOOTER_READ_BYTES
            ) -> FooterEntry:
        """The footer of ``path``, from (in order) the in-process LRU, the
        disk sidecar, or a ranged tail read — validated against the live
        ``(mtime, size)`` stat on every call."""
        key = _stat_key(filesystem, path)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                storage_metrics().inc('storage_footer_cache_hit')
                return entry
        storage_metrics().inc('storage_footer_cache_miss')
        footer = self._sidecar_load(key)
        if footer is None:
            footer = read_footer_bytes(filesystem, path, key[2],
                                       footer_read_bytes)
            self._sidecar_store(key, footer)
        metadata = pq.read_metadata(pa.BufferReader(footer))
        entry = FooterEntry(metadata=metadata, footer_bytes=footer,
                            file_size=key[2])
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self._capacity:
                self._entries.popitem(last=False)
        return entry

    # ------------------------------------------------------- disk sidecar

    def _sidecar_path(self, path: str) -> Optional[str]:
        if not self._disk_dir:
            return None
        digest = hashlib.sha1(path.encode('utf-8')).hexdigest()[:20]
        return os.path.join(self._disk_dir,
                            SIDECAR_BASENAME.format(digest=digest))

    def _sidecar_load(self, key: Tuple[str, int, int]) -> Optional[bytes]:
        """Footer bytes from the sidecar when its recorded ``(path, mtime,
        size)`` matches ``key``; None on absence, mismatch or corruption
        (a half-written or garbage sidecar is a miss, never an error)."""
        sidecar = self._sidecar_path(key[0])
        if sidecar is None:
            return None
        try:
            with open(sidecar, 'rb') as f:
                header_len = int.from_bytes(f.read(4), 'little')
                header = json.loads(f.read(header_len).decode('utf-8'))
                if (header.get('path') != key[0]
                        or int(header.get('mtime_ns', -1)) != key[1]
                        or int(header.get('size', -1)) != key[2]):
                    return None
                footer = f.read(int(header['footer_len']))
                if len(footer) != int(header['footer_len']):
                    return None
                return footer
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def _sidecar_store(self, key: Tuple[str, int, int],
                       footer: bytes) -> None:
        """Atomically persist ``footer`` (temp + ``os.replace``); a full
        disk or read-only sidecar directory degrades to in-process-only
        caching rather than failing the read."""
        sidecar = self._sidecar_path(key[0])
        if sidecar is None:
            return
        header = json.dumps({'path': key[0], 'mtime_ns': key[1],
                             'size': key[2],
                             'footer_len': len(footer)}).encode('utf-8')
        tmp = '{}.tmp.{}'.format(sidecar, os.getpid())
        try:
            with open(tmp, 'wb') as f:
                f.write(len(header).to_bytes(4, 'little'))
                f.write(header)
                f.write(footer)
            os.replace(tmp, sidecar)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
