"""Object-store-native ingest engine (docs/performance.md "Object-store
ingest engine").

The seed read path hands a whole Parquet fragment to
``fragment.to_table()`` — one serialized stream per rowgroup, a footer
round-trip per worker, no defense against object-store tail latency. This
package replaces it with **planned byte-range I/O**:

- :mod:`~petastorm_tpu.storage.range_planner` parses the footer once and
  emits exactly the column-chunk byte ranges the projected field set needs,
  coalescing near-adjacent ranges under a gap threshold into merged GETs;
- :mod:`~petastorm_tpu.storage.fetcher` executes the plan with a parallel
  bounded-window fetch pool and **request hedging** against tail latency
  (duplicate the slowest quantile after an adaptive deadline, first
  response wins);
- :mod:`~petastorm_tpu.storage.metadata_cache` amortizes footer reads
  across rowgroups, workers and runs (in-process LRU + atomic disk
  sidecar keyed by ``(path, mtime, size)``);
- :mod:`~petastorm_tpu.storage.engine` assembles the three into a
  :class:`~petastorm_tpu.storage.engine.RowGroupSource` the worker read
  path consumes in place of ``fragment.to_table()``.

Engagement is decided by :func:`resolve_storage_policy` from the
``make_reader(storage_policy=)`` kwarg: ``None`` auto-engages only for
non-local URL schemes (local/HDFS stay on the byte-identical seed path),
``False`` never engages, ``True`` / a :class:`StoragePolicy` always does.

Counters (``storage_footer_cache_hit`` / ``..._miss`` /
``storage_ranges_coalesced`` / ``storage_hedge_fired`` / ``..._won`` —
declared in ``telemetry/spans.py``) accumulate in a process-local registry
merged into ``Reader.telemetry_snapshot()``; like the breaker counters they
are reliable on in-process (thread/dummy) pools — process-pool workers keep
them worker-side. Stage timings (``range_fetch`` / ``range_hedge``) ride
the normal batch-sidecar transport and survive every pool shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Union
from urllib.parse import urlparse

from petastorm_tpu.telemetry.registry import MetricsRegistry

#: URL schemes served by the seed pyarrow-FS passthrough path — the engine
#: never auto-engages for these (explicit ``storage_policy=True`` still
#: wins, which is how the local-FS tests and bench drive it). Single-letter
#: "schemes" are Windows drive letters (fs_utils._scheme_of convention).
LOCAL_SCHEMES = ('', 'file', 'hdfs')


@dataclass(frozen=True)
class StoragePolicy:
    """Tuning surface of the ingest engine (defaults fit S3/GCS-class
    stores; the knob table lives in docs/performance.md).

    ``coalesce_gap_bytes``: merge column-chunk ranges separated by at most
    this many bytes into one GET (the wasted gap bytes are cheaper than a
    second round-trip). ``max_in_flight``: parallel range-GET window, also
    actuated live via the ``storage_fetch_window`` autotune knob
    (``PETASTORM_TPU_STORAGE_FETCH_WINDOW``). ``hedge_*``: duplicate a GET
    still in flight after ``max(hedge_min_s, quantile(completed) *
    hedge_factor)`` — first response wins, the loser's bytes are dropped.
    ``footer_read_bytes``: initial tail read when the footer size is
    unknown. ``cache_capacity`` / ``cache_dir``: in-process LRU entries and
    the optional disk-sidecar directory for the footer cache."""

    coalesce_gap_bytes: int = 64 * 1024
    max_in_flight: int = 8
    hedge_enabled: bool = True
    hedge_quantile: float = 0.9
    hedge_factor: float = 3.0
    hedge_min_s: float = 0.05
    footer_read_bytes: int = 64 * 1024
    cache_capacity: int = 256
    cache_dir: Optional[str] = None


def _scheme_of(url: str) -> str:
    scheme = urlparse(url).scheme
    # single-letter scheme = Windows drive letter, i.e. a local path
    return '' if len(scheme) <= 1 else scheme.lower()


def resolve_storage_policy(
        policy: Union[None, bool, StoragePolicy],
        dataset_url_or_urls: Any) -> Optional[StoragePolicy]:
    """Resolve the ``make_reader(storage_policy=)`` kwarg into the policy
    the workers run with, or None for the byte-identical seed path.

    ``None`` (the default) engages the engine only when the dataset URL
    scheme is non-local — pointing the same code at ``s3://`` flips the
    engine on, while every local/HDFS job stays on the seed path with zero
    resolution cost. ``False`` disables unconditionally; ``True`` resolves
    to the default :class:`StoragePolicy`; a policy instance passes
    through."""
    if policy is False:
        return None
    if isinstance(policy, StoragePolicy):
        return policy
    if policy is True:
        return StoragePolicy()
    if policy is not None:
        raise TypeError(
            'storage_policy must be None, a bool or a StoragePolicy; '
            'got {!r}'.format(policy))
    urls = (dataset_url_or_urls if isinstance(dataset_url_or_urls, list)
            else [dataset_url_or_urls])
    if not urls or not isinstance(urls[0], str):
        return None
    return StoragePolicy() if _scheme_of(urls[0]) not in LOCAL_SCHEMES \
        else None


#: process-local registry the storage counters accumulate in (module
#: docstring: merged into reader snapshots; in-process pools see it all)
_metrics = MetricsRegistry()


def storage_metrics() -> MetricsRegistry:
    """The process-local storage counter registry."""
    return _metrics


def storage_metrics_snapshot() -> Dict[str, Any]:
    """JSON-safe snapshot of the storage counters (registry format)."""
    return _metrics.snapshot()


def reset_storage_metrics() -> None:
    """Swap in a fresh registry (tests / bench isolation)."""
    global _metrics
    _metrics = MetricsRegistry()


__all__ = ['LOCAL_SCHEMES', 'StoragePolicy', 'resolve_storage_policy',
           'storage_metrics', 'storage_metrics_snapshot',
           'reset_storage_metrics']
