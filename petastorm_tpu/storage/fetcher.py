"""Parallel multi-range fetch pool with tail-latency request hedging.

Executes a :class:`~petastorm_tpu.storage.range_planner.RangePlan` as
concurrent ranged reads over a bounded in-flight window (the
``storage_fetch_window`` autotune knob / ``PETASTORM_TPU_STORAGE_FETCH_WINDOW``
env var actuate it live). Every read runs on its own per-thread file handle
(pyarrow ``NativeFile`` reads release the GIL but handles are not
thread-safe), so a hedged duplicate is a genuinely independent GET.

**Hedging**: a range still in flight after an adaptive deadline —
``max(hedge_min_s, quantile(completed durations) * hedge_factor)`` — gets a
duplicate read on a separate pool; the first response wins and is committed
exactly once, the loser is cancelled when still queued or its late bytes
dropped when already running (thread reads cannot be interrupted — the
semantic cancellation is the drop). Counters ``storage_hedge_fired`` /
``storage_hedge_won`` and the ``range_hedge`` stage span account every
duplicate, so doctor can flag a store whose hedges win too often.

Clock discipline: all duration arithmetic flows through the injected
``clock`` callable (tests drive hedging deterministically with the
fault-injection latency distribution plus scripted readers); the blocking
waits themselves use future timeouts, not wall-clock reads.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import FIRST_COMPLETED, Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from concurrent.futures import wait as wait_futures
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import time

from petastorm_tpu.errors import TransientIOError
from petastorm_tpu.storage import StoragePolicy, storage_metrics
from petastorm_tpu.storage.range_planner import ByteRange, RangePlan
from petastorm_tpu.telemetry.cost_model import percentile
from petastorm_tpu.telemetry.spans import record_stage

#: live override of the in-flight window (the autotune knob's actuator)
FETCH_WINDOW_ENV = 'PETASTORM_TPU_STORAGE_FETCH_WINDOW'

#: completed-duration samples kept for the adaptive hedge deadline
_MAX_SAMPLES = 512


@dataclass
class FetchResult:
    """One executed plan: fetched segments plus the accounting that rides
    the ``range_fetch`` trace args into the cost ledger."""

    segments: Dict[ByteRange, bytes] = field(default_factory=dict)
    bytes_fetched: int = 0
    ranges: int = 0
    hedges_fired: int = 0
    hedges_won: int = 0
    seconds: float = 0.0

    def trace_args(self) -> Dict[str, int]:
        """The JSON-safe args the ``range_fetch`` span carries (folded into
        ``CostLedger`` entries' ``fetch`` cell)."""
        return {'bytes': self.bytes_fetched, 'ranges': self.ranges,
                'hedges_fired': self.hedges_fired,
                'hedges_won': self.hedges_won}


def fetch_window(policy: StoragePolicy) -> int:
    """The effective in-flight window: the env override when set and valid
    (clamped to [1, 128]), else the policy's ``max_in_flight``."""
    raw = os.environ.get(FETCH_WINDOW_ENV)
    if raw:
        try:
            return min(max(int(raw), 1), 128)
        except ValueError:
            pass
    return max(int(policy.max_in_flight), 1)


class RangeFetcher(object):
    """Fetch pool for ONE file (module docstring). ``open_fn`` opens a new
    readable handle per calling thread — each concurrent leg gets its own
    connection, which is what makes a hedge an independent request."""

    def __init__(self, open_fn: Callable[[], Any], policy: StoragePolicy,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._open_fn = open_fn
        self._policy = policy
        self._clock = clock
        self._local = threading.local()
        self._lock = threading.Lock()
        self._samples: List[float] = []

    # ------------------------------------------------------------ plumbing

    def _handle(self) -> Any:
        handle = getattr(self._local, 'handle', None)
        if handle is None:
            handle = self._open_fn()
            self._local.handle = handle
        return handle

    def _timed_read(self, byte_range: ByteRange) -> Tuple[bytes, float]:
        started = self._clock()
        handle = self._handle()
        handle.seek(byte_range.start)
        data = handle.read(byte_range.length)
        if len(data) != byte_range.length:
            raise TransientIOError(
                'short read: wanted [{}, {}) got {} bytes'.format(
                    byte_range.start, byte_range.stop, len(data)))
        return bytes(data), self._clock() - started

    def _note_sample(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)
            if len(self._samples) > _MAX_SAMPLES:
                del self._samples[:len(self._samples) - _MAX_SAMPLES]

    def _deadline(self) -> Optional[float]:
        """Seconds a primary may run before its hedge fires; None when
        hedging is off. Adaptive: the ``hedge_quantile`` of completed reads
        times ``hedge_factor``, floored at ``hedge_min_s`` (the floor alone
        governs until samples accumulate)."""
        if not self._policy.hedge_enabled:
            return None
        with self._lock:
            samples = sorted(self._samples)
        adaptive = (percentile(samples, self._policy.hedge_quantile)
                    * self._policy.hedge_factor)
        return max(self._policy.hedge_min_s, adaptive)

    # --------------------------------------------------------------- fetch

    def fetch(self, plan: RangePlan) -> FetchResult:
        """Execute ``plan``: all ranges in parallel under the bounded
        window, hedging stragglers past the adaptive deadline. Read errors
        propagate (the worker's retry/breaker wrapping owns recovery); a
        hedged range fails only when BOTH legs fail."""
        result = FetchResult(ranges=len(plan.ranges))
        if not plan.ranges:
            return result
        started = self._clock()
        window = fetch_window(self._policy)
        pool = ThreadPoolExecutor(
            max_workers=window,
            thread_name_prefix='petastorm-tpu-range-fetch')
        # hedges run on their own pool: a window full of stragglers must
        # never queue the very duplicates meant to overtake them
        hedge_pool = ThreadPoolExecutor(
            max_workers=window,
            thread_name_prefix='petastorm-tpu-range-hedge')
        try:
            futures = [(byte_range, pool.submit(self._timed_read, byte_range))
                       for byte_range in plan.ranges]
            for byte_range, primary in futures:
                data = self._await_range(byte_range, primary, hedge_pool,
                                         result)
                result.segments[byte_range] = data
                result.bytes_fetched += len(data)
        finally:
            # losers may still be mid-read; never block the winner on them
            pool.shutdown(wait=False)
            hedge_pool.shutdown(wait=False)
        result.seconds = self._clock() - started
        return result

    def _await_range(self, byte_range: ByteRange,
                     primary: 'Future[Tuple[bytes, float]]',
                     hedge_pool: ThreadPoolExecutor,
                     result: FetchResult) -> bytes:
        """Wait for one range: primary up to the hedge deadline, then race
        primary vs duplicate — first successful leg commits, once."""
        deadline = self._deadline()
        try:
            data, seconds = primary.result(timeout=deadline)
            self._note_sample(seconds)
            return data
        except FutureTimeoutError:
            pass
        result.hedges_fired += 1
        storage_metrics().inc('storage_hedge_fired')
        hedge_started = self._clock()
        hedge = hedge_pool.submit(self._timed_read, byte_range)
        pending: Set['Future[Tuple[bytes, float]]'] = {primary, hedge}
        error: Optional[BaseException] = None
        winner: Optional['Future[Tuple[bytes, float]]'] = None
        data = b''
        while pending and winner is None:
            done, pending = wait_futures(pending,
                                         return_when=FIRST_COMPLETED)
            for future in done:
                try:
                    data, seconds = future.result()
                except (Exception, ) as exc:  # either leg may fail with any
                    # I/O error type; the race only surfaces it when the
                    # OTHER leg also fails (re-raised below) — a single-leg
                    # failure is exactly what hedging papers over
                    error = exc
                    continue
                winner = future
                self._note_sample(seconds)
                break
        record_stage('range_hedge', self._clock() - hedge_started)
        if winner is None:
            if error is None:
                raise TransientIOError('hedged fetch completed without a '
                                       'result or an error')
            raise error
        loser = primary if winner is hedge else hedge
        loser.cancel()  # no-op once running: late bytes are simply dropped
        if winner is hedge:
            result.hedges_won += 1
            storage_metrics().inc('storage_hedge_won')
        return data
