"""Shared dataset-identity and local-sidecar-state helpers.

Three subsystems keep small per-dataset state next to the data: the rowgroup
cache keys every entry by a *dataset token* (``WorkerSetup``), the cost
profiler persists its ledger sidecar in the dataset's *local state home*
(``telemetry/cost_model.py``), and the lineage audit plane keeps its batch
manifest there too (``telemetry/lineage.py``). Before this module each of
them re-derived the same two facts — "what is this read's identity?" and
"where does its local state live?" — independently; this is the ONE
definition all of them call (docs/observability.md "Cost profiler" /
"Sample lineage & determinism audit").

Derivations, not policy: callers still decide what to store and when — this
module only answers *token* and *path* questions, deterministically.
"""

from __future__ import annotations

import hashlib
import os
from typing import Any, Iterable, Optional, Sequence, Tuple

#: field-spec row: ``(name, numpy_dtype, shape, codec_config)`` — all
#: stringified by the caller so the token hash never depends on object repr
#: details of live codec instances
FieldSpec = Tuple[str, str, str, str]


def derive_dataset_token(dataset_path_or_paths: Any,
                         fields_to_read: Sequence[str],
                         decode: bool,
                         has_transform: bool,
                         field_specs: Iterable[FieldSpec],
                         device_decode_fields: Iterable[str] = ()) -> str:
    """The 16-hex-char identity of one (dataset, read configuration) pair.

    Covers the dataset location AND the read configuration: two readers with
    different column sets / decode modes / per-field codec interpretations
    (``field_overrides``) sharing one cache_location must never serve each
    other's entries, and a cost/lineage sidecar recorded under one
    configuration must never be consumed under another. Codec configs are
    part of the identity because cached values are the POST-decode output.

    ``device_decode_fields`` is appended only when non-empty, so every
    existing cache keyed by the historical 5-part token stays warm for
    readers that never use the device-decode knob.
    """
    token_parts = '{}|{}|{}|{}|{}'.format(dataset_path_or_paths,
                                          sorted(fields_to_read), decode,
                                          has_transform,
                                          sorted(field_specs))
    device_fields = sorted(device_decode_fields)
    if device_fields:
        token_parts += '|{}'.format(device_fields)
    return hashlib.md5(token_parts.encode('utf-8')).hexdigest()[:16]


def local_state_home(dataset_url_or_path: str,
                     cache_location: Optional[str] = None) -> Optional[str]:
    """The directory holding a dataset's local sidecar state: the disk-cache
    directory when one is configured (it already is the per-dataset local
    state home), else the dataset directory itself for a LOCAL store
    (``file://`` or a bare path); None for remote stores with no cache —
    the caller must then require an explicit path."""
    if cache_location:
        return cache_location
    path = dataset_url_or_path
    if path.startswith('file://'):
        path = path[len('file://'):]
    if '://' in path:
        return None
    return path


def sidecar_path(dataset_url_or_path: str, basename: str,
                 cache_location: Optional[str] = None) -> Optional[str]:
    """Where a named sidecar file lives for one dataset:
    ``local_state_home(...)/basename``, or None when the dataset has no
    local state home (remote store, no cache)."""
    home = local_state_home(dataset_url_or_path, cache_location)
    if home is None:
        return None
    return os.path.join(home, basename)


def cache_state_home(cache: Any) -> Optional[str]:
    """The per-dataset local-state directory a cache object provides:
    its ``state_home`` (the disk caches' root directory), or None for
    NullCache / non-disk caches. The one accessor readers use instead of
    poking cache internals."""
    home = getattr(cache, 'state_home', None)
    if home is None:
        return None
    return str(home)
