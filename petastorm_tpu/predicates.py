"""Row predicates pushed into workers (reference: petastorm/predicates.py:28-183).

A predicate names the fields it needs (``get_fields``) and decides inclusion
(``do_include``). Workers do a two-phase read: load only predicate fields, evaluate,
then load the remaining columns for surviving rows only. ``do_include`` receives a dict
of field values — scalars for the row reader, numpy arrays for the batch reader (where it
must return a boolean mask), same duality as the reference (petastorm/reader.py:259-261).
"""

import hashlib

import numpy as np


class PredicateBase(object):
    """Row-predicate interface (reference: petastorm/predicates.py): ``get_fields``
    names the columns needed, ``do_include`` decides per row."""

    def get_fields(self):
        raise NotImplementedError()

    def do_include(self, values):
        raise NotImplementedError()


class in_set(PredicateBase):
    """True when ``values[field]`` is in the given set (reference: predicates.py:45-61)."""

    def __init__(self, inclusion_values, predicate_field):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    @property
    def inclusion_values(self):
        """The inclusion set (read-only; decode_engine pushdown introspects it)."""
        return frozenset(self._inclusion_values)

    @property
    def predicate_field(self):
        """Name of the field this predicate reads."""
        return self._predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        value = values[self._predicate_field]
        if isinstance(value, np.ndarray) and value.ndim > 0:
            return np.isin(value, list(self._inclusion_values))
        return value in self._inclusion_values


class in_intersection(PredicateBase):
    """True when any element of a list-valued field intersects the given values
    (reference: predicates.py:64-80).

    Row mode gets one row's sequence and returns a scalar; batch mode
    (``make_batch_reader``) gets the whole column — an object array of per-row
    sequences, or a 2-D array when row lengths are uniform — and returns an ``(n,)``
    mask."""

    def __init__(self, inclusion_values, predicate_field):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        value = values[self._predicate_field]
        if isinstance(value, np.ndarray) and (value.ndim >= 2 or value.dtype == object):
            intersects = self._inclusion_values.intersection
            return np.fromiter((bool(intersects(np.ravel(row))) for row in value),
                               dtype=bool, count=len(value))
        return bool(self._inclusion_values.intersection(value))


class in_lambda(PredicateBase):
    """Arbitrary user function over the named fields, with optional shared state
    (reference: predicates.py:83-107)."""

    def __init__(self, predicate_fields, predicate_func, state_arg=None):
        if not isinstance(predicate_fields, (list, tuple, set)):
            raise ValueError('predicate_fields must be a collection of field names')
        self._predicate_fields = list(predicate_fields)
        self._predicate_func = predicate_func
        self._state_arg = state_arg

    def get_fields(self):
        return set(self._predicate_fields)

    def do_include(self, values):
        args = [values[f] for f in self._predicate_fields]
        if self._state_arg is not None:
            return self._predicate_func(*args, self._state_arg)
        return self._predicate_func(*args)


class in_negate(PredicateBase):
    """Logical NOT of another predicate (reference: predicates.py:110-122)."""

    def __init__(self, predicate):
        self._predicate = predicate

    @property
    def predicate(self):
        """The negated inner predicate (read-only; pushdown introspection)."""
        return self._predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        result = self._predicate.do_include(values)
        if isinstance(result, np.ndarray):
            return ~result
        return not result


class in_reduce(PredicateBase):
    """Reduce several predicates with ``any``/``all``-style function, e.g.
    ``in_reduce([p1, p2], all)`` (reference: predicates.py:125-142). For batch (mask)
    results, ``numpy.logical_and.reduce``/``logical_or.reduce`` are applied when the
    reduction function is ``all``/``any``."""

    def __init__(self, predicate_list, reduce_func):
        self._predicate_list = list(predicate_list)
        self._reduce_func = reduce_func

    @property
    def predicates(self):
        """The reduced child predicates (read-only; pushdown introspection)."""
        return tuple(self._predicate_list)

    @property
    def reduce_func(self):
        """The reduction function (``all``/``any`` are pushdown-compilable)."""
        return self._reduce_func

    def get_fields(self):
        fields = set()
        for predicate in self._predicate_list:
            fields |= predicate.get_fields()
        return fields

    def do_include(self, values):
        results = [p.do_include(values) for p in self._predicate_list]
        if any(isinstance(r, np.ndarray) for r in results):
            results = [np.asarray(r) for r in results]
            if self._reduce_func is all:
                return np.logical_and.reduce(results)
            if self._reduce_func is any:
                return np.logical_or.reduce(results)
        return self._reduce_func(results)


class in_pseudorandom_split(PredicateBase):
    """Deterministic hash-bucket split of a dataset on a key field: ``fraction_list``
    partitions [0,1); rows land in a bucket by md5 of the key; the predicate keeps rows in
    bucket ``subset_index`` (reference: predicates.py:145-183). Stable across runs and
    machines — suitable for train/val/test splits."""

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError('subset_index out of range')
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError('fractions must sum to <= 1.0')
        self._boundaries = np.cumsum([0.0] + list(fraction_list))
        self._subset_index = subset_index
        self._predicate_field = predicate_field

    @property
    def predicate_field(self):
        """Name of the hash-bucketed key field (pushdown introspection)."""
        return self._predicate_field

    def get_fields(self):
        return {self._predicate_field}

    @staticmethod
    def _bucket_position(value):
        digest = hashlib.md5(str(value).encode('utf-8')).hexdigest()
        return int(digest[:8], 16) / float(0xFFFFFFFF + 1)

    def do_include(self, values):
        value = values[self._predicate_field]
        lo = self._boundaries[self._subset_index]
        hi = self._boundaries[self._subset_index + 1]
        if isinstance(value, np.ndarray) and value.ndim > 0:
            positions = np.array([self._bucket_position(v) for v in value])
            return (positions >= lo) & (positions < hi)
        position = self._bucket_position(value)
        return lo <= position < hi
