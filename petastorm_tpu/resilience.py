"""Resilience primitives: retry/backoff, transient-error classification, the
quarantine ledger, and circuit breakers.

The reference (SURVEY §5.3) only *detects* failures — a worker exception aborts the
epoch. Production input pipelines treat transient faults as routine (tf.data service
restarts workers and re-dispatches their splits, arXiv 2210.14826); this module supplies
the policy objects the rest of the stack threads through:

- :class:`RetryPolicy` — bounded attempts, exponential backoff with **deterministic
  seeded jitter**, per-attempt and total deadline budgets. Applied around filesystem
  resolution (:mod:`petastorm_tpu.fs_utils`) and rowgroup loads
  (:mod:`petastorm_tpu.reader_worker`).
- :func:`run_with_retry` — the retry loop itself, classifier-driven so only transient
  failures burn attempts.
- :class:`QuarantineRecord` / :class:`QuarantineLedger` — the skip-with-quarantine
  bookkeeping for ``make_reader(..., on_error='skip')``: every skipped rowgroup is
  recorded (piece, path, exception, attempts, reason — ``'error'`` or ``'hang'``)
  and surfaced through ``Reader.diagnostics``, ``LoaderStats``, and the doctor —
  degradation is always visible, never silent.
- :class:`CircuitBreaker` / :class:`BreakerBoard` — closed/open/half-open breakers
  (injectable clock, so every transition is deterministic in tests) that wrap the
  components retry alone cannot protect: a persistently failing dependency should be
  *routed around* for a cooldown, not hammered. Deployed in front of the shm result
  transport (repeated checksum failures → temporary ZMQ-wire fallback), the disk
  cache (repeated corruption/IO errors → bypass to direct reads) and filesystem
  opens (per-path-prefix, composing with :class:`RetryPolicy` via
  :func:`call_with_breaker`). States surface in ``Reader.diagnostics['breakers']``
  and the doctor report (docs/robustness.md "Hang detection & circuit breakers").

This is the repo's first strict-typed module (mypy.ini ``[mypy-petastorm_tpu.resilience]``).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from petastorm_tpu.errors import TransientIOError

#: on_error modes accepted by make_reader / make_batch_reader
ON_ERROR_MODES: Tuple[str, ...] = ('raise', 'retry', 'skip')

#: declared ``QuarantineRecord.reason`` values — the registry every
#: construction site must draw from (pipecheck protocol-conformance,
#: docs/static-analysis.md): ledger consumers (doctor, dashboards) dispatch
#: on these strings, so an undeclared reason is a silent new failure class
QUARANTINE_REASONS: Tuple[str, ...] = ('error', 'hang')


def check_on_error(on_error: str) -> str:
    """Validate an ``on_error`` mode (shared by both reader factories)."""
    if on_error not in ON_ERROR_MODES:
        raise ValueError('on_error must be one of {}, got {!r}'
                         .format(ON_ERROR_MODES, on_error))
    return on_error


def resolve_retry_policy(on_error: str,
                         retry_policy: Optional['RetryPolicy']) -> Optional['RetryPolicy']:
    """The ONE normalization of the ``(on_error, retry_policy)`` pair, used by every
    layer (reader factories, Reader, WorkerSetup): ``'raise'`` means no retry anywhere
    (today's exact behavior — an explicitly passed policy is ignored), other modes get
    the given policy or the default. Also validates ``on_error``."""
    check_on_error(on_error)
    if on_error == 'raise':
        return None
    return retry_policy if retry_policy is not None else RetryPolicy()


def is_transient_error(exc: BaseException) -> bool:
    """Default transient classifier: OS-level IO failures (connection resets, timeouts,
    throttling surfaced as errno failures — pyarrow raises its ``ArrowIOError`` as an
    ``OSError`` subclass) plus explicit :class:`TransientIOError`. Data corruption
    (``ArrowInvalid``/``ValueError``), schema and decode bugs are permanent: retrying a
    truncated footer re-reads the same bytes."""
    if isinstance(exc, TransientIOError):
        return True
    if isinstance(exc, (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                        PermissionError)):
        # Deterministic filesystem answers — retrying cannot change them.
        return False
    return isinstance(exc, (OSError, TimeoutError))


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff and deterministic seeded jitter.

    :param max_attempts: total attempts including the first (1 = no retry).
    :param backoff_base_s: sleep before the first retry.
    :param backoff_multiplier: growth factor per subsequent retry.
    :param max_backoff_s: backoff ceiling.
    :param jitter_fraction: each sleep is scaled by a factor drawn uniformly from
        ``[1 - jitter_fraction, 1 + jitter_fraction]``. The draw is a pure function of
        ``(seed, key, attempt)`` — two runs with the same seed sleep identically, so
        fault-injection tests and distributed workers are reproducible.
    :param seed: jitter seed; None keeps jitter deterministic with seed 0.
    :param per_attempt_deadline_s: if a *failed* attempt ran longer than this, the
        budget is considered consumed and no further retry is made (Python cannot
        preempt a blocked C call, so this bounds retries-after-slow-failures rather
        than the attempt itself).
    :param total_deadline_s: wall-clock budget across all attempts and backoffs;
        exhausting it stops retrying even if attempts remain.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 2.0
    jitter_fraction: float = 0.1
    seed: Optional[int] = None
    per_attempt_deadline_s: Optional[float] = None
    total_deadline_s: Optional[float] = None

    def __post_init__(self) -> None:
        if self.max_attempts < 1:
            raise ValueError('max_attempts must be >= 1, got {}'.format(self.max_attempts))
        if self.backoff_base_s < 0 or self.max_backoff_s < 0:
            raise ValueError('backoff durations must be non-negative')
        if not 0 <= self.jitter_fraction <= 1:
            raise ValueError('jitter_fraction must be in [0, 1], got {}'
                             .format(self.jitter_fraction))

    def backoff_s(self, attempt: int, key: int = 0) -> float:
        """Deterministic sleep before retry number ``attempt`` (1-based): exponential
        base schedule scaled by the seeded jitter draw for ``(seed, key, attempt)``."""
        if attempt < 1:
            raise ValueError('attempt is 1-based, got {}'.format(attempt))
        base = min(self.max_backoff_s,
                   self.backoff_base_s * self.backoff_multiplier ** (attempt - 1))
        if not self.jitter_fraction:
            return base
        # hash of an int tuple is deterministic across processes (PYTHONHASHSEED only
        # salts str/bytes), so workers with the same (seed, key, attempt) draw the
        # same jitter.
        draw = random.Random(hash((self.seed or 0, key, attempt))).uniform(
            1.0 - self.jitter_fraction, 1.0 + self.jitter_fraction)
        return base * draw


#: retry-notification callback: (attempt_number, exception, sleep_seconds)
OnRetry = Callable[[int, BaseException, float], None]


def run_with_retry(fn: Callable[[], Any],
                   policy: RetryPolicy,
                   key: int = 0,
                   is_transient: Callable[[BaseException], bool] = is_transient_error,
                   sleep: Callable[[float], None] = time.sleep,
                   clock: Callable[[], float] = time.monotonic,
                   on_retry: Optional[OnRetry] = None) -> Tuple[Any, int]:
    """Call ``fn`` under ``policy``; returns ``(result, retries_used)``.

    Only exceptions classified transient by ``is_transient`` are retried; anything else
    re-raises immediately (attempt 1 semantics). When the attempt/deadline budget is
    exhausted the LAST exception re-raises unchanged — callers decide whether that means
    abort (``on_error='retry'``) or quarantine (``on_error='skip'``).

    ``key`` decorrelates the jitter streams of concurrent workers retrying different
    rowgroups under the same seed (pass e.g. the piece index)."""
    start = clock()
    attempt = 0
    while True:
        attempt += 1
        attempt_start = clock()
        try:
            return fn(), attempt - 1
        except BaseException as exc:  # noqa: BLE001 - the retry loop must see every exception; is_transient decides, non-transient re-raises below
            attempt_elapsed = clock() - attempt_start
            if not is_transient(exc):
                raise
            if attempt >= policy.max_attempts:
                raise
            if (policy.per_attempt_deadline_s is not None
                    and attempt_elapsed > policy.per_attempt_deadline_s):
                raise
            delay = policy.backoff_s(attempt, key=key)
            if (policy.total_deadline_s is not None
                    and clock() - start + delay > policy.total_deadline_s):
                raise
            if on_retry is not None:
                on_retry(attempt, exc, delay)
            if delay > 0:
                sleep(delay)


@dataclass(frozen=True)
class QuarantineRecord:
    """One skipped rowgroup: where it was, what killed it, how hard we tried.

    ``reason`` distinguishes *how* the rowgroup left the stream: ``'error'`` (an
    exception exhausted the retry budget — the PR-1 path) or ``'hang'`` (the worker
    holding it blew ``item_deadline_s`` and was reaped by the watchdog;
    docs/robustness.md "Hang detection & circuit breakers")."""

    piece_index: int
    fragment_path: str
    row_group_id: Optional[int]
    error_type: str
    error: str
    attempts: int
    epoch: int = 0
    reason: str = 'error'

    @classmethod
    def from_exception(cls, exc: BaseException, piece_index: int, fragment_path: str,
                       row_group_id: Optional[int], attempts: int,
                       epoch: int = 0) -> 'QuarantineRecord':
        return cls(piece_index=piece_index, fragment_path=fragment_path,
                   row_group_id=row_group_id, error_type=type(exc).__name__,
                   error=str(exc)[:500], attempts=attempts, epoch=epoch)

    def as_dict(self) -> Dict[str, Any]:
        return {'piece_index': self.piece_index, 'fragment_path': self.fragment_path,
                'row_group_id': self.row_group_id, 'error_type': self.error_type,
                'error': self.error, 'attempts': self.attempts, 'epoch': self.epoch,
                'reason': self.reason}


class QuarantineLedger:
    """Thread-safe collection of :class:`QuarantineRecord`; the reader appends as
    quarantined pieces surface on the results channel, observability consumers
    (``Reader.diagnostics``, ``LoaderStats``, doctor) read it at any time."""

    def __init__(self) -> None:
        self._records: List[QuarantineRecord] = []
        self._lock = threading.Lock()

    def add(self, record: QuarantineRecord) -> None:
        with self._lock:
            self._records.append(record)

    def records(self) -> List[QuarantineRecord]:
        with self._lock:
            return list(self._records)

    def as_dicts(self) -> List[Dict[str, Any]]:
        return [record.as_dict() for record in self.records()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def __bool__(self) -> bool:
        return len(self) > 0

    def raise_if_any(self) -> None:
        """Strict post-epoch validation: convert a non-empty ledger into a
        :class:`~petastorm_tpu.errors.QuarantinedRowGroupError` naming the first
        skipped rowgroup (and how many more there are). For jobs that tolerate
        degradation mid-epoch but must not silently train on a partial dataset."""
        from petastorm_tpu.errors import QuarantinedRowGroupError
        records = self.records()
        if not records:
            return
        first = records[0]
        raise QuarantinedRowGroupError(
            '{} rowgroup(s) were quarantined this run; first: piece {} of {!r} '
            '(rowgroup {}) failed after {} attempt(s) with {}: {}'.format(
                len(records), first.piece_index, first.fragment_path,
                first.row_group_id, first.attempts, first.error_type, first.error),
            piece_index=first.piece_index, fragment_path=first.fragment_path,
            row_group_id=first.row_group_id, attempts=first.attempts)


# ---------------------------------------------------------------------------
# Circuit breakers (docs/robustness.md "Hang detection & circuit breakers")
# ---------------------------------------------------------------------------

#: breaker state names (the classic three-state machine)
BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 'closed', 'open', 'half_open'

#: ledger-replay breaker (service/ledger.py, docs/service.md "Dispatcher
#: crash with a ledger"): consecutive corrupt journal replays before a
#: restarting dispatcher DISCARDS the journal instead of replaying it —
#: a journal that corrupts every replay must degrade the fleet to
#: replay-from-clients, not wedge every restart on the same bad frames
LEDGER_REPLAY_BREAKER_THRESHOLD = 2
LEDGER_REPLAY_BREAKER_RECOVERY_S = 60.0

#: transition-notification callback: (breaker_name, old_state, new_state)
OnBreakerTransition = Callable[[str, str, str], None]


class CircuitBreaker:
    """Closed/open/half-open circuit breaker with an injectable clock.

    Retry answers "this call failed, try again"; the breaker answers "this
    *dependency* keeps failing, stop calling it for a while". State machine:

    - **closed** (healthy): calls flow; ``failure_threshold`` *consecutive*
      failures trip it open (any success resets the streak).
    - **open**: :meth:`allow` returns False — callers fail fast / take their
      fallback path without touching the broken dependency — until
      ``recovery_timeout_s`` of ``clock`` time has passed, after which the next
      :meth:`allow` moves to half-open.
    - **half-open**: calls flow again as probes; the first success closes the
      breaker, the first failure re-opens it (restarting the cooldown).

    ``clock`` is injectable (default ``time.monotonic``) so every transition is
    deterministic in tests; ``on_transition`` feeds telemetry counters
    (``breaker_open``). Thread-safe; pickles by dropping the lock (each process
    gets an independent breaker — states cross process boundaries via the
    results-channel sidecar, not via shared memory)."""

    def __init__(self, name: str, failure_threshold: int = 5,
                 recovery_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[OnBreakerTransition] = None) -> None:
        if failure_threshold < 1:
            raise ValueError('failure_threshold must be >= 1, got {}'
                             .format(failure_threshold))
        if recovery_timeout_s < 0:
            raise ValueError('recovery_timeout_s must be >= 0')
        self.name = name
        self.failure_threshold = failure_threshold
        self.recovery_timeout_s = recovery_timeout_s
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._failures = 0
        self._successes = 0
        self._opened_count = 0
        # transitions queued under the lock, observers notified AFTER release
        # (an observer reading breaker state back — e.g. the incident
        # recorder snapshotting the board — must not deadlock)
        self._pending_notifications: List[Tuple[OnBreakerTransition, str,
                                                str, str]] = []

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        del state['_lock']
        state['_on_transition'] = None  # callbacks are process-local wiring
        state['_pending_notifications'] = []
        return state

    def __setstate__(self, state: Dict[str, Any]) -> None:
        self.__dict__.update(state)
        self._lock = threading.Lock()

    def observe_transitions(self, callback: OnBreakerTransition) -> None:
        """Attach an additional transition observer, chaining after any callback
        already installed — the supported way for a component adopting an
        injected breaker (e.g. a pool feeding its telemetry counters) to watch
        it without clobbering the owner's wiring. Observers are process-local
        (dropped on pickle, like ``on_transition``)."""
        with self._lock:
            existing = self._on_transition
            if existing is None:
                self._on_transition = callback
                return

            def chained(name: str, old_state: str, new_state: str,
                        _first: OnBreakerTransition = existing,
                        _second: OnBreakerTransition = callback) -> None:
                _first(name, old_state, new_state)
                _second(name, old_state, new_state)
            self._on_transition = chained

    def _transition(self, new_state: str) -> None:
        # caller holds self._lock
        old_state = self._state
        if old_state == new_state:
            return
        self._state = new_state
        if new_state == BREAKER_OPEN:
            self._opened_at = self._clock()
            self._opened_count += 1
        # Flight-recorder hook (docs/observability.md "Flight recorder"):
        # every breaker transition in every process is an anomaly instant on
        # the traced timeline (worker-side ones ride the trace batch sidecar).
        # Local import: tracing is an observability layer above this module.
        from petastorm_tpu.telemetry.tracing import trace_enabled, trace_instant
        if trace_enabled():
            trace_instant('breaker_transition',
                          args={'breaker': self.name, 'from_state': old_state,
                                'to_state': new_state})
        callback = self._on_transition
        if callback is not None:
            # queued, not called: the caller still holds self._lock, and an
            # observer is allowed to read breaker state back (the incident
            # recorder snapshots the whole board mid-capture)
            self._pending_notifications.append(
                (callback, self.name, old_state, new_state))

    def _notify(self) -> None:
        # call OUTSIDE self._lock: drain the transition notifications queued
        # by _transition and deliver them to the observer chain
        while True:
            with self._lock:
                if not self._pending_notifications:
                    return
                callback, name, old_state, new_state = \
                    self._pending_notifications.pop(0)
            callback(name, old_state, new_state)

    def allow(self) -> bool:
        """True when a call may proceed. In the open state this is where the
        cooldown expires: once ``recovery_timeout_s`` has elapsed the breaker
        moves to half-open and the call proceeds as a probe."""
        with self._lock:
            if self._state == BREAKER_OPEN:
                if self._clock() - self._opened_at >= self.recovery_timeout_s:
                    self._transition(BREAKER_HALF_OPEN)
                    result = True
                else:
                    result = False
            else:
                result = True
        self._notify()
        return result

    def record_success(self) -> None:
        """A guarded call succeeded: reset the failure streak; a half-open probe
        success closes the breaker."""
        with self._lock:
            self._successes += 1
            self._consecutive_failures = 0
            if self._state == BREAKER_HALF_OPEN:
                self._transition(BREAKER_CLOSED)
        self._notify()

    def record_failure(self) -> None:
        """A guarded call failed: trip open after ``failure_threshold``
        consecutive failures (immediately, when half-open)."""
        with self._lock:
            self._failures += 1
            self._consecutive_failures += 1
            if self._state == BREAKER_HALF_OPEN:
                self._transition(BREAKER_OPEN)
            elif (self._state == BREAKER_CLOSED
                    and self._consecutive_failures >= self.failure_threshold):
                self._transition(BREAKER_OPEN)
        self._notify()

    @property
    def state(self) -> str:
        """Current state name; reading it applies the open→half-open cooldown
        transition (state is a function of the clock, not only of events)."""
        with self._lock:
            if (self._state == BREAKER_OPEN
                    and self._clock() - self._opened_at >= self.recovery_timeout_s):
                self._transition(BREAKER_HALF_OPEN)
            result = self._state
        self._notify()
        return result

    @property
    def tripped(self) -> bool:
        """True when this breaker has ever recorded a failure or opened — the
        'interesting enough to report' criterion used by snapshots."""
        with self._lock:
            return (self._failures > 0 or self._opened_count > 0
                    or self._state != BREAKER_CLOSED)

    def reset(self) -> None:
        """Force back to a pristine closed state (tests, manual recovery)."""
        with self._lock:
            self._transition(BREAKER_CLOSED)
            self._consecutive_failures = 0
            self._failures = 0
            self._successes = 0
            self._opened_count = 0
        self._notify()

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe state for diagnostics / the doctor report."""
        state = self.state  # applies the cooldown transition first
        with self._lock:
            return {'state': state, 'failures': self._failures,
                    'successes': self._successes,
                    'consecutive_failures': self._consecutive_failures,
                    'opened_count': self._opened_count,
                    'failure_threshold': self.failure_threshold,
                    'recovery_timeout_s': self.recovery_timeout_s}


class BreakerBoard:
    """Named registry of :class:`CircuitBreaker` instances (one per guarded
    dependency: ``'fs:<path-prefix>'``, ``'cache:<location>'``, ...). Process
    local: worker processes each hold their own board, and its snapshot rides
    the results-channel ``breakers`` sidecar into ``Reader.diagnostics`` the
    same way stage-span telemetry does."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._observers: List[OnBreakerTransition] = []

    def breaker(self, name: str, failure_threshold: int = 5,
                recovery_timeout_s: float = 30.0,
                clock: Callable[[], float] = time.monotonic,
                on_transition: Optional[OnBreakerTransition] = None) -> CircuitBreaker:
        """Get or create the breaker ``name`` (settings apply on creation)."""
        existing = self._breakers.get(name)
        if existing is not None:
            return existing
        with self._lock:
            created = name not in self._breakers
            brk = self._breakers.setdefault(
                name, CircuitBreaker(name, failure_threshold=failure_threshold,
                                     recovery_timeout_s=recovery_timeout_s,
                                     clock=clock, on_transition=on_transition))
            observers = list(self._observers) if created else []
        for callback in observers:
            brk.observe_transitions(callback)
        return brk

    def observe_transitions(self, callback: OnBreakerTransition) -> None:
        """Watch every transition on the board: chains ``callback`` onto each
        breaker already registered AND onto every breaker created later — the
        board-level trigger hook the incident recorder subscribes to
        (telemetry/incident.py; docs/observability.md "Incident autopsy
        plane"). Observers are process-local, like per-breaker ones."""
        with self._lock:
            self._observers.append(callback)
            breakers = list(self._breakers.values())
        for brk in breakers:
            brk.observe_transitions(callback)

    def snapshot(self, only_tripped: bool = False) -> Dict[str, Dict[str, Any]]:
        """``{name: breaker.as_dict()}``; ``only_tripped`` keeps the wire
        sidecar small by omitting never-failed closed breakers."""
        with self._lock:
            breakers = dict(self._breakers)
        return {name: brk.as_dict() for name, brk in breakers.items()
                if not only_tripped or brk.tripped}

    def reset(self) -> None:
        """Drop every registered breaker (test isolation)."""
        with self._lock:
            self._breakers.clear()


#: the process-wide board every in-process breaker registers on
_default_board = BreakerBoard()


def default_board() -> BreakerBoard:
    """The process-wide :class:`BreakerBoard` (cache + filesystem breakers live
    here; the process pool's shm breaker is pool-owned and consumer-side)."""
    return _default_board


def call_with_breaker(
        fn: Callable[[], Any], breaker: CircuitBreaker,
        is_failure: Callable[[BaseException], bool] = is_transient_error) -> Any:
    """Run ``fn`` under ``breaker``: an open breaker fails fast with
    :class:`~petastorm_tpu.errors.TransientIOError` (classified transient, so a
    wrapping :func:`run_with_retry` burns its remaining budget on cheap fast
    failures instead of hammering a stalled dependency); outcomes feed the
    breaker (only ``is_failure`` exceptions count — a ``KeyError`` in user code
    must not trip an IO breaker)."""
    if not breaker.allow():
        raise TransientIOError(
            'circuit breaker {!r} is open (cooling down for {:.3g}s after {} '
            'consecutive failure(s)); failing fast instead of re-touching the '
            'broken dependency'.format(breaker.name, breaker.recovery_timeout_s,
                                       breaker.failure_threshold))
    try:
        result = fn()
    except BaseException as exc:
        if is_failure(exc):
            breaker.record_failure()
        raise
    breaker.record_success()
    return result
