"""petastorm_tpu: a TPU-native data access framework for ML training from Apache Parquet.

A ground-up JAX/XLA-first re-design with the capabilities of petastorm (reference:
/root/reference, v0.13.0): multi-framework schema with tensor/image codecs, dataset
materialization with embedded metadata, a parallel rowgroup reader with sharding /
shuffling / predicates / NGram sequence windowing / caching / weighted mixing, and
framework adapters. The primary consumer is a mesh-sharded JAX input pipeline
(``petastorm_tpu.parallel``) that assembles globally-sharded ``jax.Array`` batches with
double-buffered host->device transfer; PyTorch and TF adapters are thin wrappers for
capability parity (reference: petastorm/pytorch.py, petastorm/tf_utils.py).
"""

__version__ = '0.4.0'

from petastorm_tpu.errors import NoDataAvailableError  # noqa: F401
from petastorm_tpu.reader import Reader, make_batch_reader, make_reader  # noqa: F401
from petastorm_tpu.transform import TransformSpec  # noqa: F401
from petastorm_tpu.unischema import Unischema, UnischemaField  # noqa: F401
