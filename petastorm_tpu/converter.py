"""Dataset converter: in-memory/cluster DataFrame -> cached Parquet store -> framework
loaders (reference: petastorm/spark/spark_dataset_converter.py:156-728).

The reference is Spark-only; this converter accepts **pandas DataFrames, pyarrow Tables,
or pyspark DataFrames** (pyspark gated on availability) and adds a JAX loader as the
primary consumer next to the reference's TF/torch ones. Parity behaviors kept:
content-dedup cache under a parent cache dir, atexit + explicit ``delete()`` cleanup,
eventual-consistency file wait, small-median-file-size warning, and
data-parallel-shard sanity checks (jax.distributed replaces Horovod env sniffing).
"""

import atexit
import hashlib
import logging
import os
import time
import uuid
import warnings

logger = logging.getLogger(__name__)

#: env var naming the parent cache directory (the analog of the reference's Spark conf
#: key 'petastorm.spark.converter.parentCacheDirUrl', spark_dataset_converter.py:164)
CACHE_DIR_ENV = 'PETASTORM_TPU_CONVERTER_CACHE_DIR'

_MIN_RECOMMENDED_FILE_BYTES = 50 << 20  # reference: 50 MB warning threshold (:636-650)

_active_converters = {}


def _cleanup_all():
    for converter in list(_active_converters.values()):
        converter.delete(silent=True)


atexit.register(_cleanup_all)


def _to_arrow_table(df):
    import pyarrow as pa
    if isinstance(df, pa.Table):
        return df
    try:
        import pandas as pd
        if isinstance(df, pd.DataFrame):
            return pa.Table.from_pandas(df, preserve_index=False)
    except ImportError:
        pass
    raise TypeError('Unsupported dataframe type {!r}: pass a pyarrow.Table, a pandas '
                    'DataFrame, or a pyspark DataFrame'.format(type(df)))


def _table_fingerprint(table):
    """Content-identity hash for dedup (the analog of the reference's Spark-plan
    sameResult dedup, spark_dataset_converter.py:405-522): schema + row count + per-column
    buffer digests."""
    h = hashlib.sha1()
    h.update(str(table.schema).encode('utf-8'))
    h.update(str(table.num_rows).encode('utf-8'))
    for column in table.columns:
        for chunk in column.chunks:
            for buf in chunk.buffers():
                if buf is not None:
                    h.update(memoryview(buf))
                    h.update(str(buf.size).encode())
    return h.hexdigest()[:24]


def _is_spark_dataframe(df):
    try:
        from pyspark.sql import DataFrame
        return isinstance(df, DataFrame)
    except ImportError:
        return False


class DatasetConverter(object):
    """A materialized dataset with loader factories (reference: SparkDatasetConverter,
    spark_dataset_converter.py:156-286)."""

    def __init__(self, cache_dir_url, file_urls, dataset_size):
        self.cache_dir_url = cache_dir_url
        self.file_urls = file_urls
        self.dataset_size = dataset_size

    def __len__(self):
        return self.dataset_size

    # ------------------------------------------------------------ loaders

    def make_jax_loader(self, batch_size, mesh=None, partition_spec=None,
                        loader_kwargs=None, **reader_kwargs):
        """Primary TPU path: mesh-sharded JaxDataLoader over the materialized store."""
        from petastorm_tpu.parallel.loader import JaxDataLoader
        from petastorm_tpu.reader import make_batch_reader
        self._check_shard_args(reader_kwargs)
        reader = make_batch_reader(self.file_urls, **reader_kwargs)
        return JaxDataLoader(reader, batch_size, mesh=mesh,
                             partition_spec=partition_spec, **(loader_kwargs or {}))

    def make_tf_dataset(self, batch_size=32, shuffle_row_count=None, prefetch=None,
                        **reader_kwargs):
        """tf.data pipeline: unbatch -> shuffle -> batch -> prefetch(AUTOTUNE)
        (reference: spark_dataset_converter.py:289-350)."""
        return _TfDatasetContextManager(self, batch_size, shuffle_row_count, prefetch,
                                        reader_kwargs)

    def make_torch_dataloader(self, batch_size=32, shuffling_queue_capacity=0,
                              **reader_kwargs):
        """BatchedDataLoader over the store (reference: :353-398)."""
        return _TorchLoaderContextManager(self, batch_size, shuffling_queue_capacity,
                                          reader_kwargs)

    def _check_shard_args(self, reader_kwargs):
        """Warn when the declared shard layout disagrees with the JAX runtime
        (reference Horovod check: spark_dataset_converter.py:116-153)."""
        from petastorm_tpu.parallel.mesh import distributed_shard_info
        cur_shard = reader_kwargs.get('cur_shard')
        shard_count = reader_kwargs.get('shard_count')
        detected_shard, detected_count = distributed_shard_info()
        if detected_count is not None:
            if shard_count is None:
                reader_kwargs['cur_shard'] = detected_shard
                reader_kwargs['shard_count'] = detected_count
            elif (cur_shard, shard_count) != (detected_shard, detected_count):
                warnings.warn('cur_shard/shard_count ({}, {}) disagree with the '
                              'distributed runtime ({}, {})'
                              .format(cur_shard, shard_count, detected_shard,
                                      detected_count))
        return reader_kwargs

    # ------------------------------------------------------------ lifecycle

    def delete(self, silent=False):
        """Remove the materialized store (reference: :284-286,583-599)."""
        try:
            from petastorm_tpu.fs_utils import delete_path, get_filesystem_and_path_or_paths
            fs, path = get_filesystem_and_path_or_paths(self.cache_dir_url)
            delete_path(fs, path)
        except Exception:
            if not silent:
                raise
            # silent=True tolerates any deletion failure, but never silently:
            # an undeletable store is a disk-quota leak worth a log line
            logger.warning('Failed to delete converter store %s (silent=True); '
                           'the materialized files may linger',
                           self.cache_dir_url, exc_info=True)
        _active_converters.pop(self.cache_dir_url, None)
        # A deleted store must not be served to a later same-plan make_converter.
        for key, conv in list(_spark_plan_converters.items()):
            if conv is self:
                del _spark_plan_converters[key]


class _TfDatasetContextManager(object):
    def __init__(self, converter, batch_size, shuffle_row_count, prefetch, reader_kwargs):
        self._converter = converter
        self._batch_size = batch_size
        self._shuffle = shuffle_row_count
        self._prefetch = prefetch
        self._reader_kwargs = reader_kwargs

    def __enter__(self):
        import tensorflow as tf
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset
        self._converter._check_shard_args(self._reader_kwargs)
        _wait_file_available(self._converter.file_urls)
        self._reader = make_batch_reader(self._converter.file_urls,
                                         **self._reader_kwargs)
        dataset = make_petastorm_dataset(self._reader)
        dataset = dataset.unbatch()
        if self._shuffle:
            dataset = dataset.shuffle(self._shuffle)
        dataset = dataset.batch(self._batch_size)
        dataset = dataset.prefetch(self._prefetch if self._prefetch is not None
                                   else tf.data.AUTOTUNE)
        return dataset

    def __exit__(self, exc_type, exc_val, exc_tb):
        self._reader.stop()
        self._reader.join()


class _TorchLoaderContextManager(object):
    def __init__(self, converter, batch_size, shuffling_queue_capacity, reader_kwargs):
        self._converter = converter
        self._batch_size = batch_size
        self._capacity = shuffling_queue_capacity
        self._reader_kwargs = reader_kwargs

    def __enter__(self):
        from petastorm_tpu.pytorch import BatchedDataLoader
        from petastorm_tpu.reader import make_batch_reader
        self._converter._check_shard_args(self._reader_kwargs)
        _wait_file_available(self._converter.file_urls)
        self._reader = make_batch_reader(self._converter.file_urls,
                                         **self._reader_kwargs)
        return BatchedDataLoader(self._reader, batch_size=self._batch_size,
                                 shuffling_queue_capacity=self._capacity)

    def __exit__(self, exc_type, exc_val, exc_tb):
        self._reader.stop()
        self._reader.join()


def _wait_file_available(urls, timeout_s=30):
    """Eventual-consistency wait (reference: spark_dataset_converter.py:602-631)."""
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths, path_exists
    fs, paths = get_filesystem_and_path_or_paths(list(urls))
    deadline = time.time() + timeout_s
    missing = list(paths)
    while missing:
        missing = [p for p in missing if not path_exists(fs, p)]
        if not missing:
            return
        if time.time() > deadline:
            raise RuntimeError('Files not available after {}s: {}'
                               .format(timeout_s, missing[:3]))
        time.sleep(1)


def _parent_cache_dir(parent_cache_dir_url):
    url = parent_cache_dir_url or os.environ.get(CACHE_DIR_ENV)
    if not url:
        raise ValueError('No converter cache dir configured: pass '
                         'parent_cache_dir_url or set ${}'.format(CACHE_DIR_ENV))
    return url.rstrip('/')


def make_converter(df, parent_cache_dir_url=None, rowgroup_size_mb=32, compression=None,
                   rows_per_file=None):
    """Materialize a DataFrame/Table to a cached Parquet store and return a
    :class:`DatasetConverter` (reference: make_spark_converter,
    spark_dataset_converter.py:656-728). Re-converting identical content reuses the
    cached store."""
    if _is_spark_dataframe(df):
        return _make_converter_spark(df, _parent_cache_dir(parent_cache_dir_url),
                                     rowgroup_size_mb)
    import pyarrow as pa
    table = _to_arrow_table(df)
    parent = _parent_cache_dir(parent_cache_dir_url)
    fingerprint = _table_fingerprint(table)
    cache_dir = '{}/{}'.format(parent, fingerprint)

    from petastorm_tpu.fs_utils import (delete_path, get_filesystem_and_path_or_paths,
                                        path_exists)
    fs, cache_path = get_filesystem_and_path_or_paths(cache_dir)
    success_marker = cache_path + '/_SUCCESS'
    if path_exists(fs, success_marker):
        logger.info('Converter cache hit: %s', cache_dir)
    else:
        if path_exists(fs, cache_path):
            # A dir without _SUCCESS is a crashed partial conversion: its leftover part
            # files would be globbed into file_urls below. Start clean.
            logger.warning('Removing partial converter cache %s', cache_dir)
            delete_path(fs, cache_path)
        fs.create_dir(cache_path, recursive=True)
        from petastorm_tpu.etl.dataset_metadata import write_table_files
        write_table_files(fs, cache_path, table.schema, table.to_batches(),
                          rowgroup_size_mb=rowgroup_size_mb,
                          rows_per_file=rows_per_file,
                          compression=compression or 'snappy')
        with fs.open_output_stream(success_marker) as sink:
            sink.write(b'')
    file_infos = fs.get_file_info(pa.fs.FileSelector(cache_path))
    files = sorted(info.path for info in file_infos
                   if info.base_name.endswith('.parquet'))
    sizes = sorted(info.size for info in file_infos
                   if info.base_name.endswith('.parquet'))
    if sizes and sizes[len(sizes) // 2] < _MIN_RECOMMENDED_FILE_BYTES:
        logger.warning('Median converter file size %d bytes < recommended %d; consider '
                       'fewer/larger files (reference: '
                       'spark_dataset_converter.py:636-650)',
                       sizes[len(sizes) // 2], _MIN_RECOMMENDED_FILE_BYTES)
    converter = DatasetConverter(cache_dir, files, table.num_rows)
    _active_converters[cache_dir] = converter
    return converter


#: live Spark-branch converters by query-plan hash — IN-SESSION dedup only, like the
#: reference's plan ``sameResult`` scoping (spark_dataset_converter.py:585-607): a
#: plan hash identifies the query, not the data, so persisting it across sessions
#: would serve stale rows after the source data changed.
_spark_plan_converters = {}


def _make_converter_spark(df, parent, rowgroup_size_mb):
    """Spark-DataFrame branch: executors write the parquet (the data may not fit the
    driver), then the driver embeds petastorm metadata (Unischema JSON + rowgroup
    index — inferred from the files the executors wrote) via
    :func:`materialize_dataset`, so the cache is a full petastorm_tpu store exactly
    like the Arrow branch — ``make_reader`` works on it, not just
    ``make_batch_reader``. Dedup keys on ``DataFrame.semanticHash`` (query-plan
    identity), valid only within this session — see ``_spark_plan_converters``."""
    try:
        plan_key = df.semanticHash()
    except Exception:  # semanticHash is best-effort; None disables in-session dedup
        plan_key = None
    if plan_key is not None and plan_key in _spark_plan_converters:
        converter = _spark_plan_converters[plan_key]
        logger.info('Converter reuse (same spark plan this session): %s',
                    converter.cache_dir_url)
        return converter

    cache_dir = '{}/{}'.format(parent, uuid.uuid4().hex)
    df.write.option('parquet.block.size', rowgroup_size_mb << 20).parquet(cache_dir)
    from petastorm_tpu.etl.dataset_metadata import materialize_dataset, open_dataset
    from petastorm_tpu.unischema import Unischema
    handle = open_dataset(cache_dir)
    schema = Unischema.from_arrow_schema(handle.schema)
    with materialize_dataset(cache_dir, schema):
        pass  # files already written by the executors; exit embeds the metadata
    handle = open_dataset(cache_dir)
    files = sorted(f.path for f in handle.arrow_dataset.get_fragments())
    count = df.count()
    converter = DatasetConverter(cache_dir, files, count)
    _active_converters[cache_dir] = converter
    if plan_key is not None:
        _spark_plan_converters[plan_key] = converter
    return converter
