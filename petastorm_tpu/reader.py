"""Reader factories and the Reader runtime (reference: petastorm/reader.py).

``make_reader`` reads petastorm_tpu (or petastorm) datasets row-at-a-time with codec
decode; ``make_batch_reader`` reads any Parquet store columnar-batch-at-a-time. Both drive
the same columnar worker (petastorm_tpu/reader_worker.py) over a ventilated rowgroup
schedule with bounded in-flight work.
"""

import logging
import threading
import warnings

import numpy as np

from petastorm_tpu.cache import ArrowIpcDiskCache, LocalDiskCache, NullCache
from petastorm_tpu.errors import MetadataError, NoDataAvailableError
from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.fs_utils import (as_arrow_filesystem, check_hdfs_driver,
                                    make_filesystem_factory,
                                    normalize_dataset_url_or_urls)
from petastorm_tpu.reader_worker import ColumnarBatch, RowGroupWorker, WorkerSetup
from petastorm_tpu.telemetry.tracing import (merge_trace_events,
                                             set_trace_enabled, trace_enabled,
                                             trace_instant)
from petastorm_tpu.unischema import Unischema
from petastorm_tpu.workers import EmptyResultError
from petastorm_tpu.workers.dummy_pool import DummyPool
from petastorm_tpu.workers.thread_pool import ThreadPool
from petastorm_tpu.workers.ventilator import ConcurrentVentilator

logger = logging.getLogger(__name__)

#: extra rowgroups kept in flight beyond the worker count (reference: reader.py:45-47)
_VENTILATE_EXTRA_ROWGROUPS = 2

#: pool-shape defaults shared by the make_reader signature and the reader_pool
#: conflict warning — one source of truth so they cannot drift apart
_DEFAULT_POOL_TYPE = 'thread'
_DEFAULT_WORKERS_COUNT = 10
_DEFAULT_RESULTS_QUEUE_SIZE = 50


def _make_pool(reader_pool_type, workers_count, results_queue_size,
               shm_transport=None, item_deadline_s=None, heartbeat_interval_s=None):
    if reader_pool_type == 'thread':
        return ThreadPool(workers_count, results_queue_size)
    if reader_pool_type == 'process':
        from petastorm_tpu.workers.process_pool import ProcessPool
        kwargs = {}
        if heartbeat_interval_s is not None:
            kwargs['heartbeat_interval_s'] = heartbeat_interval_s
        return ProcessPool(workers_count, results_queue_size,
                           shm_transport=shm_transport,
                           item_deadline_s=item_deadline_s, **kwargs)
    if reader_pool_type == 'dummy':
        return DummyPool()
    raise ValueError('Unknown reader_pool_type {!r} (expected thread/process/dummy)'
                     .format(reader_pool_type))


def _retrying(fn, retry_policy, counter=None):
    """Run a construction-time filesystem operation (dataset open, rowgroup
    enumeration) under the reader's retry policy; ``counter`` (a 1-element list)
    accumulates retries so they surface in ``diagnostics['io_retries']`` like any
    worker-side retry."""
    if retry_policy is None:
        return fn()
    from petastorm_tpu.resilience import run_with_retry

    def on_retry(attempt, exc, delay):
        logger.warning('Transient IO failure opening dataset (attempt %d): %s; '
                       'retrying in %.3fs', attempt, exc, delay)
    result, retries = run_with_retry(fn, retry_policy, on_retry=on_retry)
    if counter is not None:
        counter[0] += retries
    return result


def _make_cache(cache_type, cache_location, cache_size_limit, cache_row_size_estimate,
                cache_extra_settings, cache_format='arrow-ipc', has_transform=False):
    if cache_type in (None, 'null'):
        return NullCache()
    if cache_type == 'local-disk':
        extra = dict(cache_extra_settings or {})
        if cache_format == 'arrow-ipc':
            cache_cls = ArrowIpcDiskCache
            # A transform_spec may mutate columns/rows in place; zero-copy mmap
            # hits are read-only and would crash it on the warm epoch only. Decode
            # hits writable in that case (one memcpy per column — still no Parquet
            # read/decode/unpickle); cache_extra_settings={'writable_hits': ...}
            # overrides either way.
            if has_transform:
                extra.setdefault('writable_hits', True)
        elif cache_format == 'pickle':
            cache_cls = LocalDiskCache
        else:
            raise ValueError('Unknown cache_format {!r} (expected arrow-ipc/pickle)'
                             .format(cache_format))
        cache = cache_cls(cache_location, cache_size_limit, cache_row_size_estimate or 0,
                          **extra)
        # An explicit writable_hits override is a statement about what the
        # consumer needs (e.g. in-place mutation of hit columns with no
        # transform_spec) — pin it so the autotuner never treats the hit mode
        # as a free knob (docs/autotuning.md).
        if 'writable_hits' in (cache_extra_settings or {}):
            cache.writable_hits_pinned = True
        return cache
    raise ValueError('Unknown cache_type {!r} (expected null/local-disk)'.format(cache_type))


def make_reader(dataset_url_or_urls, schema_fields=None,
                reader_pool_type=_DEFAULT_POOL_TYPE,
                workers_count=_DEFAULT_WORKERS_COUNT,
                results_queue_size=_DEFAULT_RESULTS_QUEUE_SIZE, seed=None, shuffle_rows=False,
                shuffle_row_groups=True, shuffle_row_drop_partitions=1, predicate=None,
                rowgroup_selector=None, num_epochs=1, cur_shard=None, shard_count=None,
                shard_seed=None, cache_type='null', cache_location=None,
                cache_size_limit=None, cache_row_size_estimate=None,
                cache_extra_settings=None, cache_format='arrow-ipc',
                transform_spec=None, storage_options=None,
                filesystem=None, resume_state=None, reader_pool=None,
                field_overrides=None, hdfs_driver='libhdfs', on_error='raise',
                retry_policy=None, shm_transport=None, item_deadline_s=None,
                heartbeat_interval_s=None, trace=None, service_url=None,
                autotune=None, device_decode_fields=None, metrics_port=None,
                slo_policy=None, cost_schedule=None, lineage=None,
                incidents=None, storage_policy=None, history=None,
                topology=None):
    """Reader for datasets written with a Unischema (petastorm_tpu or petastorm stores):
    rows decoded through codecs, emitted one namedtuple per ``next()`` (reference:
    petastorm/reader.py:62-204). ``schema_fields`` may be a list of field names / regexes,
    or an :class:`~petastorm_tpu.ngram.NGram` for sequence windows. ``reader_pool``
    overrides ``reader_pool_type`` with a pre-built pool instance (e.g. a ThreadPool with
    profiling_enabled). ``field_overrides`` — list of :class:`UnischemaField`s replacing
    same-named stored fields for THIS read (read-time reinterpretation: e.g. swap a
    ``DctImageCodec`` field to ``DctCoefficientsCodec`` so raw coefficients flow to an
    on-device decode). ``hdfs_driver`` — petastorm API compatibility (reference:
    reader.py:126-127); pyarrow.fs provides libhdfs only, 'libhdfs3' warns.

    Resilience (docs/robustness.md): ``on_error`` is the per-rowgroup failure policy —
    ``'raise'`` (default; any failure aborts the read, today's exact behavior),
    ``'retry'`` (transient IO failures are retried per ``retry_policy``, then raised),
    ``'skip'`` (after retries, the failing rowgroup is excluded and recorded in the
    quarantine ledger, visible via ``Reader.diagnostics['quarantine']``). ``retry_policy``
    is a :class:`~petastorm_tpu.resilience.RetryPolicy` (default: 3 attempts,
    exponential backoff with seeded jitter).

    Zero-copy data plane (docs/performance.md): ``cache_format`` picks the
    ``cache_type='local-disk'`` value format — ``'arrow-ipc'`` (default; decoded
    rowgroups stored as Arrow IPC files, hits are memory-mapped READ-ONLY zero-copy
    views — with a ``transform_spec`` present, hits are decoded writable instead so
    in-place mutation keeps working; ``cache_extra_settings={'writable_hits': ...}``
    overrides) or ``'pickle'`` (the reference's format; every hit pays a full
    unpickle and returns writable arrays).
    ``shm_transport`` controls the process pool's shared-memory result transport —
    None (auto-on when available), True (require), False (ZMQ frames only); ignored
    by thread/dummy pools, which never cross a process boundary.

    Hang watchdog (docs/robustness.md "Hang detection & circuit breakers";
    process pool only): ``item_deadline_s`` — a worker holding one rowgroup
    longer than this without a result is reaped and respawned; under
    ``on_error='skip'`` the offending rowgroup is quarantined with
    ``reason='hang'`` instead of re-dispatched (None, the default, disables the
    per-item deadline). ``heartbeat_interval_s`` — cadence of the workers'
    liveness stamps (default 0.5s; a worker whose stamp stalls while it holds
    work is reaped even without an item deadline; 0 disables stamping).

    Flight recorder (docs/observability.md "Flight recorder"): ``trace``
    arms/disarms the per-process trace ring buffer — True/False call
    :func:`~petastorm_tpu.telemetry.tracing.set_trace_enabled` (process-global,
    like the telemetry switch; workers spawned by this reader's pool inherit
    it), None (default) leaves the ``PETASTORM_TPU_TRACE`` env setting in
    place. Export the capture with ``Reader.dump_trace()``.

    Disaggregated input service (docs/service.md): ``service_url``
    (``'tcp://host:port'``) points this reader at a shared preprocessing
    fleet instead of building an in-process pool — decode runs on the
    service's workers, results arrive over TCP (shm fast path when
    co-located), and ``on_error`` modes, the quarantine ledger, telemetry
    and tracing work unchanged. Pool-shape arguments are ignored (the fleet
    defines its own shape); ``None`` (default) keeps today's in-process
    behavior byte-identical.

    Closed-loop autotuning (docs/autotuning.md): ``autotune=True`` (or an
    :class:`~petastorm_tpu.autotune.AutotunePolicy`) starts a controller
    thread that samples this reader's telemetry mid-epoch, attributes the
    bottleneck stage, and hill-climbs one knob at a time (ventilation depth,
    pool workers, decode threads, cache mode — propose, hold, measure rows/s,
    commit or revert) with the circuit-breaker board as a safety interlock.
    Inspect with :meth:`Reader.autotune_report` / ``diagnostics['autotune']``;
    every decision is also an ``autotune_decision`` JSONL/trace event. Off by
    default — with ``autotune`` unset no controller exists and no knob is
    ever touched.

    Device-resident decode tail (docs/performance.md): ``device_decode_fields``
    names codec fields whose payloads SKIP host decode — workers pass the
    compressed/packed bytes through (DCT coefficient blocks for
    ``DctImageCodec``, raw ``.npy`` bytes for ``NdarrayCodec``, raw deflate
    frames for ``CompressedNdarrayCodec``) and the
    :class:`~petastorm_tpu.parallel.loader.JaxDataLoader` decodes them as
    jitted device kernels after ONE coalesced upload, double-buffered against
    the train step. Raw-form values reach non-loader consumers as-is; the
    small ``__hw``/``__enc`` auxiliary metadata columns ride
    ``iter_columnar`` batches only (the namedtuple row/batch APIs emit schema
    fields and drop them). On a CPU backend the loader falls back to host
    decode byte-identically. Unset (default) keeps every
    path byte-identical to a reader without the knob. Mutually exclusive with
    ``transform_spec`` (host transforms need decoded values — use the loader's
    ``device_transforms`` instead) and NGram readers.

    Live metrics plane (docs/observability.md "Live metrics plane"):
    ``metrics_port`` attaches a scrape endpoint to this reader — ``/metrics``
    (Prometheus text over :meth:`Reader.telemetry_snapshot`, SLO gauges
    refreshed per scrape), ``/healthz``, ``/vars``; ``0`` binds an ephemeral
    port (``Reader.metrics_url`` names it), None (default) serves nothing.
    ``slo_policy`` sets the input-efficiency SLO
    (:class:`~petastorm_tpu.telemetry.slo.SloPolicy`, a float target, or
    None = the default 0.9 target) evaluated by
    :meth:`Reader.efficiency_report` / ``diagnostics['slo']``.

    Cost-aware scheduling (docs/performance.md "Cost-aware scheduling"):
    ``cost_schedule`` consumes the persisted per-rowgroup cost ledger
    (``petastorm-tpu-throughput costs``) to interleave heavy and light
    rowgroups deterministically (same seed + same ledger => same order on
    every pool), split oversized rowgroups into sub-range work items, and
    pre-stage predicted-slow items — ``True`` (default policy), a
    :class:`~petastorm_tpu.schedule.SchedulePolicy`, or a ledger path
    string. With no persisted ledger the read is byte-identical to an
    unscheduled reader (cold start) while live cost observations accumulate
    and persist at ``stop()`` for the next run. Unset (None, the default)
    builds no scheduler and keeps every path byte-identical. Not compatible
    with ``resume_state`` (a re-planned schedule would shift the
    checkpoint's item coordinates).

    Sample-lineage audit (docs/observability.md "Sample lineage &
    determinism audit"): ``lineage`` arms the
    :class:`~petastorm_tpu.telemetry.lineage.LineageRecorder` — a chained
    order digest over every delivered item's ``(epoch, fragment, rowgroup,
    row_range, drop, rows)`` identity (:meth:`Reader.order_digest`;
    identical across dummy/thread/process/service pools for the same seed,
    invariant under worker respawns), optional sampled content fingerprints,
    and a bounded batch-manifest JSONL next to the dataset that
    ``petastorm-tpu-throughput lineage verify`` replays without reading
    data. ``True`` (default policy), a manifest path string, or a
    :class:`~petastorm_tpu.telemetry.lineage.LineagePolicy`; digest state
    rides ``state_dict()`` so save/resume folds to the same digest. Unset
    (None, the default) records nothing.

    Incident autopsy plane (docs/observability.md "Incident autopsy
    plane"): ``incidents`` arms an edge-triggered black-box recorder
    (:class:`~petastorm_tpu.telemetry.incident.IncidentRecorder`) — when a
    failure edge fires (breaker trip, hang-watchdog reap, quarantine, shm
    CRC drop, SLO breach, lineage divergence) the recorder atomically writes
    a bundle directory holding the drained trace ring, the full telemetry
    snapshot, breaker/quarantine/cost/lineage state and config provenance,
    rate-limited per trigger kind and retention-bounded. Inspect with
    ``petastorm-tpu-throughput autopsy <bundle>`` (ranked probable-cause
    report) and :meth:`Reader.incident_report` / ``diagnostics
    ['incidents']``. ``True`` (default policy), or an
    :class:`~petastorm_tpu.telemetry.incident.IncidentPolicy`. Unset (None,
    the default) builds no recorder and keeps every path byte-identical.

    Object-store ingest engine (docs/performance.md "Object-store ingest
    engine"): ``storage_policy`` arms planned byte-range I/O in the workers
    — column-chunk ranges planned from a cached Parquet footer, coalesced
    into merged GETs, fetched by a parallel bounded-window pool with
    tail-latency request hedging. ``None`` (default) auto-engages only for
    non-local URL schemes (s3/gs/abfs/...) and keeps local/HDFS reads
    byte-identical to the seed path; ``False`` never engages; ``True`` or a
    :class:`~petastorm_tpu.storage.StoragePolicy` always does. Counters and
    ``range_fetch``/``range_hedge`` stage timings land in
    :meth:`Reader.telemetry_snapshot`; per-rowgroup fetch costs flow into
    the cost ledger so ``cost_schedule`` prices network I/O too.

    Longitudinal observatory (docs/observability.md "Longitudinal
    observatory"): ``history`` arms the cross-run goodput historian — one
    structured run record (config/knob/storage/schedule fingerprints,
    rows/s, goodput efficiency, per-stage time shares, storage counters,
    incident/quarantine counts) is appended at ``stop()`` to an append-only
    CRC-framed store keyed by :attr:`Reader.dataset_token`, which
    ``petastorm-tpu-throughput history list|show|compare`` diffs against a
    robust trailing baseline with change-point attribution. Arming history
    also arms the live regression sentinel (an EWMA + Page–Hinkley drift
    test over the run's own rows/s and wait-share series) that fires a
    ``perf_regression`` incident on a mid-run goodput collapse. ``True``
    (default policy), a store path string, or a
    :class:`~petastorm_tpu.telemetry.history.HistoryPolicy` (its
    ``sentinel`` field tunes/disables the sentinel). Unset (None, the
    default) records nothing and keeps every path byte-identical.

    Elastic pod-scale sharding (docs/robustness.md "Elastic pod-scale
    sharding"): ``topology`` replaces static ``cur_shard``/``shard_count``
    with a shard map negotiated from the process topology
    (``jax.process_index()``/``process_count()``, env-overridable with
    ``PETASTORM_TPU_PROCESS_INDEX/_COUNT``) and recorded in a durable
    CRC-framed membership journal on shared storage; on a host
    join/leave/lease expiry the survivors re-deal ONLY the undelivered
    rowgroups, and per-host lineage digests compose into a
    topology-invariant global digest
    (:func:`~petastorm_tpu.parallel.topology.compose_global_digest`).
    ``True`` (default policy), a journal path string, or a
    :class:`~petastorm_tpu.parallel.topology.TopologyPolicy`. Mutually
    exclusive with ``cur_shard``/``shard_count``/``shard_seed`` and
    ``cost_schedule``. Unset (None, the default) keeps the static-shard
    path byte-identical."""
    from petastorm_tpu.resilience import resolve_retry_policy
    if trace is not None:
        set_trace_enabled(bool(trace))
    check_hdfs_driver(hdfs_driver)
    retry_policy = resolve_retry_policy(on_error, retry_policy)
    construction_retries = [0]
    dataset_url_or_urls = normalize_dataset_url_or_urls(dataset_url_or_urls)
    handle = _retrying(
        lambda: dataset_metadata.open_dataset(dataset_url_or_urls,
                                              storage_options=storage_options,
                                              filesystem=filesystem),
        retry_policy, construction_retries)
    try:
        schema = dataset_metadata.get_schema(handle)
    except MetadataError:
        raise RuntimeError(
            'Dataset at {!r} has no Unischema metadata. Use make_batch_reader for plain '
            'Parquet stores.'.format(dataset_url_or_urls))
    if field_overrides:
        schema = _apply_field_overrides(schema, field_overrides)
    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings, cache_format,
                        has_transform=transform_spec is not None)
    if service_url is not None:
        if reader_pool is not None:
            raise ValueError('service_url and reader_pool are mutually '
                             'exclusive — the service defines the pool')
        from petastorm_tpu.service.service_client import ServicePool
        reader_pool = ServicePool(service_url)
    if reader_pool is not None:
        # Pool-shape kwargs describe a pool this call is NOT building (ADVICE.md r1).
        ignored = [name for name, value, default in [
            ('workers_count', workers_count, _DEFAULT_WORKERS_COUNT),
            ('results_queue_size', results_queue_size, _DEFAULT_RESULTS_QUEUE_SIZE),
            ('reader_pool_type', reader_pool_type, _DEFAULT_POOL_TYPE),
            ('shm_transport', shm_transport, None),
            ('item_deadline_s', item_deadline_s, None),
            ('heartbeat_interval_s', heartbeat_interval_s, None)]
            if value != default]
        if ignored:
            warnings.warn('{} was supplied; ignoring pool-shape arguments {} '
                          '(the {} defines its own shape)'.format(
                              'service_url' if service_url is not None
                              else 'reader_pool', ignored,
                              'service fleet' if service_url is not None
                              else 'pre-built pool'))
    pool = reader_pool if reader_pool is not None else _make_pool(
        reader_pool_type, workers_count, results_queue_size, shm_transport,
        item_deadline_s, heartbeat_interval_s)
    return Reader(dataset_url_or_urls, handle=handle, schema=schema,
                  schema_fields=schema_fields,
                  reader_pool=pool, seed=seed, shuffle_rows=shuffle_rows,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs, cur_shard=cur_shard, shard_count=shard_count,
                  shard_seed=shard_seed, cache=cache, transform_spec=transform_spec,
                  is_batched_reader=False, decode=True,
                  storage_options=storage_options, filesystem=filesystem,
                  resume_state=resume_state, on_error=on_error,
                  retry_policy=retry_policy,
                  initial_io_retries=construction_retries[0],
                  autotune=autotune, device_decode_fields=device_decode_fields,
                  metrics_port=metrics_port, slo_policy=slo_policy,
                  cost_schedule=cost_schedule, lineage=lineage,
                  incidents=incidents, storage_policy=storage_policy,
                  history=history, topology=topology)


def make_batch_reader(dataset_url_or_urls, schema_fields=None, reader_pool_type='thread',
                      workers_count=10, results_queue_size=50, seed=None,
                      shuffle_rows=False, shuffle_row_groups=True,
                      shuffle_row_drop_partitions=1, predicate=None, num_epochs=1,
                      cur_shard=None, shard_count=None, shard_seed=None, cache_type='null',
                      cache_location=None, cache_size_limit=None,
                      cache_row_size_estimate=None, cache_extra_settings=None,
                      cache_format='arrow-ipc', transform_spec=None,
                      storage_options=None, filesystem=None,
                      resume_state=None, hdfs_driver='libhdfs', on_error='raise',
                      retry_policy=None, shm_transport=None, item_deadline_s=None,
                      heartbeat_interval_s=None, trace=None, service_url=None,
                      autotune=None, device_decode_fields=None,
                      metrics_port=None, slo_policy=None, cost_schedule=None,
                      lineage=None, incidents=None, storage_policy=None,
                      history=None, topology=None):
    """Reader for arbitrary Parquet stores: native columns only (no codec decode), one
    namedtuple of column arrays per rowgroup batch (reference: petastorm/reader.py:207-346).
    ``on_error`` / ``retry_policy`` / ``cache_format`` / ``shm_transport`` /
    ``item_deadline_s`` / ``heartbeat_interval_s`` / ``trace`` /
    ``service_url`` / ``autotune`` / ``metrics_port`` / ``slo_policy`` /
    ``cost_schedule`` / ``lineage`` / ``incidents`` / ``storage_policy`` /
    ``history`` / ``topology``
    behave exactly as in
    :func:`make_reader`.
    ``device_decode_fields`` (docs/performance.md "Device-resident decode
    tail") requires the store's Unischema codec registry: on a Unischema
    store the named fields ship their raw codec payloads (container stripped)
    instead of the stored blob values; on a plain Parquet store it raises —
    there is no codec to interpret the bytes with (use :func:`make_reader`
    for the full decode tail).
    """
    from petastorm_tpu.resilience import resolve_retry_policy
    if trace is not None:
        set_trace_enabled(bool(trace))
    check_hdfs_driver(hdfs_driver)
    retry_policy = resolve_retry_policy(on_error, retry_policy)
    construction_retries = [0]
    dataset_url_or_urls = normalize_dataset_url_or_urls(dataset_url_or_urls)
    handle = _retrying(
        lambda: dataset_metadata.open_dataset(dataset_url_or_urls,
                                              storage_options=storage_options,
                                              filesystem=filesystem),
        retry_policy, construction_retries)
    stored_schema = None
    try:
        stored_schema = dataset_metadata.get_schema(handle)
        warnings.warn('This store was written with a Unischema; use make_reader to get '
                      'codec-decoded rows. make_batch_reader will emit raw stored values.')
    except MetadataError:
        pass
    if device_decode_fields:
        # the batch reader has no codec registry of its own: ship-raw kernels
        # need the store's Unischema to know each field's payload form
        if stored_schema is None:
            raise ValueError(
                'device_decode_fields requires a Unischema store (the codec '
                'registry tells the ship-raw kernels what the payload bytes '
                'are); this store has none — use make_reader on a Unischema '
                'store instead')
        batch_schema = stored_schema
    else:
        batch_schema = None
    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings, cache_format,
                        has_transform=transform_spec is not None)
    if service_url is not None:
        # Pool-shape kwargs describe a pool this call is NOT building — the
        # service fleet defines its own shape (same contract as make_reader's
        # reader_pool warning).
        ignored = [name for name, value, default in [
            ('workers_count', workers_count, _DEFAULT_WORKERS_COUNT),
            ('results_queue_size', results_queue_size, _DEFAULT_RESULTS_QUEUE_SIZE),
            ('reader_pool_type', reader_pool_type, _DEFAULT_POOL_TYPE),
            ('shm_transport', shm_transport, None),
            ('item_deadline_s', item_deadline_s, None),
            ('heartbeat_interval_s', heartbeat_interval_s, None)]
            if value != default]
        if ignored:
            warnings.warn('service_url was supplied; ignoring pool-shape '
                          'arguments {} (the service fleet defines its own '
                          'shape)'.format(ignored))
        from petastorm_tpu.service.service_client import ServicePool
        pool = ServicePool(service_url)
    else:
        pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                          shm_transport, item_deadline_s, heartbeat_interval_s)
    return Reader(dataset_url_or_urls, handle=handle, schema=batch_schema,
                  schema_fields=schema_fields,
                  reader_pool=pool, seed=seed, shuffle_rows=shuffle_rows,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate, rowgroup_selector=None, num_epochs=num_epochs,
                  cur_shard=cur_shard, shard_count=shard_count, shard_seed=shard_seed,
                  cache=cache, transform_spec=transform_spec, is_batched_reader=True,
                  decode=False, storage_options=storage_options, filesystem=filesystem,
                  resume_state=resume_state, on_error=on_error,
                  retry_policy=retry_policy,
                  initial_io_retries=construction_retries[0],
                  autotune=autotune, device_decode_fields=device_decode_fields,
                  metrics_port=metrics_port, slo_policy=slo_policy,
                  cost_schedule=cost_schedule, lineage=lineage,
                  incidents=incidents, storage_policy=storage_policy,
                  history=history, topology=topology)


class Reader(object):
    """The reader runtime: schedules rowgroups through a worker pool and iterates results
    (reference: petastorm/reader.py:349-710)."""

    def __init__(self, dataset_url_or_urls, handle=None, schema=None, schema_fields=None,
                 reader_pool=None, seed=None, shuffle_rows=False, shuffle_row_groups=True,
                 shuffle_row_drop_partitions=1, predicate=None, rowgroup_selector=None,
                 num_epochs=1, cur_shard=None, shard_count=None, shard_seed=None,
                 cache=None, transform_spec=None, is_batched_reader=False, decode=True,
                 storage_options=None, filesystem=None, resume_state=None,
                 on_error='raise', retry_policy=None, initial_io_retries=0,
                 autotune=None, device_decode_fields=None, metrics_port=None,
                 slo_policy=None, cost_schedule=None, lineage=None,
                 incidents=None, storage_policy=None, history=None,
                 topology=None):
        from petastorm_tpu.resilience import QuarantineLedger, resolve_retry_policy
        retry_policy = resolve_retry_policy(on_error, retry_policy)
        construction_retries = [initial_io_retries]
        construction_policy = retry_policy
        self.num_epochs = num_epochs
        self.is_batched_reader = is_batched_reader
        self.last_row_consumed = False
        self._stopped = False
        self.on_error = on_error
        #: skip-with-quarantine ledger — records arrive on the results channel attached
        #: to the empty stand-in batches of skipped rowgroups (docs/robustness.md)
        self.quarantine = QuarantineLedger()
        self._io_retries = 0
        # Circuit-breaker observability: worker-process breaker states arrive on
        # each batch's 'breakers' sidecar (last writer wins per breaker name) and
        # merge with this process's board in diagnostics['breakers'].
        self._breaker_states = {}
        # Cache observability: per-batch cache_hit sidecar flags accumulate here
        # (works across all pools — the flag rides the results channel).
        self._cache = cache
        self._cache_hits = 0
        self._cache_misses = 0
        # Autotune goodput signal (docs/autotuning.md): rows delivered off the
        # results channel — the controller's per-window rows/s numerator.
        self._rows_consumed = 0
        self._transform_spec = transform_spec
        self._autotune = None
        # Pipeline telemetry (docs/observability.md): worker-process stage times
        # arrive on each batch's telemetry sidecar and merge here; pool-level
        # registries merge at snapshot time, so telemetry_snapshot() covers every
        # process that touched this reader's rows.
        from petastorm_tpu.telemetry import MetricsRegistry
        self._telemetry = MetricsRegistry()
        # Input-efficiency SLO (docs/observability.md "Efficiency SLOs"):
        # windows are measured from construction on the span clock; breach
        # events are edge-triggered inside the tracker, so polling
        # diagnostics cannot inflate the count.
        from petastorm_tpu.telemetry.export import logger_from_env
        from petastorm_tpu.telemetry.slo import (SloTracker,
                                                 resolve_slo_policy, slo_clock)
        self._started_at = slo_clock()
        self._slo = SloTracker(resolve_slo_policy(slo_policy),
                               jsonl=logger_from_env())
        self._metrics_server = None
        # Sample-lineage audit plane (docs/observability.md): the policy is
        # resolved up front (its fingerprint sampling knob ships to workers
        # in the WorkerSetup); the recorder itself is built after the work
        # plan is frozen, so its manifest header can record the exact
        # reproduction config.
        from petastorm_tpu.telemetry.lineage import resolve_lineage_policy
        self._lineage = None
        self._lineage_policy = resolve_lineage_policy(lineage)
        # Incident autopsy plane (docs/observability.md "Incident autopsy
        # plane"): policy resolved up front, the recorder itself is built
        # after the pool starts — its evidence sources (cost/lineage/
        # autotune) must exist before the first edge can fire.
        from petastorm_tpu.telemetry.incident import resolve_incident_policy
        self._incidents = None
        self._incident_policy = resolve_incident_policy(incidents)
        # Longitudinal observatory (docs/observability.md "Longitudinal
        # observatory"): policy resolved up front; the historian + sentinel
        # are built after the incident plane so the sentinel can file its
        # perf_regression bundles there. Unset => nothing is built.
        from petastorm_tpu.telemetry.history import resolve_history_policy
        self._history = None
        self._history_policy = resolve_history_policy(history)
        self._history_written = False
        self._history_fingerprints = {}
        self._sentinel = None
        # edge-detection state for the poll-based triggers (all consumed
        # under _accounting_lock in _note_item_consumed)
        self._incident_last_divergence = 0
        self._incident_last_crc_failures = 0
        # Elastic pod-scale sharding (docs/robustness.md "Elastic pod-scale
        # sharding"): policy resolved up front; the HostTopology itself is
        # built once the filtered rowgroup list exists, so the negotiated
        # deal covers exactly what this read will ventilate. Unset => no
        # journal, no negotiation — the static path stays byte-identical.
        from petastorm_tpu.parallel.topology import resolve_topology_policy
        self._topology = None
        self._topology_policy = resolve_topology_policy(topology)
        self._shard_skew = None

        if (cur_shard is None) != (shard_count is None):
            raise ValueError('cur_shard and shard_count must be specified together')
        if cur_shard is not None and not 0 <= cur_shard < shard_count:
            raise ValueError('cur_shard must be in [0, shard_count)')
        if self._topology_policy is not None:
            if cur_shard is not None or shard_seed is not None:
                raise ValueError(
                    'topology= and static cur_shard/shard_count/shard_seed '
                    'are mutually exclusive — the topology plane negotiates '
                    'the shard map (docs/robustness.md "Elastic pod-scale '
                    'sharding")')
            if cost_schedule is not None:
                raise ValueError(
                    'topology= is not compatible with cost_schedule — a '
                    're-planned interleave would shift the global item '
                    'coordinates a reshard re-deals')
        if predicate is not None and schema_fields is not None and _is_ngram(schema_fields):
            raise ValueError('Predicates are not supported together with NGram '
                             '(reference semantics: reader.py:430-434)')

        if handle is None:
            handle = _retrying(
                lambda: dataset_metadata.open_dataset(dataset_url_or_urls,
                                                      storage_options=storage_options,
                                                      filesystem=filesystem),
                construction_policy, construction_retries)
        self._handle = handle
        if schema is None:
            schema = Unischema.from_arrow_schema(handle.arrow_dataset.schema)
        self.schema = schema

        ngram = None
        if schema_fields is not None and _is_ngram(schema_fields):
            ngram = schema_fields
            if is_batched_reader:
                raise ValueError('NGram is not supported by make_batch_reader '
                                 '(reference semantics: arrow_reader_worker.py:107-108)')
            ngram.resolve_regex_field_names(schema)
            if not ngram.timestamp_overlap and shuffle_row_drop_partitions > 1:
                raise NotImplementedError('timestamp_overlap=False is not supported with '
                                          'shuffle_row_drop_partitions > 1 (reference: '
                                          'reader.py:436-438)')
            fields_to_read = list(ngram.get_field_names_at_all_timesteps())
        elif schema_fields is not None:
            view = schema.create_schema_view(schema_fields)
            fields_to_read = list(view.fields)
        else:
            fields_to_read = list(schema.fields)
        self.ngram = ngram

        # Predicate fields must be loaded even if not in the requested view.
        partition_names = set(handle.partition_field_names)
        worker_predicate = predicate
        main_process_predicate = None
        if predicate is not None:
            predicate_fields = set(predicate.get_fields())
            if predicate_fields and predicate_fields <= partition_names:
                # Pure partition-key predicate: prune rowgroups up front, no worker work
                # (reference: reader.py:617-641).
                main_process_predicate = predicate
                worker_predicate = None
            else:
                missing = [f for f in predicate_fields if f not in fields_to_read]
                fields_to_read += [f for f in missing if f in schema.fields
                                   or f in partition_names]

        # ------------------------------------------- device-resident decode tail
        # (docs/performance.md): validate the ship-raw field set up front so a
        # bad knob fails at construction with a precise message, not inside a
        # worker process mid-epoch.
        self.device_decode_fields = frozenset(device_decode_fields or ())
        if self.device_decode_fields:
            from petastorm_tpu import decode_engine
            if ngram is not None:
                raise ValueError('device_decode_fields is not supported with '
                                 'NGram readers (windows need decoded values)')
            if transform_spec is not None:
                raise ValueError(
                    'device_decode_fields and transform_spec are mutually '
                    'exclusive: host transforms need decoded values — declare '
                    'the augment chain as JaxDataLoader device_transforms '
                    'instead (docs/performance.md)')
            missing = sorted(f for f in self.device_decode_fields
                             if f not in fields_to_read)
            if missing:
                raise ValueError('device_decode_fields name fields not in this '
                                 'read: {}'.format(missing))
            in_partition = sorted(self.device_decode_fields & partition_names)
            if in_partition:
                raise ValueError('device_decode_fields cannot name partition '
                                 'keys: {}'.format(in_partition))
            for name in sorted(self.device_decode_fields):
                field = schema.fields.get(name)
                if field is None:
                    raise ValueError('device_decode_fields names field {!r} '
                                     'which has no schema entry'.format(name))
                decode_engine.validate_device_field(field)

        # Object-store ingest engine (docs/performance.md): resolve the
        # storage_policy kwarg ONCE against the dataset URL — None stays None
        # on local/HDFS schemes, so the seed path pays nothing, not even an
        # attribute lookup in the workers' hot loop.
        from petastorm_tpu.storage import resolve_storage_policy
        self._storage_policy = resolve_storage_policy(storage_policy,
                                                      dataset_url_or_urls)

        url_for_factory = dataset_url_or_urls if not isinstance(dataset_url_or_urls, list) \
            else dataset_url_or_urls[0]
        # Workers feed this filesystem into Arrow C++ — unwrap any HA failover proxy
        # (as_arrow_filesystem) when the caller supplied one explicitly. Under a
        # retrying on_error policy the factory itself retries filesystem RESOLUTION
        # (connection setup is as transient-failure-prone as reads).
        filesystem_factory = (make_filesystem_factory(url_for_factory, storage_options,
                                                      retry_policy=retry_policy)
                              if filesystem is None
                              else (lambda: as_arrow_filesystem(filesystem)))
        worker_setup = WorkerSetup(
            dataset_path_or_paths=handle.path_or_paths,
            filesystem_factory=filesystem_factory,
            schema=schema,
            fields_to_read=fields_to_read,
            transform_spec=transform_spec,
            batched_output=is_batched_reader,
            decode=decode,
            ngram=ngram,
            cache=cache,
            shuffle_rows=shuffle_rows,
            seed=seed,
            partition_field_names=partition_names,
            on_error=on_error,
            retry_policy=retry_policy,
            device_decode_fields=self.device_decode_fields,
            lineage_fingerprint_every=(self._lineage_policy.fingerprint_every
                                       if self._lineage_policy is not None
                                       else 0),
            storage_policy=self._storage_policy)
        # Single source of truth for the emitted schema: the workers' own derivation.
        self.result_schema = worker_setup.result_schema
        #: the dataset identity the disk cache and the cost ledger key on
        #: (docs/observability.md "Cost profiler")
        self.dataset_token = worker_setup.dataset_token

        # ------------------------------------------------ rowgroup schedule
        # Under 'skip', permanently unreadable footers (truncated part-files) are
        # excluded from the schedule and quarantined at enumeration time — workers
        # would only re-discover the same corruption per rowgroup. Records are staged
        # per attempt and committed once, so a transient mid-enumeration failure that
        # triggers a construction retry cannot double-record a corrupt fragment.
        # NOT with a rowgroup_selector: its selected indexes refer to the FULL
        # enumeration (see below), and dropping a fragment would silently shift every
        # later piece under the selection — a corrupt footer is loud in that combination.
        def enumerate_row_groups():
            staged = []
            on_fragment_error = None
            if on_error == 'skip' and rowgroup_selector is None:
                from petastorm_tpu.resilience import QuarantineRecord

                def on_fragment_error(exc, fragment_path, fragment_index):
                    staged.append(QuarantineRecord.from_exception(
                        exc, piece_index=fragment_index, fragment_path=fragment_path,
                        row_group_id=None, attempts=1, epoch=0))
            return dataset_metadata.load_row_groups(
                handle, on_fragment_error=on_fragment_error), staged

        row_groups, construction_quarantine = _retrying(
            enumerate_row_groups, construction_policy, construction_retries)
        if construction_quarantine and resume_state is not None:
            # Fragments dropped at enumeration shift the (piece, drop) coordinates the
            # checkpoint's consumed sets refer to; a shifted resume would silently
            # re-serve or lose the wrong rowgroups. items_per_epoch validation below
            # only catches COUNT changes — refuse explicitly.
            raise ValueError(
                'Cannot resume: {} fragment(s) became unreadable since the checkpoint '
                'was taken ({}); resume coordinates would not match the checkpoint'
                .format(len(construction_quarantine),
                        ', '.join(r.fragment_path for r in construction_quarantine)))
        for record in construction_quarantine:
            self.quarantine.add(record)
        self._io_retries = construction_retries[0]
        if rowgroup_selector is not None:
            # Selector piece indexes refer to the FULL load_row_groups enumeration (what
            # build_rowgroup_index scanned) — apply before any other filtering.
            from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
            indexes = get_row_group_indexes(handle)
            selected = rowgroup_selector.select_row_groups(indexes)
            row_groups = [rg for i, rg in enumerate(row_groups) if i in selected]
        if main_process_predicate is not None:
            row_groups = [rg for rg in row_groups
                          if _eval_partition_predicate(main_process_predicate, rg)]
        self._row_groups = row_groups

        if self._topology_policy is not None:
            # Negotiated sharding: the deal is computed over GLOBAL rowgroup
            # indices, journaled for the rest of the pod, and replaces the
            # static modulo split (generation-0 deals match it exactly).
            from petastorm_tpu.parallel.topology import (
                HostTopology, default_topology_journal_path)
            from petastorm_tpu.dataset_state import cache_state_home
            url_for_topology = dataset_url_or_urls if not isinstance(
                dataset_url_or_urls, list) else dataset_url_or_urls[0]
            journal_path = self._topology_policy.journal_path or \
                default_topology_journal_path(url_for_topology,
                                              cache_state_home(cache))
            if journal_path is None:
                raise ValueError(
                    'topology= needs a membership journal on shared storage, '
                    'but this dataset has no local state home (remote store, '
                    'no cache) — pass TopologyPolicy(journal_path=...)')
            self._topology = HostTopology(self._topology_policy, journal_path,
                                          len(row_groups),
                                          registry=self._telemetry)
            bad = [i for i in self._topology.assignment
                   if not 0 <= i < len(row_groups)]
            if bad:
                raise ValueError(
                    'topology assignment names global rowgroup indices {} '
                    'outside this dataset\'s {} filtered rowgroup(s) — the '
                    'policy was dealt against a different dataset or filter '
                    'config'.format(bad, len(row_groups)))
            effective_count = self._topology.process_count
            shard_row_groups = [row_groups[i]
                                for i in self._topology.assignment]
        else:
            effective_count = shard_count
            shard_row_groups = self._partition_row_groups(
                row_groups, cur_shard, shard_count, shard_seed)
        # Degenerate-sharding detector (docs/robustness.md): a shard count
        # above the filtered rowgroup count leaves >= 1 sibling empty — THIS
        # shard may look healthy while the pod's split is silently skewed.
        # Detected here on every shard so pods see it before training starts.
        if effective_count is not None and effective_count > len(row_groups):
            self._shard_skew = {
                'shard_count': effective_count,
                'rowgroups': len(row_groups),
                'empty_shards': effective_count - len(row_groups),
            }
            warnings.warn(
                'shard_skew: {} shard(s) over {} rowgroup(s) leaves {} '
                'shard(s) empty and the split skewed — use fewer shards or '
                'more files (diagnostics["shard_skew"])'.format(
                    effective_count, len(row_groups),
                    effective_count - len(row_groups)))
        if not shard_row_groups:
            raise NoDataAvailableError(
                'No rowgroups available for shard {} of {} (dataset has {} rowgroups '
                'after filtering). Use fewer shards or more files.'
                .format(self._topology.process_index
                        if self._topology is not None else cur_shard,
                        effective_count, len(row_groups)))
        self._shard_row_groups = shard_row_groups
        #: the frozen shard configuration a checkpoint must match on resume
        #: (satellite: silent wrong-stream replay on config drift)
        self._shard_config = {'cur_shard': cur_shard,
                              'shard_count': shard_count,
                              'shard_seed': shard_seed,
                              'topology': self._topology is not None}

        items = []
        for piece_index, rg in enumerate(shard_row_groups):
            for drop_part in range(shuffle_row_drop_partitions):
                items.append({
                    'piece_index': piece_index,
                    'fragment_path': rg.fragment_path,
                    'row_group_id': rg.row_group_id,
                    'partition_keys': rg.partition_keys,
                    'worker_predicate': worker_predicate,
                    'shuffle_row_drop_partition': (drop_part, shuffle_row_drop_partitions),
                })

        # -------------------------------------------- cost-aware scheduling
        # (docs/performance.md "Cost-aware scheduling"): load the persisted
        # per-rowgroup cost ledger, split oversized rowgroups into sub-range
        # work items, and pick the epoch ventilation order — all frozen here
        # (pure function of ledger + seed), so the order never depends on
        # runtime timing. Unset => nothing is built, every path byte-identical.
        #: piece index -> (fragment_path, row_group_id), incl. the virtual
        #: pieces of split rowgroups — what cost_ledger() attributes with
        self._piece_locator = {index: (rg.fragment_path, rg.row_group_id)
                               for index, rg in enumerate(shard_row_groups)}
        self._cost_scheduler = None
        order_fn = None
        from petastorm_tpu.schedule import resolve_schedule_policy
        schedule_policy = resolve_schedule_policy(cost_schedule)
        if schedule_policy is not None:
            if resume_state is not None:
                raise ValueError(
                    'cost_schedule cannot be combined with resume_state: a '
                    're-planned schedule (ledger-driven splits) would shift '
                    'the work-item coordinates the checkpoint refers to — '
                    'resume without cost_schedule')
            from petastorm_tpu.dataset_state import cache_state_home
            from petastorm_tpu.schedule import CostAwareScheduler, load_ledger
            url_for_ledger = dataset_url_or_urls if not isinstance(
                dataset_url_or_urls, list) else dataset_url_or_urls[0]
            ledger, ledger_path = load_ledger(
                url_for_ledger, self.dataset_token,
                cache_location=cache_state_home(cache),
                ledger_path=schedule_policy.ledger_path)
            self._cost_scheduler = CostAwareScheduler(
                self.dataset_token, schedule_policy, ledger=ledger,
                ledger_path=ledger_path)
            locator = {index: (rg.fragment_path, rg.row_group_id,
                               rg.row_group_num_rows)
                       for index, rg in enumerate(shard_row_groups)}
            # NGram windows span rows — interleave applies, splitting never.
            # Split parts cap at the pool's worker count (sub-ranges re-pay
            # the rowgroup read, so parts beyond the parallelism are
            # overhead), floored at 2: even a 1-worker pool benefits from a
            # p99 rowgroup publishing incrementally, and the floor keeps the
            # plan identical across equally-shaped pool/service topologies.
            items, _virtual = self._cost_scheduler.plan_items(
                items, locator, allow_split=ngram is None,
                max_parts=max(2, int(getattr(reader_pool, 'workers_count',
                                             1) or 1)))
            # ONE source of truth for piece->rowgroup attribution (virtual
            # split pieces included): the scheduler's own plan map
            self._piece_locator = self._cost_scheduler.piece_locator()
            if shuffle_row_groups:
                order_fn = self._cost_scheduler.order_items
                self._cost_scheduler.live_reorder = True
            else:
                # no per-epoch shuffle: one static cost-balanced order,
                # identical every epoch (the FIFO analog of the seeded path)
                items = self._cost_scheduler.order_items(items, None)

        # ---------------------------------------------- checkpoint / resume
        # Consumption is tracked at work-item (rowgroup x drop-partition) granularity:
        # every item yields exactly one ColumnarBatch, tagged with its absolute epoch and
        # counted when popped off the results queue. Deterministic epoch order (sorted
        # fragments + seeded shuffles) makes the position replayable — the extension
        # SURVEY.md §5.4 prescribes over the reference's epoch-only restart granularity.
        self._items_per_epoch = len(items)
        self._accounting_lock = threading.Lock()
        self._next_lock = threading.Lock()  # concurrent next() support (see __next__)
        self._epochs_consumed = 0
        self._consumed_by_epoch = {}  # absolute epoch -> set of (piece, drop)
        iterations = num_epochs
        skip_by_iteration = None
        pre_shuffles = 0
        self._resume_fast_forward = {}
        self._resume_lineage = None
        if resume_state is not None:
            self._load_resume_state(resume_state)
            pre_shuffles = self._epochs_consumed
            skip_by_iteration = {epoch - self._epochs_consumed: set(ids)
                                 for epoch, ids in self._consumed_by_epoch.items()}
            if num_epochs is not None:
                iterations = num_epochs - self._epochs_consumed
                if iterations <= 0:
                    raise ValueError(
                        'resume_state shows all {} epochs already consumed'.format(num_epochs))

        # ------------------------------------------------- lineage recorder
        # (docs/observability.md "Sample lineage & determinism audit"): built
        # once the work plan is frozen — the manifest header written here is
        # the exact reproduction record the dry replay verifier consumes.
        if self._lineage_policy is not None:
            from petastorm_tpu.dataset_state import cache_state_home
            from petastorm_tpu.telemetry.lineage import (LineageRecorder,
                                                         build_manifest_logger,
                                                         canonical_identity)
            url_for_state = dataset_url_or_urls if not isinstance(
                dataset_url_or_urls, list) else dataset_url_or_urls[0]
            manifest_jsonl, manifest_path = build_manifest_logger(
                self._lineage_policy, url_for_state, self.dataset_token,
                cache_state_home(cache))
            self._lineage = LineageRecorder(
                self.dataset_token, self._lineage_policy,
                jsonl=manifest_jsonl, manifest_path=manifest_path,
                registry=self._telemetry,
                resume_state=self._resume_lineage)
            header = {
                'dataset_url': str(url_for_state),
                'seed': seed,
                'shuffle_row_groups': bool(shuffle_row_groups),
                'num_epochs': num_epochs,
                'pre_shuffles': pre_shuffles,
                'resumed': resume_state is not None,
                'cur_shard': cur_shard, 'shard_count': shard_count,
                'shard_seed': shard_seed,
                'drop_partitions': shuffle_row_drop_partitions,
                'items_per_epoch': len(items),
                # construction-order item list: what each epoch's reorder
                # permutes — [piece, fragment, rowgroup, row_range, drop],
                # coerced through the same canonicalization deliveries fold
                # with so replay and recording can never disagree on types
                'items': [[int(item['piece_index'])] + canonical_identity(
                    0, item['fragment_path'], item['row_group_id'],
                    item.get('row_range'),
                    item['shuffle_row_drop_partition'][0])[1:]
                    for item in items],
                # the sharded enumeration for the zero-read dataset
                # cross-check (footer metadata only)
                'shard_rowgroups': [
                    [str(rg.fragment_path),
                     int(rg.row_group_id)
                     if rg.row_group_id is not None else None,
                     int(rg.row_group_num_rows)]
                    for rg in shard_row_groups],
                'quarantined_fragments': sorted(
                    record.fragment_path
                    for record in construction_quarantine),
                'schedule': (self._cost_scheduler.plan_fingerprint()
                             if self._cost_scheduler is not None else None),
            }
            if self._topology is not None:
                # negotiated-topology provenance (parallel/topology.py):
                # written ONLY when armed so a static-shard recording stays
                # byte-identical to the seed manifest format
                header['topology'] = self._topology.header()
            if skip_by_iteration:
                header['skip_by_iteration'] = {
                    str(k): sorted(list(item) for item in v)
                    for k, v in skip_by_iteration.items()}
            self._lineage.write_header(header)

        max_in_flight = getattr(reader_pool, 'workers_count', 1) + _VENTILATE_EXTRA_ROWGROUPS
        self._ventilator = ConcurrentVentilator(
            ventilate_fn=_traced_ventilate(reader_pool.ventilate,
                                           self._lineage),
            items_to_ventilate=items,
            iterations=iterations,
            max_ventilation_queue_size=max_in_flight,
            randomize_item_order=shuffle_row_groups,
            random_seed=seed,
            pre_shuffle_count=pre_shuffles,
            skip_ids_by_iteration=skip_by_iteration,
            item_id_fn=_item_id,
            reset_iterations=num_epochs,
            tag_epoch=True,
            order_fn=order_fn)
        self._pool = reader_pool
        if (self._cost_scheduler is not None
                and hasattr(reader_pool, 'set_cost_hint_fn')):
            # service path: ship the measured cost with every submit so the
            # dispatcher's DRR charges real cost and routes heavy items to
            # the least-loaded workers (docs/performance.md)
            reader_pool.set_cost_hint_fn(self._cost_scheduler.cost_hint_for)
        if on_error == 'skip' and hasattr(reader_pool, 'set_hang_result_factory'):
            # Per-item-deadline watchdog hook (docs/robustness.md): when the pool
            # reaps a hung worker, the overdue rowgroup is quarantined — an empty
            # stand-in batch carrying a QuarantineRecord(reason='hang') rides the
            # normal delivery path, so consumption accounting stays exact.
            reader_pool.set_hang_result_factory(
                _make_hang_stand_in_factory(ngram))
        self._pool.start(RowGroupWorker, worker_setup, self._ventilator)

        if ngram is not None:
            self._results_reader = _NGramResultsReader(
                self.result_schema, ngram, on_batch=self._note_item_consumed,
                fast_forward=self._resume_fast_forward)
        elif is_batched_reader:
            self._results_reader = _BatchResultsReader(self.result_schema,
                                                       on_batch=self._note_item_consumed,
                                                       fast_forward=self._resume_fast_forward)
        else:
            self._results_reader = _RowResultsReader(self.result_schema,
                                                     on_batch=self._note_item_consumed,
                                                     fast_forward=self._resume_fast_forward)

        # Closed-loop autotuner (docs/autotuning.md): built only when asked —
        # the disabled path constructs nothing and mutates nothing.
        from petastorm_tpu.autotune.policy import resolve_policy
        autotune_policy = resolve_policy(autotune)
        if autotune_policy is not None:
            from petastorm_tpu.autotune.controller import setup_reader_autotune
            self._autotune = setup_reader_autotune(self, autotune_policy)
            self._autotune.start()

        # Incident autopsy plane (docs/observability.md "Incident autopsy
        # plane"): the black-box recorder subscribes to the failure edges the
        # pipeline already raises — breaker trips (both this process's board
        # and the worker-side sidecar states), hang reaps, quarantines, shm
        # CRC drops, SLO breach edges and lineage divergence — and captures
        # one rate-limited evidence bundle per edge.
        if self._incident_policy is not None:
            from petastorm_tpu.dataset_state import cache_state_home
            from petastorm_tpu.resilience import default_board
            from petastorm_tpu.telemetry.incident import (IncidentRecorder,
                                                          default_incident_home)
            url_for_incidents = dataset_url_or_urls if not isinstance(
                dataset_url_or_urls, list) else dataset_url_or_urls[0]
            self._incidents = IncidentRecorder(
                default_incident_home(cache_state_home(cache)),
                self._incident_policy, registry=self._telemetry)
            self._incidents.add_source('metrics', self.telemetry_snapshot)
            self._incidents.add_source(
                'slo', lambda: self._evaluate_slo(self.telemetry_snapshot()))
            self._incidents.add_source('breakers', self._breaker_evidence)
            self._incidents.add_source('quarantine', self.quarantine.as_dicts)
            if self._cost_scheduler is not None:
                self._incidents.add_source('costs',
                                           self._cost_scheduler.report)
            if self._lineage is not None:
                self._incidents.add_source('lineage', self._lineage.report)
            if self._autotune is not None:
                self._incidents.add_source('autotune', self._autotune.report)
            if self._topology is not None:
                self._incidents.add_source('topology', self._topology.report)
                # construction-time edges: a corrupt membership journal and
                # a reshard-survivor join are both capture-worthy evidence
                if self._topology.frames_dropped:
                    self._incidents.trigger(
                        'ledger_corrupt',
                        args={'journal': self._topology.journal.path,
                              'frames_dropped': self._topology.frames_dropped,
                              'plane': 'topology'})
                if self._topology.generation > 0:
                    self._incidents.trigger(
                        'host_reshard',
                        args={'generation': self._topology.generation,
                              'host_id': self._topology.host_id,
                              'assignment': list(self._topology.assignment)})
            provenance = {
                'dataset_url': str(url_for_incidents),
                'dataset_token': self.dataset_token,
                'seed': seed, 'num_epochs': num_epochs,
                'shuffle_row_groups': bool(shuffle_row_groups),
                'cur_shard': cur_shard, 'shard_count': shard_count,
                'topology': (self._topology.header()
                             if self._topology is not None else None),
                'on_error': on_error,
                'pool': type(reader_pool).__name__,
                'items_per_epoch': self._items_per_epoch,
            }
            self._incidents.add_source('config', lambda: provenance)
            default_board().observe_transitions(
                self._incidents.on_breaker_transition)
            self._slo.observe_breaches(self._on_slo_breach)

        # Longitudinal observatory (docs/observability.md "Longitudinal
        # observatory"): the historian appends one run record at stop();
        # the sentinel watches this run's own rows/s + wait-share series and
        # fires the edge-triggered perf_regression anomaly into the
        # incident plane on a mid-run collapse.
        if self._history_policy is not None:
            from petastorm_tpu.dataset_state import cache_state_home
            from petastorm_tpu.telemetry.history import (RunHistorian,
                                                         default_history_path,
                                                         fingerprint)
            from petastorm_tpu.telemetry.sentinel import (
                RegressionSentinel, resolve_sentinel_policy)
            url_for_history = dataset_url_or_urls if not isinstance(
                dataset_url_or_urls, list) else dataset_url_or_urls[0]
            history_path = (self._history_policy.path
                            or default_history_path(url_for_history,
                                                    cache_state_home(cache)))
            if history_path is not None:
                self._history = RunHistorian(history_path,
                                             self._history_policy,
                                             registry=self._telemetry)
            # the run's configuration identity, frozen now so the record
            # written at stop() attributes with construction-time truth
            self._history_fingerprints = {
                'config': fingerprint({
                    'seed': seed, 'num_epochs': num_epochs,
                    'shuffle_row_groups': bool(shuffle_row_groups),
                    'shuffle_rows': bool(shuffle_rows),
                    'cur_shard': cur_shard, 'shard_count': shard_count,
                    'on_error': on_error,
                    'pool': type(reader_pool).__name__,
                    'batched': bool(is_batched_reader),
                    'transform': transform_spec is not None,
                    'device_decode_fields': sorted(self.device_decode_fields),
                    'items_per_epoch': self._items_per_epoch}),
                'storage': (fingerprint(repr(self._storage_policy))
                            if self._storage_policy is not None else None),
                'schedule': (self._cost_scheduler.plan_fingerprint()
                             if self._cost_scheduler is not None else None),
            }
            sentinel_policy = resolve_sentinel_policy(
                self._history_policy.sentinel)
            if sentinel_policy is not None:
                self._sentinel = RegressionSentinel(
                    sentinel_policy, owner='reader',
                    registry=self._telemetry, incidents=self._incidents,
                    dataset_token=self.dataset_token)
                if self._incidents is not None:
                    self._incidents.add_source('sentinel',
                                               self._sentinel.report)
            if (self._autotune is not None and self._history is not None
                    and getattr(autotune_policy, 'warm_start', False)):
                self._warm_start_autotune()

        # Live metrics plane (docs/observability.md): one scrape endpoint
        # over this reader's cross-process snapshot; SLO gauges refresh per
        # scrape. Started last so a scrape can never observe a half-built
        # reader; stop() tears it down.
        if metrics_port is not None:
            from petastorm_tpu.telemetry.http_exporter import MetricsHttpServer
            self._metrics_server = MetricsHttpServer(
                snapshot_fn=self._scrape_snapshot,
                health_fn=self._scrape_health,
                port=int(metrics_port))
            self._metrics_server.start()

    # --------------------------------------------------------------- sharding

    @staticmethod
    def _partition_row_groups(row_groups, cur_shard, shard_count, shard_seed):
        """Deterministic modulo sharding, with optional seeded pre-shuffle so shards draw
        from the whole dataset (reference: petastorm/reader.py:570-594)."""
        if cur_shard is None:
            return list(row_groups)
        indexed = list(enumerate(row_groups))
        if shard_seed is not None:
            np.random.RandomState(shard_seed).shuffle(indexed)
        return [rg for index, (orig, rg) in enumerate(indexed)
                if index % shard_count == cur_shard]

    # --------------------------------------------------------------- iterator

    def __iter__(self):
        return self

    def __next__(self):
        if self._stopped:
            raise RuntimeError('Trying to read a sample from a stopped reader')
        try:
            # Serialized: the results reader buffers a batch across calls, and the
            # reference supports concurrent next() from many threads
            # (reference test_end_to_end.py:832-842) — per-row lock cost is noise
            # next to namedtuple assembly.
            with self._next_lock:
                result = self._results_reader.read_next(self._pool)
            return result
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration

    next = __next__

    def __len__(self):
        """Total rows in this shard per epoch (reference: reader.py:492-494)."""
        return sum(rg.row_group_num_rows for rg in self._shard_row_groups)

    def iter_columnar(self, include_empty=False):
        """Iterate raw :class:`ColumnarBatch` results straight off the worker pool —
        the zero-copy fast path for columnar consumers (JaxDataLoader), skipping the
        per-row namedtuple conversion of ``__next__``. Do not interleave with ``next()``.
        ``include_empty`` also yields zero-row batches (published so every work item is
        observable — delivery-exact checkpointing needs them).

        NGram readers yield WINDOW-major batches: each column is
        ``(num_windows, ngram.length, *field_shape)`` (``NGram.windows_as_arrays``) and
        ``num_rows`` counts windows. Window batches carry the piece's ``item_id``
        (zero-window pieces publish an empty batch to carry it), so checkpoint/resume
        and the device loaders' delivery accounting work for NGram exactly as for
        rows, with the window as the row unit (VERDICT r3 item 4)."""
        while True:
            if self._stopped:
                raise RuntimeError('Trying to read from a stopped reader')
            try:
                batch = self._pool.get_results()
            except EmptyResultError:
                self.last_row_consumed = True
                return
            if self.ngram is not None:
                # NGramWindows payload (shared columns + gather starts) -> dense
                # window-major arrays, one vectorized gather per column. item_id
                # rides along so delivery accounting / resume see the piece —
                # and so do the resilience/cache/telemetry sidecars, which
                # _note_item_consumed below accounts from this rebuilt batch.
                batch = ColumnarBatch(
                    self.ngram.windows_as_arrays(batch.columns, batch.starts),
                    len(batch.starts), item_id=batch.item_id,
                    retries=getattr(batch, 'retries', 0),
                    quarantine=getattr(batch, 'quarantine', None),
                    cache_hit=getattr(batch, 'cache_hit', None),
                    telemetry=getattr(batch, 'telemetry', None),
                    breakers=getattr(batch, 'breakers', None),
                    trace=getattr(batch, 'trace', None),
                    lineage=getattr(batch, 'lineage', None))
            self._note_item_consumed(batch)
            if self._resume_fast_forward and batch.item_id is not None:
                # Honor a row_cursor from a row-path checkpoint: skip the rows that
                # were already emitted before the checkpoint (exact-once everywhere).
                start = self._resume_fast_forward.pop(batch.item_id, 0)
                if start:
                    batch = _slice_batch(batch, start)
            if batch.num_rows or include_empty:
                yield batch

    def reset(self):
        """Re-ventilate for another ``num_epochs`` pass; only valid after full consumption
        (reference: reader.py:496-520)."""
        if not self.last_row_consumed:
            raise NotImplementedError('Currently reset() can only be called after the '
                                      'reader was fully consumed')
        self._results_reader.reset()
        self._ventilator.reset()
        self.last_row_consumed = False

    # ----------------------------------------------------------- checkpoint / resume

    def _note_item_consumed(self, batch):
        # Resilience sidecar first: retry/quarantine accounting applies to every result
        # (on_batch fires exactly once per published batch on every pool).
        record = getattr(batch, 'quarantine', None)
        if record is not None:
            self.quarantine.add(record)
            if self._incidents is not None:
                # black-box capture at the edge: a reaped hang and a skipped
                # rowgroup are distinct trigger kinds (distinct autopsy
                # causes), both carrying the (epoch, rowgroup, attempt)
                # coordinates of the failing item
                kind = ('watchdog_reap' if record.reason == 'hang'
                        else 'quarantine')
                self._incidents.trigger(
                    kind,
                    ctx=(record.epoch, record.piece_index, record.attempts),
                    args=record.as_dict())
        retries = getattr(batch, 'retries', 0)
        if retries:
            with self._accounting_lock:
                self._io_retries += retries
        cache_hit = getattr(batch, 'cache_hit', None)
        if cache_hit is not None:
            with self._accounting_lock:
                if cache_hit:
                    self._cache_hits += 1
                else:
                    self._cache_misses += 1
        stage_times = getattr(batch, 'telemetry', None)
        if stage_times:
            # cross-process span merge: the sidecar is a {stage: hist_snapshot}
            # dict (additive, so respawned workers merge like any other)
            self._telemetry.merge_stage_times(stage_times)
            if self._cost_scheduler is not None:
                # live cost feed (docs/performance.md "Cost-aware scheduling"):
                # a batch's sidecar holds the stage time of (mostly) its own
                # rowgroup — fold it into the live ledger persisted at stop()
                scheduled_id = getattr(batch, 'item_id', None)
                if scheduled_id is not None:
                    self._cost_scheduler.observe(scheduled_id[1], stage_times)
        breakers = getattr(batch, 'breakers', None)
        if breakers:
            opened = []
            with self._accounting_lock:
                if self._incidents is not None:
                    # worker-process breakers arrive as sidecar states, not
                    # callbacks: detect the closed→open edge against the
                    # last-seen state before folding the update in
                    opened = [
                        (name, state) for name, state in breakers.items()
                        if state.get('state') == 'open'
                        and (self._breaker_states.get(name) or {}).get(
                            'state') != 'open']
                self._breaker_states.update(breakers)
            for name, state in opened:
                self._incidents.trigger(
                    'breaker_open',
                    args={'breaker': name, 'snapshot': state})
        trace_sidecar = getattr(batch, 'trace', None)
        if trace_sidecar:
            # flight-recorder merge: the producing process's drained timeline
            # events land in this process's recorder, preserving their pid —
            # one dump_trace() then spans every process
            merge_trace_events(trace_sidecar)
        if self._incidents is not None:
            # poll-based edges, O(1) per batch: the process pool's CRC-drop
            # count and the lineage recorder's divergence count only ever
            # grow — a delta since the last batch IS the edge
            crc_failures = getattr(self._pool, '_shm_crc_failures', 0)
            if crc_failures > self._incident_last_crc_failures:
                self._incident_last_crc_failures = crc_failures
                self._incidents.trigger(
                    'shm_crc_drop',
                    args={'shm_crc_failures': crc_failures})
        if self._sentinel is not None:
            # live drift watch (docs/observability.md "Longitudinal
            # observatory"): one float compare per batch between windows;
            # the snapshot + evaluation only run when a window is due
            from petastorm_tpu.telemetry.slo import slo_clock
            if self._sentinel.due(slo_clock() - self._started_at):
                self._evaluate_slo(self.telemetry_snapshot())
        item_id = getattr(batch, 'item_id', None)
        if item_id is None:
            return
        if self._lineage is not None:
            # lineage delivery accounting (docs/observability.md "Sample
            # lineage"): exactly one deliver per work item on every pool —
            # the recorder folds it at its ventilation-order slot
            self._lineage.deliver(
                item_id, getattr(batch, 'num_rows', 0) or 0,
                fingerprint=getattr(batch, 'lineage', None),
                quarantined=record is not None)
            if self._incidents is not None:
                divergences = self._lineage.divergence_count
                if divergences > self._incident_last_divergence:
                    self._incident_last_divergence = divergences
                    self._incidents.trigger(
                        'lineage_divergence', ctx=item_id,
                        args={'divergence_count': divergences})
        epoch, piece, drop = item_id
        if trace_enabled():
            # consumer-side anchor of the rowgroup's trace: present on every
            # pool/transport, so a trace always ends on the consumer track
            trace_instant('rowgroup_consumed', ctx=(epoch, piece, 0),
                          args={'rows': getattr(batch, 'num_rows', 0)})
        if self._topology is not None:
            # journal the delivery under its GLOBAL rowgroup index — the set
            # a reshard subtracts to re-deal only the undelivered remainder
            # (docs/robustness.md "Elastic pod-scale sharding")
            self._topology.note_progress(epoch, piece, drop)
        with self._accounting_lock:
            self._rows_consumed += getattr(batch, 'num_rows', 0) or 0
            self._consumed_by_epoch.setdefault(epoch, set()).add((piece, drop))
            # Epochs complete strictly in order; results of later epochs accumulate in
            # their own sets until the earlier epoch's straggler items are popped.
            while (len(self._consumed_by_epoch.get(self._epochs_consumed, ()))
                   >= self._items_per_epoch):
                del self._consumed_by_epoch[self._epochs_consumed]
                self._epochs_consumed += 1

    def _load_resume_state(self, state):
        if not isinstance(state, dict) or state.get('version') != 1:
            raise ValueError('Unrecognized resume_state {!r}'.format(state))
        saved_shard = state.get('shard_config')
        if saved_shard is not None and saved_shard != self._shard_config:
            # Silent wrong-stream guard: a checkpoint replayed under a
            # different shard split skips/duplicates rows without any error
            # — refuse loudly, naming both configs (the split-plan refusal
            # discipline). Cross-topology restore goes through the
            # negotiated path only (topology.merge_topology_states).
            raise ValueError(
                'resume_state was captured under shard config {!r}, but '
                'this reader is configured with {!r} — resuming would '
                'silently replay the wrong row stream. Rebuild with the '
                'original sharding, or restore across topologies via '
                'petastorm_tpu.parallel.topology.merge_topology_states'
                .format(saved_shard, self._shard_config))
        saved_topology = state.get('topology')
        if saved_topology is not None:
            if self._topology is None:
                raise ValueError(
                    'resume_state was captured by a topology-armed reader '
                    '(identity {}/{}), but this reader is static-sharded — '
                    'restore through make_reader(topology=...) (see '
                    'topology.policy_from_state)'.format(
                        saved_topology.get('process_index'),
                        saved_topology.get('process_count')))
            if list(saved_topology.get('assignment') or []) != \
                    list(self._topology.assignment):
                raise ValueError(
                    'resume_state was dealt assignment {!r}, but this '
                    'reader negotiated {!r} — re-deal the checkpoint with '
                    'topology.merge_topology_states before resuming on a '
                    'changed topology'.format(
                        saved_topology.get('assignment'),
                        list(self._topology.assignment)))
        if state['items_per_epoch'] != self._items_per_epoch:
            raise ValueError(
                'resume_state was captured from a reader with {} work items per epoch, '
                'but this reader has {} — dataset contents, sharding, predicate, selector '
                'or shuffle_row_drop_partitions differ'
                .format(state['items_per_epoch'], self._items_per_epoch))
        self._epochs_consumed = int(state['epochs_consumed'])
        self._consumed_by_epoch = {
            self._epochs_consumed + int(offset): {tuple(item) for item in ids}
            for offset, ids in state['consumed_by_epoch'].items()}
        # lineage digest continuity (docs/observability.md): the chain value
        # + pending suffix saved by state_dict(), handed to the recorder so
        # the resumed run folds to the same digest as an uninterrupted one
        self._resume_lineage = state.get('lineage')
        cursor = state.get('row_cursor')
        if cursor is not None:
            # Replay the mid-batch position: the item is NOT in the consumed sets (its
            # batch was only partially emitted), so it re-ventilates in its epoch; the
            # row reader fast-forwards past the rows already emitted before checkpoint.
            key = (self._epochs_consumed + int(cursor['epoch_offset']),
                   int(cursor['piece']), int(cursor['drop']))
            self._resume_fast_forward[key] = int(cursor['next_row'])

    def state_dict(self):
        """Snapshot of the read position, resumable via ``make_reader(...,
        resume_state=state)`` with identical construction arguments.

        Granularity is the work item (rowgroup x drop-partition): an item counts as
        consumed once ALL of its rows have been emitted (``consumed_by_epoch`` maps
        epoch offsets to consumed items — several epochs can be partially consumed at
        once since completions interleave across epoch boundaries). A checkpoint taken
        mid-batch on the row path additionally records a ``row_cursor`` (item + next
        row index), and resume fast-forwards that item to the exact row — no rows are
        lost or duplicated (row-exact, provided in-batch row order is reproducible:
        either ``shuffle_rows=False`` or a fixed ``seed``; with ``shuffle_rows=True``
        and ``seed=None`` the partial batch is replayed in a new random order and
        resume is only item-exact). Results published by workers but not yet popped
        are re-read (at-least-once). Call from the consuming thread, between ``next()``
        calls. The reference has no analog (restart granularity is the epoch,
        SURVEY.md §5.4).

        NGram readers checkpoint identically with the WINDOW as the row unit: the
        cursor records the next window of the partially-emitted piece, and resume
        replays from that window (window-exact under a seeded shuffle, since the
        per-piece window order is then reproducible).
        """
        if (self._cost_scheduler is not None
                and self._cost_scheduler.split_count):
            # A split plan's work items carry row_range coordinates a resumed
            # reader cannot reconstruct (resume rejects cost_schedule, and an
            # unscheduled resume would match a parent piece id against the
            # unsplit item — silently skipping the rowgroup's other
            # sub-ranges). Refuse loudly rather than emit a checkpoint that
            # loses rows. Interleave-only plans (no splits) checkpoint fine:
            # their item coordinates are identical to an unscheduled reader's.
            raise ValueError(
                'state_dict() is not supported on a cost-scheduled reader '
                'whose plan split rowgroups ({} split(s)): the sub-range '
                'work-item coordinates cannot be resumed. Checkpoint with '
                'cost_schedule disabled, or a SchedulePolicy(split=False).'
                .format(self._cost_scheduler.split_count))
        lineage_state = None
        if self._lineage is not None:
            # taken OUTSIDE the accounting lock (the recorder has its own);
            # state_dict runs on the consuming thread between next() calls,
            # so no deliver can interleave with this snapshot
            lineage_state = self._lineage.state_dict()
        cursor = None
        if isinstance(self._results_reader, (_RowResultsReader, _NGramResultsReader)):
            # NGram: the work-item unit is identical; the cursor's row index counts
            # WINDOWS (the NGram path's row unit) instead of rows. Under _next_lock:
            # with concurrent next() threads, an unlocked read could catch the
            # last-row/acknowledge window mid-flight and snapshot a torn position.
            with self._next_lock:
                cursor = self._results_reader.cursor()
        with self._accounting_lock:
            state = {
                'version': 1,
                'items_per_epoch': self._items_per_epoch,
                'epochs_consumed': self._epochs_consumed,
                'consumed_by_epoch': {
                    epoch - self._epochs_consumed: sorted(ids)
                    for epoch, ids in self._consumed_by_epoch.items()},
                # the shard configuration this position is only valid under
                # — resume validates it and refuses a drifted config loudly
                'shard_config': dict(self._shard_config),
            }
            if self._topology is not None:
                # the negotiated identity + explicit global assignment that
                # cross-topology restore (topology.merge_topology_states)
                # re-deals onto a different host count
                state['topology'] = self._topology.state_block()
            if cursor is not None:
                (epoch, piece, drop), next_row = cursor
                # Deferred acknowledgment guarantees epoch >= _epochs_consumed: the
                # partially-emitted item is unconsumed, so its epoch cannot be closed.
                state['row_cursor'] = {'epoch_offset': epoch - self._epochs_consumed,
                                       'piece': piece, 'drop': drop,
                                       'next_row': next_row}
            if lineage_state is not None:
                # the chained-digest state (docs/observability.md "Sample
                # lineage"): a resumed reader seeded with it folds to the
                # exact digest of an uninterrupted run
                state['lineage'] = lineage_state
            return state

    @property
    def items_per_epoch(self):
        return self._items_per_epoch

    @property
    def io_retries(self):
        """Cumulative transient-IO retries spent by workers on this reader's behalf."""
        with self._accounting_lock:
            return self._io_retries

    @property
    def rows_consumed(self):
        """Cumulative rows delivered off the results channel (NGram: windows) —
        the autotuner's goodput numerator (docs/autotuning.md)."""
        with self._accounting_lock:
            return self._rows_consumed

    def autotune_report(self):
        """The closed-loop autotuner's state (docs/autotuning.md): windows,
        decision log, frozen-by-breaker flag, and current knob values/bounds —
        ``{'enabled': False}`` when the reader was built without
        ``autotune``."""
        if self._autotune is None:
            return {'enabled': False}
        return self._autotune.report()

    @property
    def telemetry(self):
        """The reader's consumer-side :class:`~petastorm_tpu.telemetry.MetricsRegistry`
        (worker sidecar merges land here); prefer :meth:`telemetry_snapshot` for
        the pool-inclusive view."""
        return self._telemetry

    def telemetry_snapshot(self):
        """One JSON-safe telemetry snapshot covering every process: the reader's
        registry (which absorbed the worker-sidecar stage times) merged with the
        pool's consumer-side registry (shm_map/shm_release/pool_wait,
        wire_bytes_copied). Feed it to
        :func:`petastorm_tpu.telemetry.analyze.attribute_bottleneck` or
        :func:`petastorm_tpu.telemetry.export.to_prometheus_text`."""
        from petastorm_tpu.telemetry import merge_snapshots
        pool_registry = getattr(self._pool, 'telemetry', None)
        storage_snapshot = None
        if self._storage_policy is not None:
            # the ingest engine's process-local counters (footer cache /
            # coalescing / hedging); armed-only so unarmed readers stay
            # byte-identical, and populated in-process for thread/dummy
            # pools (process-pool workers keep them worker-side, like the
            # other worker counters)
            from petastorm_tpu.storage import storage_metrics_snapshot
            storage_snapshot = storage_metrics_snapshot()
        if pool_registry is None and storage_snapshot is None:
            return self._telemetry.snapshot()
        return merge_snapshots(self._telemetry.snapshot(),
                               pool_registry.snapshot()
                               if pool_registry is not None else None,
                               storage_snapshot)

    # ------------------------------------------------------- efficiency SLO

    def _evaluate_slo(self, snapshot):
        from petastorm_tpu.telemetry.slo import slo_clock
        report = self._slo.evaluate(snapshot, slo_clock() - self._started_at,
                                    rows=self.rows_consumed,
                                    registry=self._telemetry)
        if self._sentinel is not None:
            # the regression sentinel windows the same cumulative series the
            # SLO report carries; it enforces its own min_window_s, so extra
            # evaluations (scrapes, diagnostics) cannot shrink a window
            self._sentinel.observe(report)
            self._sentinel.export_gauges()
        return report

    def efficiency_report(self):
        """One input-efficiency SLO evaluation over this reader's lifetime
        (docs/observability.md "Efficiency SLOs"): efficiency in [0, 1]
        derived from the recorded consumer wait spans (``pool_wait``, plus
        ``shuffle_wait``/``d2d_wait`` when a loader consumes this reader),
        the starvation fraction, goodput vs ideal rows/s, and the breach
        accounting (edge-triggered ``slo_breach`` counter / JSONL event /
        trace instant on each ok→breach transition). Also under
        ``diagnostics['slo']``; the ``slo_efficiency`` gauge refreshes in
        the telemetry registry on every call."""
        return self._evaluate_slo(self.telemetry_snapshot())

    # --------------------------------------------------------- cost profiler

    def cost_ledger(self, ledger=None):
        """Fold the flight recorder's per-rowgroup span history for this
        reader into a :class:`~petastorm_tpu.telemetry.cost_model.CostLedger`
        (docs/observability.md "Cost profiler"). Requires tracing to have
        been armed for the read (``trace=True`` / ``PETASTORM_TPU_TRACE=1``)
        — an unarmed read yields an empty ledger. ``ledger`` continues an
        existing ledger (same dataset token); the default starts a fresh one
        keyed by :attr:`dataset_token`. The one-command form is
        ``petastorm-tpu-throughput costs <dataset_url>``."""
        from petastorm_tpu.telemetry.cost_model import CostLedger
        from petastorm_tpu.telemetry.tracing import trace_snapshot
        if ledger is None:
            ledger = CostLedger(self.dataset_token)
        # the piece locator covers the virtual pieces of split rowgroups too,
        # so a scheduled read attributes sub-range costs to the parent rowgroup
        ledger.ingest_trace(trace_snapshot(), dict(self._piece_locator))
        return ledger

    # ------------------------------------------------------- lineage audit

    def order_digest(self):
        """The chained sample-lineage order digest over every item delivered
        so far (docs/observability.md "Sample lineage & determinism audit"):
        a hex string identical across dummy/thread/process/service pools for
        the same seed + shard config + schedule plan, and invariant under
        worker respawns/redeliveries. None when the reader was built without
        ``lineage``."""
        if self._lineage is None:
            return None
        return self._lineage.order_digest()

    # ----------------------------------------------- incident autopsy plane

    def _breaker_evidence(self):
        """The bundle's ``breakers`` source: worker-sidecar states merged
        with this process's board (same merge ``diagnostics`` performs)."""
        from petastorm_tpu.resilience import default_board
        with self._accounting_lock:
            breakers = dict(self._breaker_states)
        breakers.update(default_board().snapshot())
        return breakers

    def _on_slo_breach(self, report):
        """SLO ok→breach edge observer → one ``slo_breach`` incident."""
        if self._incidents is not None:
            self._incidents.trigger(
                'slo_breach',
                args={'efficiency': report.get('efficiency'),
                      'target': report.get('target_efficiency'),
                      'wait_seconds': report.get('wait_seconds')})

    def incident_report(self):
        """The incident recorder's summary — capture/rate-limit counters and
        the retained bundle names (docs/observability.md "Incident autopsy
        plane"); None when the reader was built without ``incidents``."""
        if self._incidents is None:
            return None
        return self._incidents.report()

    # ------------------------------------------- longitudinal observatory

    def build_history_record(self):
        """The structured run record this reader would append at ``stop()``
        (docs/observability.md "Longitudinal observatory"): fingerprints,
        headline rows/s + efficiency, per-stage time shares, storage
        counters, incident/quarantine counts. None when built without
        ``history``. Knob values are read live, so call before ``stop()``
        restores the autotuner's knobs to see what the run actually ran
        with."""
        if self._history_policy is None:
            return None
        from petastorm_tpu.telemetry.history import build_run_record, fingerprint
        from petastorm_tpu.telemetry.slo import (efficiency_from_snapshot,
                                                 slo_clock)
        elapsed = slo_clock() - self._started_at
        snapshot = self.telemetry_snapshot()
        rows = self.rows_consumed
        slo_report = efficiency_from_snapshot(snapshot, elapsed, rows=rows)
        knobs = {}
        try:
            from petastorm_tpu.autotune.knobs import build_reader_knobs
            knobs = {knob.knob_id: float(knob.get())
                     for knob in build_reader_knobs(self)}
        except Exception:  # noqa: BLE001 - the record is advisory; a dead knob target must not fail stop()
            logger.debug('history: knob capture failed', exc_info=True)
        fingerprints = dict(self._history_fingerprints)
        fingerprints['knobs'] = fingerprint(knobs) if knobs else None
        cost_skew = None
        if self._cost_scheduler is not None:
            cost_skew = self._cost_scheduler.cost_skew()
        return build_run_record(
            'reader', self.dataset_token, elapsed, rows,
            snapshot=snapshot, slo_report=slo_report,
            fingerprints=fingerprints, knobs=knobs,
            incidents=self.incident_report(),
            quarantined=len(self.quarantine), cost_skew=cost_skew)

    def _warm_start_autotune(self):
        """``AutotunePolicy(warm_start=True)``: seed the live knobs from the
        newest same-token, same-platform run record before the controller's
        first window, so this run starts from last run's converged values
        instead of re-climbing from the defaults. Gated off — with a debug
        line, never an error — when the store holds no comparable record
        (first run, or the platform changed)."""
        from petastorm_tpu.telemetry.history import (last_good_record,
                                                     load_records,
                                                     run_platform)
        try:
            records, _dropped = load_records(self._history.path)
            record = last_good_record(records, self.dataset_token,
                                      run_platform())
            if record is None:
                logger.debug('autotune warm start: no comparable run record '
                             'in %s; starting from defaults',
                             self._history.path)
                return
            applied = self._autotune.warm_start(record.get('knobs') or {})
            if applied:
                logger.info('autotune warm start: seeded %s from the run '
                            'recorded at %s',
                            {k: v['to'] for k, v in applied.items()},
                            record.get('recorded_unix_s'))
        except Exception:  # noqa: BLE001 - warm start is an optimization; failure means defaults, not a dead reader
            logger.warning('autotune warm start failed; starting from '
                           'defaults', exc_info=True)

    def _write_history_record(self):
        """Append this run's record to the longitudinal store — called from
        ``stop()`` BEFORE the autotuner restores its knobs (the record must
        capture the values the run actually ran with). Idempotent."""
        if self._history is None or self._history_written:
            return
        self._history_written = True
        try:
            record = self.build_history_record()
            if record is not None:
                self._history.append(record)
        except Exception:  # noqa: BLE001 - the historian is advisory; a read that succeeded must not fail over its memory
            logger.warning('could not record this run in the history store',
                           exc_info=True)

    def history_report(self):
        """The historian's store status (path, appended count, dropped
        frames); None when the reader was built without ``history``."""
        if self._history is None:
            return None
        return self._history.state()

    # ------------------------------------------------------- metrics plane

    def _snapshot_with_slo(self):
        """One telemetry snapshot (built ONCE — the cross-process merge is
        the expensive half) evaluated against the SLO, with the fresh
        ``slo_*`` gauges spliced in; returns ``(snapshot, slo_report)``."""
        snapshot = self.telemetry_snapshot()
        report = self._evaluate_slo(snapshot)
        gauges = snapshot.setdefault('gauges', {})
        if report['efficiency'] is not None:
            gauges['slo_efficiency'] = report['efficiency']
        gauges['slo_target_efficiency'] = report['target_efficiency']
        if self._lineage is not None:
            # the /metrics view of the audit plane: fold progress + reorder-
            # buffer depth (the lineage_divergence counter rides the
            # registry's counters like any other)
            lineage = self._lineage.report()
            gauges['lineage_items_folded'] = lineage['items_folded']
            gauges['lineage_pending_items'] = lineage['pending_items']
        if self._sentinel is not None:
            # the smoothed drift series (sentinel_rate_ewma /
            # sentinel_wait_share_ewma) ride the same scrape
            gauges.update(self._sentinel.gauges())
        # the SLO tracker's trailing ring buffer rides the /vars document
        # (a list, not a gauge — the text scrape ignores it)
        snapshot['slo_history'] = report.get('history', [])
        return snapshot, report

    def _scrape_snapshot(self):
        """The /metrics endpoint's per-scrape snapshot (SLO gauges fresh)."""
        snapshot, _report = self._snapshot_with_slo()
        return snapshot

    def _scrape_health(self):
        """The ``/healthz`` fields for this reader's endpoint."""
        return {'rows_consumed': self.rows_consumed,
                'stopped': self._stopped,
                'rowgroups_quarantined': len(self.quarantine)}

    @property
    def metrics_url(self):
        """The live scrape endpoint base URL, or None when the reader was
        built without ``metrics_port`` (docs/observability.md)."""
        if self._metrics_server is None:
            return None
        return self._metrics_server.url

    # --------------------------------------------------------- flight recorder

    def dump_trace(self, path=None):
        """Export the flight recorder as Chrome-trace/Perfetto JSON
        (docs/observability.md "Flight recorder"): every event this process
        recorded plus the worker events merged off the ``trace`` batch
        sidecars — per-process tracks, stage slices, anomaly instants, and
        worker→consumer flow arrows per rowgroup. Writes to ``path`` when
        given; returns the trace dict either way (load it at
        https://ui.perfetto.dev). Requires tracing to have been armed for the
        read (``trace=True`` / ``PETASTORM_TPU_TRACE=1``) — otherwise the
        trace is empty."""
        from petastorm_tpu.telemetry.trace_export import (to_chrome_trace,
                                                          write_chrome_trace)
        from petastorm_tpu.telemetry.tracing import trace_snapshot
        snapshot = trace_snapshot()
        if path is not None:
            return write_chrome_trace(path, snapshot)
        return to_chrome_trace(snapshot)

    def trace_summary(self):
        """The non-visual flight-recorder view (doctor/bench embed it): event
        counts, dropped-event count, anomaly instants, and the top-5 longest
        rowgroup traces — see
        :func:`petastorm_tpu.telemetry.trace_export.summarize_trace`."""
        from petastorm_tpu.telemetry.trace_export import summarize_trace
        from petastorm_tpu.telemetry.tracing import trace_snapshot
        return summarize_trace(trace_snapshot())

    # ------------------------------------------------------------- lifecycle

    def stop(self):
        self._stopped = True
        if self._metrics_server is not None:
            # the scrape plane goes first: a scrape against a tearing-down
            # pool would race the very state it reports
            self._metrics_server.stop()
        # the longitudinal run record is written BEFORE the autotuner stops:
        # autotune.stop() restores the pre-tuning knob values, and the
        # record must capture what the run actually ran with
        self._write_history_record()
        if self._autotune is not None:
            # the controller must stop turning knobs before the pool they
            # actuate starts tearing down
            self._autotune.stop()
        if self._cost_scheduler is not None:
            # hand this run's live cost observations to the next one
            # (best-effort: a read must never fail over its bookkeeping)
            try:
                self._cost_scheduler.persist()
            except Exception:  # noqa: BLE001 - ledger persistence is advisory; the read itself already succeeded
                logger.warning('could not persist the cost ledger',
                               exc_info=True)
        if self._lineage is not None:
            # flush the final manifest record (idempotent; the JSONL logger
            # swallows its own write failures)
            self._lineage.close()
        if self._incidents is not None:
            # the recorder only detaches its sources — retained bundles are
            # the whole point and stay on disk for the autopsy CLI
            self._incidents.close()
        if self._topology is not None:
            # journal a clean leave so survivors re-deal immediately rather
            # than waiting out the lease (idempotent)
            self._topology.close()
        self._pool.stop()

    def join(self):
        self._pool.join()

    def cleanup(self):
        pass

    @property
    def diagnostics(self):
        """Pool counters plus the resilience view: cumulative transient-IO retries and
        the quarantine ledger (always present, so dashboards can alert on non-zero
        values without key-existence checks)."""
        diag = dict(self._pool.diagnostics)
        with self._accounting_lock:
            diag['io_retries'] = self._io_retries
            diag['cache_hits'] = self._cache_hits
            diag['cache_misses'] = self._cache_misses
        # In-process cache counters (exact for thread/dummy pools; for the process
        # pool each worker keeps its own copy, so the per-batch cache_hits/misses
        # above are the cross-process aggregate).
        cache_stats = getattr(self._cache, 'stats', None)
        if cache_stats is not None:
            diag['cache'] = dict(cache_stats)
        diag['rowgroups_quarantined'] = len(self.quarantine)
        diag['quarantine'] = self.quarantine.as_dicts()
        # Circuit-breaker states (docs/robustness.md): worker-process breakers
        # (cache/filesystem, via the results-channel sidecar) + this process's
        # board (exact for thread/dummy pools) + the process pool's shm breaker.
        # Healthy (never-tripped, closed) breakers are omitted — an empty dict
        # means everything is closed.
        from petastorm_tpu.resilience import default_board
        with self._accounting_lock:
            breakers = dict(self._breaker_states)
        breakers.update(default_board().snapshot(only_tripped=True))
        shm_breaker = diag.get('shm_breaker')
        if shm_breaker is not None and (
                shm_breaker.get('failures') or shm_breaker.get('opened_count')
                or shm_breaker.get('state') != 'closed'):
            breakers['shm_transport'] = shm_breaker
        diag['breakers'] = breakers
        # One cross-process telemetry snapshot (docs/observability.md): per-stage
        # latency histograms merged from every worker sidecar + the pool
        # registry — built once and shared with the SLO evaluation (which
        # splices its fresh gauges back in).
        snapshot, slo_report = self._snapshot_with_slo()
        diag['slo'] = slo_report
        diag['telemetry'] = snapshot
        # Flight-recorder summary, only while tracing is armed (the summary of
        # an empty recorder would just be noise in every dashboard).
        if trace_enabled():
            diag['trace'] = self.trace_summary()
        # Autotune block only when a controller exists: the disabled path's
        # diagnostics stay byte-identical to the seed.
        if self._autotune is not None:
            diag['autotune'] = self._autotune.report()
        # Cost-aware schedule block only when armed, same contract.
        if self._cost_scheduler is not None:
            diag['schedule'] = self._cost_scheduler.report()
        # Lineage audit block only when armed, same contract.
        if self._lineage is not None:
            diag['lineage'] = self._lineage.report()
        # Incident autopsy block only when armed, same contract.
        if self._incidents is not None:
            diag['incidents'] = self._incidents.report()
        # Longitudinal observatory blocks only when armed, same contract.
        if self._history is not None:
            diag['history'] = self._history.state()
        if self._sentinel is not None:
            diag['sentinel'] = self._sentinel.report()
        # Storage ingest-engine block only when armed, same contract: the
        # counter roll-up doctor and dashboards read (footer-cache hits,
        # ranges coalesced, hedges fired/won — docs/performance.md
        # "Object-store ingest engine").
        if self._storage_policy is not None:
            counters = snapshot.get('counters') or {}
            diag['storage'] = {
                'policy': {
                    'coalesce_gap_bytes':
                        self._storage_policy.coalesce_gap_bytes,
                    'max_in_flight': self._storage_policy.max_in_flight,
                    'hedge_enabled': self._storage_policy.hedge_enabled,
                },
                'footer_cache_hits':
                    int(counters.get('storage_footer_cache_hit', 0)),
                'footer_cache_misses':
                    int(counters.get('storage_footer_cache_miss', 0)),
                'ranges_coalesced':
                    int(counters.get('storage_ranges_coalesced', 0)),
                'hedges_fired':
                    int(counters.get('storage_hedge_fired', 0)),
                'hedges_won':
                    int(counters.get('storage_hedge_won', 0)),
            }
        # Degenerate-sharding detector, only when one fired at construction
        # (docs/robustness.md): shard_count/rowgroups/empty_shards.
        if self._shard_skew is not None:
            diag['shard_skew'] = dict(self._shard_skew)
        # Elastic-topology block only when armed, same contract: negotiated
        # identity, assignment, membership-journal state, stale leases.
        if self._topology is not None:
            diag['topology'] = self._topology.report()
        return diag

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()


def _item_id(item):
    """Stable identity of a ventilated work item for consumption accounting."""
    return (item['piece_index'], item['shuffle_row_drop_partition'][0])


def _traced_ventilate(pool_ventilate, lineage=None):
    """Wrap a pool's ``ventilate`` so each work item's birth lands on the
    flight-recorder timeline (docs/observability.md "Flight recorder"): the
    ``ventilate`` instant is the causal origin of a rowgroup's trace — the
    ``(epoch, rowgroup)`` context every later span inherits starts here. One
    enabled-check per item when tracing is off.

    ``lineage`` (a :class:`~petastorm_tpu.telemetry.lineage.LineageRecorder`)
    additionally records each item's EXPECTED position: ventilation order is
    the fold order of the chained order digest, which is why the digest is
    identical across pools whose completion order is not."""
    def ventilate(**kwargs):
        piece = kwargs.get('piece_index')
        if trace_enabled() and piece is not None:
            trace_instant('ventilate',
                          ctx=(int(kwargs.get('epoch_index', 0)),
                               int(piece), 0))
        if lineage is not None and piece is not None:
            lineage.expect(int(kwargs.get('epoch_index', 0)), int(piece),
                           int(kwargs['shuffle_row_drop_partition'][0]),
                           str(kwargs.get('fragment_path', '')),
                           kwargs.get('row_group_id'),
                           kwargs.get('row_range'))
        pool_ventilate(**kwargs)
    return ventilate


def _make_hang_stand_in_factory(ngram):
    """Build the pool's hang-quarantine hook (docs/robustness.md): maps a
    reaped item's ventilated kwargs to the empty stand-in batch (row or NGram
    shape) carrying its ``QuarantineRecord(reason='hang')``."""
    def factory(item_kwargs, elapsed_s):
        from petastorm_tpu.resilience import QuarantineRecord
        epoch = int(item_kwargs.get('epoch_index', 0))
        piece_index = int(item_kwargs['piece_index'])
        item_id = (epoch, piece_index,
                   item_kwargs['shuffle_row_drop_partition'][0])
        record = QuarantineRecord(
            piece_index=piece_index,
            fragment_path=item_kwargs.get('fragment_path', ''),
            row_group_id=item_kwargs.get('row_group_id'),
            error_type='WorkerHangError',
            error='no result after {:.3g}s; the worker holding this rowgroup '
                  'was reaped by the watchdog'.format(elapsed_s),
            attempts=1, epoch=epoch, reason='hang')
        # anomaly marker (consumer side — the hung worker can't publish one)
        trace_instant('quarantine', ctx=(epoch, piece_index, 0),
                      args={'reason': 'hang',
                            'elapsed_s': round(elapsed_s, 3)})
        if ngram is not None:
            from petastorm_tpu.ngram_worker import NGramWindows
            return NGramWindows({}, np.empty(0, np.int64), item_id=item_id,
                                quarantine=record)
        return ColumnarBatch({}, 0, item_id=item_id, quarantine=record)
    return factory


def _slice_batch(batch, start):
    """Drop the first ``start`` rows of a ColumnarBatch (row-cursor fast-forward)."""
    from petastorm_tpu.reader_worker import ColumnarBatch
    n = max(batch.num_rows - start, 0)
    return ColumnarBatch({name: col[start:] for name, col in batch.columns.items()},
                         n, item_id=batch.item_id)


def _apply_field_overrides(schema, field_overrides):
    by_name = {f.name: f for f in field_overrides}
    unknown = sorted(set(by_name) - set(schema.fields))
    if unknown:
        raise ValueError('field_overrides name fields not in the schema: {}'
                         .format(unknown))
    return Unischema(schema.name,
                     [by_name.get(name, field) for name, field in schema.fields.items()])


def _is_ngram(schema_fields):
    from petastorm_tpu.ngram import NGram
    return isinstance(schema_fields, NGram)


def _eval_partition_predicate(predicate, row_group):
    values = {name: value for name, value in row_group.partition_keys.items()}
    return bool(predicate.do_include(values))


# ---------------------------------------------------------------------------
# Results-queue readers (reference: py_dict_reader_worker.py:66-99,
# arrow_reader_worker.py:31-88)
# ---------------------------------------------------------------------------

class _RowResultsReader(object):
    """Buffers a ColumnarBatch and pops one namedtuple per read (row-at-a-time API).

    Hot loop: rows are emitted positionally (``namedtuple._make`` over columns
    pre-ordered once per batch) — profiling shows dict-based per-row assembly costs
    ~4x the actual decode at small row sizes.

    Consumption accounting is row-exact: ``on_batch`` is invoked only once the LAST row
    of a batch has been emitted (not when the batch is popped off the queue), so a
    checkpoint taken mid-batch leaves the item unconsumed and :meth:`cursor` pinpoints
    the resume row. ``fast_forward`` maps ``item_id -> start_row`` for replaying such a
    cursor: the matching batch starts emitting at ``start_row`` instead of 0."""

    def __init__(self, result_schema, on_batch=None, fast_forward=None):
        self._namedtuple = result_schema.namedtuple
        self._field_names = list(result_schema.fields)
        self._on_batch = on_batch
        self._fast_forward = dict(fast_forward or {})
        self._columns = None
        self._num_rows = 0
        self._next_row = 0
        self._current_batch = None

    def read_next(self, pool):
        while self._columns is None or self._next_row >= self._num_rows:
            batch = pool.get_results()
            item_id = getattr(batch, 'item_id', None)
            start_row = self._fast_forward.pop(item_id, 0) if item_id is not None else 0
            if batch.num_rows == 0 or start_row >= batch.num_rows:
                # Nothing (left) to emit: consumed the moment it is popped.
                if self._on_batch is not None:
                    self._on_batch(batch)
                self._columns = None
                continue
            self._columns = [batch.columns[name] for name in self._field_names]
            self._num_rows = batch.num_rows
            self._next_row = start_row
            self._current_batch = batch
        i = self._next_row
        self._next_row = i + 1
        if self._next_row >= self._num_rows and self._on_batch is not None:
            # Acknowledge consumption only now that every row has been emitted
            # (at-least-once semantics; ADVICE.md round 1).
            self._on_batch(self._current_batch)
        return self._namedtuple._make([col[i] for col in self._columns])

    def cursor(self):
        """``(item_id, next_row)`` of the partially-emitted buffered batch, or None."""
        if self._columns is not None and self._next_row < self._num_rows:
            item_id = getattr(self._current_batch, 'item_id', None)
            if item_id is not None:
                return item_id, self._next_row
        return None

    def reset(self):
        self._columns = None
        self._num_rows = 0
        self._next_row = 0
        self._current_batch = None


class _BatchResultsReader(object):
    """Emits one namedtuple-of-arrays per rowgroup batch. A ``fast_forward`` map (from a
    row-path checkpoint's ``row_cursor``) slices the matching batch so already-emitted
    rows are not re-delivered."""

    def __init__(self, result_schema, on_batch=None, fast_forward=None):
        self._schema = result_schema
        self._on_batch = on_batch
        self._fast_forward = fast_forward if fast_forward is not None else {}

    def read_next(self, pool):
        while True:
            batch = pool.get_results()
            if self._on_batch is not None:
                self._on_batch(batch)
            if self._fast_forward and batch.item_id is not None:
                start = self._fast_forward.pop(batch.item_id, 0)
                if start:
                    batch = _slice_batch(batch, start)
            if batch.num_rows:
                # restrict to schema fields: ship-raw batches carry auxiliary
                # __hw/__enc columns the namedtuple has no slots for
                return self._schema.make_namedtuple(
                    **{name: batch.columns[name] for name in self._schema.fields})

    def reset(self):
        pass


class _NGramResultsReader(object):
    """Buffers a columnar NGramWindows payload and emits one {offset: namedtuple} per
    read, gathering rows lazily from the shared columns (no per-row dict
    materialization on the hot path).

    Checkpoint contract mirrors :class:`_RowResultsReader` with the window as the
    row unit: ``on_batch`` acknowledges a payload only once its LAST window has been
    emitted (zero-window payloads acknowledge on pop), ``cursor()`` pinpoints a
    partially-emitted payload's next window, and ``fast_forward`` replays a resumed
    payload from that window (window-exact when the per-piece shuffle is seeded)."""

    def __init__(self, result_schema, ngram, on_batch=None, fast_forward=None):
        self._ngram = ngram
        self._on_batch = on_batch
        self._fast_forward = dict(fast_forward or {})
        self._payload = None
        self._plan = None
        self._plan_columns = None
        self._next = 0

    def read_next(self, pool):
        while self._payload is None or self._next >= len(self._payload.starts):
            payload = pool.get_results()
            item_id = getattr(payload, 'item_id', None)
            start = self._fast_forward.pop(item_id, 0) if item_id is not None else 0
            if not len(payload.starts) or start >= len(payload.starts):
                # Nothing (left) to emit: consumed the moment it is popped.
                if self._on_batch is not None:
                    self._on_batch(payload)
                self._payload = None
                continue
            self._payload = payload
            self._next = start
            columns_key = frozenset(self._payload.columns)
            if columns_key != self._plan_columns:
                # one plan per column set (constant per reader) — not per window
                self._plan = self._ngram.window_plan(columns_key)
                self._plan_columns = columns_key
        start = self._payload.starts[self._next]
        self._next += 1
        if self._next >= len(self._payload.starts) and self._on_batch is not None:
            # Acknowledge only now that every window has been emitted
            # (at-least-once semantics, same as the row path).
            self._on_batch(self._payload)
        return self._ngram.window_from_plan(self._payload.columns, start, self._plan)

    def cursor(self):
        """``(item_id, next_window)`` of the partially-emitted payload, or None."""
        if self._payload is not None and self._next < len(self._payload.starts):
            item_id = getattr(self._payload, 'item_id', None)
            if item_id is not None:
                return item_id, self._next
        return None

    def reset(self):
        self._payload = None
        self._next = 0
