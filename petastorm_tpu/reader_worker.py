"""The rowgroup worker: loads one Parquet rowgroup, applies predicate/decode/shuffle/
transform, and publishes a columnar batch.

Re-design of the reference's two worker classes (petastorm/py_dict_reader_worker.py and
petastorm/arrow_reader_worker.py) as ONE columnar pipeline: data stays as Arrow/numpy
columns end-to-end (TPU-first — the device layer consumes host-contiguous arrays), and the
row-dict path is a view over the columnar result produced by the results-queue reader.

Pipeline per rowgroup (reference call stack: SURVEY.md §3.2):
  load columns (two-phase when a predicate is present) -> decode codec columns ->
  in-rowgroup seeded shuffle -> shuffle-row-drop partition slice -> TransformSpec ->
  publish ColumnarBatch
"""

import hashlib
import logging
import os
import re
import time

import numpy as np
import pyarrow.dataset as pads

from petastorm_tpu import decode_engine
from petastorm_tpu.cache import NullCache
from petastorm_tpu.telemetry.spans import (drain_stage_times, record_stage,
                                           stage_span)
from petastorm_tpu.telemetry.tracing import (clear_trace_context,
                                             current_dispatch_attempt,
                                             drain_trace_events,
                                             set_trace_context, trace_instant)
from petastorm_tpu.transform import transform_schema
from petastorm_tpu.workers.serializers import _columns_num_rows
from petastorm_tpu.workers.worker_base import WorkerBase

logger = logging.getLogger(__name__)

#: per-path-prefix filesystem breaker defaults (docs/robustness.md): the
#: threshold sits well above one rowgroup's retry budget — a single poisoned
#: file exhausting its attempts must not open the breaker for its whole
#: directory; a *mount-wide* stall (every open failing) crosses it in one or
#: two pieces
FS_BREAKER_THRESHOLD = 10
FS_BREAKER_RECOVERY_S = 30.0


class ColumnarBatch(object):
    """Decoded columns of (a partition of) one rowgroup: ``{field_name: ndarray | list}``.
    Arrays are ``(n,) + field.shape`` when shapes are uniform; ragged fields stay as lists
    of per-row arrays. ``item_id`` identifies the ventilated work item
    ``(piece_index, drop_partition)`` that produced this batch — the unit of the reader's
    checkpoint/resume accounting (empty batches are published solely to carry it).

    Resilience sidecar (docs/robustness.md): ``retries`` counts transient-IO retries the
    worker spent producing this batch (zero on the fault-free path); ``quarantine`` is a
    :class:`~petastorm_tpu.resilience.QuarantineRecord` when this batch stands in for a
    rowgroup skipped under ``on_error='skip'`` (such batches are empty — the record rides
    the results channel so the ledger works identically across all pools).

    ``cache_hit`` is the cache-observability sidecar: True when this batch was served
    from the rowgroup cache, False on a miss that filled it, None when no cache applied
    (NullCache, unpicklable predicate bypass, quarantined/ngram stand-ins). It rides
    the results channel like ``retries`` so ``Reader.diagnostics`` counts hits/misses
    identically across all pools.

    ``telemetry`` is the stage-span sidecar (docs/observability.md): a JSON-safe
    ``{stage: histogram_snapshot}`` of the time this worker spent per pipeline
    stage since its previous publish, drained from the process-local
    :class:`~petastorm_tpu.telemetry.spans.StageRecorder`. It rides the results
    channel like ``cache_hit`` and merges into the consumer-side registry — one
    ``Reader.telemetry_snapshot()`` covers all processes.

    ``breakers`` is the circuit-breaker sidecar (docs/robustness.md): the
    producing process's tripped-breaker states (``{name: state_dict}`` from its
    :func:`~petastorm_tpu.resilience.default_board`), or None when every breaker
    is healthy — how worker-process cache/filesystem breaker states reach
    ``Reader.diagnostics['breakers']`` across the process boundary.

    ``trace`` is the flight-recorder sidecar (docs/observability.md "Flight
    recorder"): the producing process's drained trace events
    (``{'pid', 'events', 'dropped'}`` from
    :func:`~petastorm_tpu.telemetry.tracing.drain_trace_events`), or None when
    tracing is off — how worker-side timeline events reach the consumer's
    recorder so one ``Reader.dump_trace()`` spans every process.

    ``lineage`` is the sample-lineage sidecar (docs/observability.md "Sample
    lineage & determinism audit"): the producing worker's sampled content
    fingerprint (``{'crc32', 'fields'}`` from
    :func:`~petastorm_tpu.telemetry.lineage.content_fingerprint`), or None
    when sampling is off / this piece was not sampled — computed where the
    batch is PRODUCED (in-process, spawned, or service-fleet worker) so a
    bit flipped anywhere downstream shows up as a cross-run mismatch."""

    __slots__ = ('columns', 'num_rows', 'item_id', 'retries', 'quarantine',
                 'cache_hit', 'telemetry', 'breakers', 'trace', 'lineage')

    def __init__(self, columns, num_rows, item_id=None, retries=0, quarantine=None,
                 cache_hit=None, telemetry=None, breakers=None, trace=None,
                 lineage=None):
        self.columns = columns
        self.num_rows = num_rows
        self.item_id = item_id
        self.retries = retries
        self.quarantine = quarantine
        self.cache_hit = cache_hit
        self.telemetry = telemetry
        self.breakers = breakers
        self.trace = trace
        self.lineage = lineage


class WorkerSetup(object):
    """Immutable per-reader configuration shipped to every worker."""

    __slots__ = ('dataset_path_or_paths', 'filesystem_factory', 'schema', 'fields_to_read',
                 'result_schema', 'transform_spec', 'batched_output', 'decode', 'ngram',
                 'cache', 'shuffle_rows', 'seed', 'partition_field_names', 'dataset_token',
                 'on_error', 'retry_policy', 'device_decode_fields',
                 'lineage_fingerprint_every', 'storage_policy')

    def __init__(self, dataset_path_or_paths, filesystem_factory, schema, fields_to_read,
                 transform_spec=None, batched_output=False, decode=True, ngram=None,
                 cache=None, shuffle_rows=False, seed=None, partition_field_names=(),
                 on_error='raise', retry_policy=None, device_decode_fields=(),
                 lineage_fingerprint_every=0, storage_policy=None):
        from petastorm_tpu.resilience import resolve_retry_policy
        self.on_error = on_error
        # One normalization for the whole stack: 'raise' means today's exact behavior
        # (no retry even of transient faults), other modes get the given or default
        # policy.
        self.retry_policy = resolve_retry_policy(on_error, retry_policy)
        self.dataset_path_or_paths = dataset_path_or_paths
        self.filesystem_factory = filesystem_factory
        self.schema = schema
        self.fields_to_read = list(fields_to_read)
        self.transform_spec = transform_spec
        self.batched_output = batched_output
        self.decode = decode
        self.ngram = ngram
        self.cache = cache or NullCache()
        self.shuffle_rows = shuffle_rows
        self.seed = seed
        self.partition_field_names = set(partition_field_names)
        #: fields whose payloads skip host decode and ship raw to the device
        #: loader (docs/performance.md "Device-resident decode tail")
        self.device_decode_fields = frozenset(device_decode_fields)
        #: sample-lineage content-fingerprint cadence (docs/observability.md
        #: "Sample lineage"): pieces with ``piece_index % N == 0`` hash their
        #: column buffers into the batch's ``lineage`` sidecar; 0 = off.
        #: A pure function of the piece identity, so every pool and the
        #: service fleet sample the SAME pieces.
        self.lineage_fingerprint_every = int(lineage_fingerprint_every)
        #: resolved StoragePolicy arming the object-store ingest engine, or
        #: None for the seed fragment.to_table() path (docs/performance.md
        #: "Object-store ingest engine"; the reader resolved the
        #: make_reader(storage_policy=) kwarg before shipping the setup)
        self.storage_policy = storage_policy
        # Cache key token covers the dataset identity AND the read configuration
        # (the ONE shared derivation — dataset_state.derive_dataset_token — that
        # the cache, the cost ledger and the lineage manifest all key on).
        field_specs = [
            (name, str(field.numpy_dtype), str(field.shape),
             str(field.codec.to_config()) if field.codec is not None else 'none')
            for name, field in schema.fields.items() if name in self.fields_to_read]
        from petastorm_tpu.dataset_state import derive_dataset_token
        self.dataset_token = derive_dataset_token(
            dataset_path_or_paths, self.fields_to_read, decode,
            transform_spec is not None, field_specs,
            self.device_decode_fields)
        read_view = schema.create_schema_view(
            [re.escape(name) for name in self.fields_to_read]) \
            if self.fields_to_read else schema
        if transform_spec is not None:
            self.result_schema = transform_schema(read_view, transform_spec)
        else:
            self.result_schema = read_view


class RowGroupWorker(WorkerBase):
    """Loads + processes one rowgroup per ventilated item (reference:
    py_dict_reader_worker.py:102-313, arrow_reader_worker.py:91-337)."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._setup = args
        self._filesystem = None
        self._parquet_format = pads.ParquetFileFormat()
        # compiled decode plans per output field set, memoized for the worker's
        # lifetime (docs/performance.md "Vectorized decode engine"); predicates
        # re-compile per piece — items may carry fresh unpickled instances, and
        # compilation is closure-building only (no IO)
        self._decode_plans = {}
        # shared footer/metadata cache for the storage ingest engine (one per
        # worker process; every rowgroup piece of a file reuses its footer)
        self._metadata_cache = None

    def _fs(self):
        if self._filesystem is None:
            with stage_span('fs_open'):
                self._filesystem = self._setup.filesystem_factory()
        return self._filesystem

    def _publish(self, payload):
        """Single publish funnel: attach the stage-span telemetry sidecar (this
        thread's accumulation since its previous publish — docs/observability.md),
        the tripped-breaker states of this process (docs/robustness.md), and the
        flight-recorder trace sidecar (this thread's drained timeline events),
        then hand the payload to the pool's results channel."""
        from petastorm_tpu.resilience import default_board
        payload.telemetry = drain_stage_times()
        payload.breakers = default_board().snapshot(only_tripped=True) or None
        payload.trace = drain_trace_events()
        self.publish_func(payload)

    def process(self, piece_index, fragment_path, row_group_id, partition_keys=None,
                worker_predicate=None, shuffle_row_drop_partition=(0, 1), epoch_index=0,
                row_range=None):
        # Causal trace context (docs/observability.md "Flight recorder"): every
        # span/instant this thread records while the item is processed — publish
        # and serialize included, they run inside this call — is tagged
        # (epoch, rowgroup, dispatch attempt). The attempt was installed by
        # process_worker_main from the pool's work frames (0 on thread/dummy
        # pools), so a re-ventilated rowgroup's second life is a distinct
        # attempt on the merged timeline.
        set_trace_context(epoch_index, piece_index, current_dispatch_attempt())
        try:
            return self._process_item(piece_index, fragment_path, row_group_id,
                                      partition_keys, worker_predicate,
                                      shuffle_row_drop_partition, epoch_index,
                                      row_range)
        finally:
            clear_trace_context()

    def _process_item(self, piece_index, fragment_path, row_group_id, partition_keys,
                      worker_predicate, shuffle_row_drop_partition, epoch_index,
                      row_range=None):
        setup = self._setup
        # (absolute_epoch, piece, drop_partition): the epoch tag lets the reader attribute
        # this result to the right epoch even when completions interleave across an epoch
        # boundary (parallel pools keep up to workers+2 items in flight).
        item_id = (epoch_index, piece_index, shuffle_row_drop_partition[0])

        # ------------------------------------------------------------- resilience
        # The retry wrapper goes around the IO-heavy load closure only (transform and
        # shuffle never touch the filesystem); the skip-with-quarantine catch covers the
        # whole piece so any permanent failure — corrupt footer, decode bug — degrades
        # to one ledger entry instead of aborting the epoch (docs/robustness.md).
        retry_cell = [0]

        def on_retry(attempt, exc, delay):
            retry_cell[0] += 1
            # Drop the cached filesystem: a broken connection must not be reused — the
            # next attempt reconnects through the (retry-aware) factory.
            self._filesystem = None
            logger.warning('Transient IO failure on piece %s (%s rg %s), attempt %d: '
                           '%s; retrying in %.3fs', piece_index, fragment_path,
                           row_group_id, attempt, exc, delay)

        def with_retry(load_fn):
            if setup.retry_policy is None:
                return load_fn()
            from petastorm_tpu.resilience import (call_with_breaker, default_board,
                                                  run_with_retry)
            # Per-path-prefix filesystem breaker composing with the retry policy
            # (docs/robustness.md): once a prefix (one store / one mount) keeps
            # failing, attempts against it fail FAST — the remaining budget burns
            # in milliseconds instead of hammering a stalled filesystem, and
            # under 'skip' the piece quarantines promptly. Only under a retrying
            # policy: on_error='raise' stays byte-identical to the seed.
            breaker = default_board().breaker(
                'fs:{}'.format(os.path.dirname(fragment_path) or fragment_path),
                failure_threshold=FS_BREAKER_THRESHOLD,
                recovery_timeout_s=FS_BREAKER_RECOVERY_S)
            result, _ = run_with_retry(
                lambda: call_with_breaker(load_fn, breaker),
                setup.retry_policy, key=piece_index, on_retry=on_retry)
            return result

        if setup.ngram is not None:
            if row_range is not None:
                # the scheduler never splits NGram readers (windows span rows);
                # a range reaching this path is a wiring bug, not a data fault
                raise ValueError('row_range sub-range items are not supported '
                                 'on the NGram path')
            try:
                payload = with_retry(lambda: self._process_ngram(
                    piece_index, fragment_path, row_group_id, partition_keys,
                    worker_predicate, shuffle_row_drop_partition, epoch_index))
            except Exception as exc:  # noqa: BLE001 - on_error policy decides
                if setup.on_error != 'skip':
                    raise
                self._publish_quarantined(exc, item_id, piece_index, fragment_path,
                                          row_group_id, retry_cell[0])
                return
            # Always published — a zero-window piece still carries its item_id so
            # the reader's consumption accounting stays exact (same contract as the
            # row path's empty ColumnarBatch below).
            payload.retries = retry_cell[0]
            payload.lineage = self._lineage_fingerprint(piece_index,
                                                        payload.columns,
                                                        len(payload.starts))
            self._publish(payload)
            return

        try:
            predicate_token = _predicate_token(worker_predicate)

            def load():
                return self._load_and_decode(fragment_path, row_group_id, partition_keys,
                                             worker_predicate, shuffle_row_drop_partition,
                                             row_range=row_range)

            cache_hit = None
            if predicate_token is None:
                # Unpicklable predicate: no stable cache identity exists — bypass the
                # cache rather than risk serving rows filtered by a different predicate.
                columns = with_retry(load)
            else:
                cache_key = '{}:{}:{}:{}:{}'.format(
                    setup.dataset_token, fragment_path, row_group_id,
                    shuffle_row_drop_partition, predicate_token)
                if row_range is not None:
                    # a sub-range item caches its own slice; appended only when
                    # the scheduler split this rowgroup, so every whole-rowgroup
                    # key (and cache already on disk) stays exactly as before
                    cache_key += ':rr{}-{}'.format(int(row_range[0]),
                                                   int(row_range[1]))
                filled = [False]

                def fill():
                    filled[0] = True
                    return with_retry(load)

                cache_applies = not isinstance(setup.cache, NullCache)
                cache_start = time.perf_counter()
                columns = setup.cache.get(cache_key, fill)
                if cache_applies:
                    cache_hit = not filled[0]
                    # cache_hit times serving from the cache; cache_miss is an
                    # ENVELOPE span (it wraps the rowgroup_read/decode of the
                    # fill) — attribution uses the leaf stages (telemetry/
                    # analyze.py).
                    record_stage('cache_hit' if cache_hit else 'cache_miss',
                                 time.perf_counter() - cache_start)
            num_rows = _columns_num_rows(columns)
            if num_rows:
                columns = self._shuffle(columns, num_rows, piece_index)
                columns, num_rows = self._apply_transform(columns, num_rows)
        except Exception as exc:  # noqa: BLE001 - on_error policy decides
            if setup.on_error != 'skip':
                raise
            self._publish_quarantined(exc, item_id, piece_index, fragment_path,
                                      row_group_id, retry_cell[0])
            return
        if num_rows == 0:
            # Publish an empty batch anyway: every item must yield exactly one result so
            # the reader's consumption accounting (state_dict/resume) stays exact.
            self._publish(ColumnarBatch({}, 0, item_id=item_id,
                                        retries=retry_cell[0],
                                        cache_hit=cache_hit))
            return
        self._publish(ColumnarBatch(columns, num_rows, item_id=item_id,
                                    retries=retry_cell[0], cache_hit=cache_hit,
                                    lineage=self._lineage_fingerprint(
                                        piece_index, columns, num_rows)))

    def _lineage_fingerprint(self, piece_index, columns, num_rows):
        """The sampled content-CRC sidecar for one produced batch
        (docs/observability.md "Sample lineage"): computed when the setup's
        cadence selects this piece, None otherwise. Sampling keys on the
        piece identity, never on worker-local counters, so every
        pool/transport fingerprints the same pieces."""
        every = self._setup.lineage_fingerprint_every
        if not every or not num_rows or piece_index % every != 0:
            return None
        from petastorm_tpu.telemetry.lineage import content_fingerprint
        return content_fingerprint(columns)

    def _publish_quarantined(self, exc, item_id, piece_index, fragment_path,
                             row_group_id, retries):
        """Skip path: record the failure and publish an EMPTY result carrying the
        quarantine record, so (a) consumption accounting still sees exactly one result
        for the item — checkpoints exclude it via the consumed set — and (b) the record
        reaches the reader-side ledger over the same channel on every pool."""
        from petastorm_tpu.resilience import QuarantineRecord
        record = QuarantineRecord.from_exception(
            exc, piece_index=piece_index, fragment_path=fragment_path,
            row_group_id=row_group_id, attempts=retries + 1, epoch=item_id[0])
        # anomaly marker on the flight-recorder timeline (ctx = this item)
        trace_instant('quarantine', args={'reason': record.reason,
                                          'error_type': record.error_type})
        logger.warning('Quarantining rowgroup piece %s (%s rg %s) after %d attempt(s): '
                       '%s: %s', piece_index, fragment_path, row_group_id, retries + 1,
                       type(exc).__name__, exc)
        if self._setup.ngram is not None:
            from petastorm_tpu.ngram_worker import NGramWindows
            self._publish(NGramWindows({}, np.empty(0, np.int64), item_id=item_id,
                                       retries=retries, quarantine=record))
        else:
            self._publish(ColumnarBatch({}, 0, item_id=item_id, retries=retries,
                                        quarantine=record))

    # ------------------------------------------------------------------ load

    def _make_fragment(self, fragment_path, row_group_id=None):
        row_groups = None if row_group_id is None else [row_group_id]
        return self._parquet_format.make_fragment(fragment_path, self._fs(),
                                                  row_groups=row_groups)

    def _storage_columns(self, field_names):
        return [name for name in field_names
                if name not in self._setup.partition_field_names]

    def _storage_source(self, fragment_path, row_group_id):
        """A planned :class:`~petastorm_tpu.storage.engine.RowGroupSource`
        when the object-store ingest engine is armed, else None (seed
        ``fragment.to_table()`` path — docs/performance.md "Object-store
        ingest engine"). Built inside the load closure, so footer reads and
        range fetches sit under the same retry/breaker wrapping as seed
        reads, and a reconnect (``self._filesystem = None``) gives the next
        attempt a fresh source over the fresh filesystem."""
        policy = getattr(self._setup, 'storage_policy', None)
        if policy is None:
            return None
        from petastorm_tpu.storage.engine import RowGroupSource
        if self._metadata_cache is None:
            from petastorm_tpu.dataset_state import cache_state_home
            from petastorm_tpu.storage.metadata_cache import MetadataCache
            # the shared disk-cache directory (when one is configured) makes
            # footers fleet-shared: every co-located service worker reads
            # the same sidecars
            disk_dir = policy.cache_dir or cache_state_home(self._setup.cache)
            self._metadata_cache = MetadataCache(
                capacity=policy.cache_capacity, disk_dir=disk_dir)
        return RowGroupSource(fragment_path, self._fs(), policy,
                              row_group_id, self._metadata_cache)

    def _load_and_decode(self, fragment_path, row_group_id, partition_keys,
                         worker_predicate, shuffle_row_drop_partition,
                         row_range=None):
        setup = self._setup
        all_fields = setup.fields_to_read
        if worker_predicate is not None:
            table, keep_indices = self._two_phase_load(fragment_path, row_group_id,
                                                       partition_keys, worker_predicate,
                                                       all_fields)
        else:
            source = self._storage_source(fragment_path, row_group_id)
            if source is not None:
                # planned byte-range read: the source times range_fetch
                # (network) and rowgroup_read (Parquet decode) disjointly
                table = source.read_columns(self._storage_columns(all_fields))
            else:
                fragment = self._make_fragment(fragment_path, row_group_id)
                with stage_span('rowgroup_read'):
                    table = fragment.to_table(columns=self._storage_columns(all_fields))
            keep_indices = None
        num_rows = table.num_rows if keep_indices is None else len(keep_indices)

        # shuffle-row-drop partition selection: deterministic equal split of the (post
        # predicate) row indices; only the selected partition is materialized (reference:
        # py_dict_reader_worker.py:290-306).
        part_index, num_parts = shuffle_row_drop_partition
        base_indices = np.arange(num_rows) if keep_indices is None else np.asarray(keep_indices)
        if row_range is not None:
            # Sub-range work item (docs/performance.md "Cost-aware scheduling"):
            # restrict to the PHYSICAL row positions [start, stop) before the
            # drop-partition split, so the scheduler's sub-ranges of one
            # rowgroup partition its rows exactly (predicate filtering
            # composes: keep_indices are physical positions too).
            start, stop = int(row_range[0]), int(row_range[1])
            base_indices = base_indices[(base_indices >= start)
                                        & (base_indices < stop)]
        if num_parts > 1:
            selected = np.array_split(base_indices, num_parts)[part_index]
        else:
            selected = base_indices
        if len(selected) != table.num_rows:
            table = table.take(selected)

        return self._decode_table(table, partition_keys, all_fields,
                                  fragment_path=fragment_path)

    def _two_phase_load(self, fragment_path, row_group_id, partition_keys,
                        worker_predicate, all_fields):
        """Load predicate columns first, evaluate, then load only the REMAINING
        columns and filter (reference: py_dict_reader_worker.py:201-269 — which
        re-read every column; here each storage column is read exactly once,
        with the already-materialized predicate table reused in the output).

        Compilable predicates (docs/performance.md "Vectorized decode engine")
        evaluate as whole-column pushdown on the pre-decode Arrow table;
        ``in_lambda``/custom predicates keep the decoded per-row path."""
        setup = self._setup
        predicate_fields = sorted(worker_predicate.get_fields())
        unknown = [f for f in predicate_fields
                   if f not in setup.schema.fields and f not in setup.partition_field_names]
        if unknown:
            raise ValueError('Predicate references unknown fields {}'.format(unknown))
        source = self._storage_source(fragment_path, row_group_id)
        if source is not None:
            fragment = None
            predicate_table = source.read_columns(
                self._storage_columns(predicate_fields))
        else:
            fragment = self._make_fragment(fragment_path, row_group_id)
            with stage_span('rowgroup_read'):
                predicate_table = fragment.to_table(
                    columns=self._storage_columns(predicate_fields))
        compiled = decode_engine.compile_predicate(
            worker_predicate, setup.schema,
            partition_field_names=setup.partition_field_names,
            decode=setup.decode)
        if compiled is not None:
            with stage_span('decode'):
                mask = compiled.evaluate(predicate_table)
        else:
            # predicate evaluation always needs DECODED values, even for
            # fields that ship raw to the device in the output assembly
            predicate_columns = self._decode_table(predicate_table, partition_keys,
                                                   predicate_fields,
                                                   fragment_path=fragment_path,
                                                   ship_raw=False)
            mask = self._evaluate_predicate(worker_predicate, predicate_columns,
                                            predicate_table.num_rows)
        keep = np.nonzero(mask)[0]
        import pyarrow as pa
        all_storage = self._storage_columns(all_fields)
        if not len(keep):
            # No survivors: build an empty table from the schema without reading data.
            physical = (source.schema_arrow() if source is not None
                        else fragment.physical_schema)
            empty = pa.table({name: pa.array([], type=physical.field(name).type)
                              for name in all_storage})
            return empty, np.array([], dtype=np.int64)
        # Single-read assembly: reuse the predicate columns already in memory and
        # read only what the output still needs; downstream sees one consistent
        # table in the output column order. The storage source keeps the same
        # invariant — columns fetched for the predicate phase are never
        # re-fetched (engine.RowGroupSource tracks them).
        have = set(predicate_table.column_names)
        remaining = [name for name in all_storage if name not in have]
        if remaining:
            if source is not None:
                remaining_table = source.read_columns(remaining)
            else:
                with stage_span('rowgroup_read'):
                    remaining_table = fragment.to_table(columns=remaining)
            full_table = pa.table(
                {name: (predicate_table.column(name) if name in have
                        else remaining_table.column(name))
                 for name in all_storage})
        else:
            full_table = predicate_table.select(all_storage)
        return full_table, keep

    def _evaluate_predicate(self, worker_predicate, predicate_columns, num_rows):
        setup = self._setup
        if setup.batched_output:
            result = worker_predicate.do_include(
                {k: np.asarray(v) for k, v in predicate_columns.items()})
            mask = np.asarray(result)
            if mask.shape != (num_rows,):
                raise ValueError('Batched predicate must return a boolean mask of shape '
                                 '({},); got {}'.format(num_rows, mask.shape))
            return mask
        # Row mode: one vectorized do_include call for the built-in predicate
        # classes, a zip-driven row loop for in_lambda/custom subclasses
        # (decode_engine; docs/performance.md "Vectorized decode engine").
        return decode_engine.evaluate_predicate_mask(worker_predicate,
                                                     predicate_columns, num_rows)

    # ---------------------------------------------------------------- decode

    def _decode_table(self, table, partition_keys, field_names, fragment_path=None,
                      ship_raw=True):
        """Arrow table -> {name: ndarray-or-list} of decoded values, through the
        per-schema compiled :class:`~petastorm_tpu.decode_engine.DecodePlan`
        (one whole-column kernel per field, no per-cell dispatch). Codec
        failures are wrapped in :class:`DecodeFieldError` carrying the field
        name and fragment path as structured attributes — a corrupt value names
        its store location, not just a message. ``ship_raw=False`` compiles the
        plan without the setup's ``device_decode_fields`` (predicate columns
        must decode fully even when the output ships raw)."""
        plan = self._decode_plan(tuple(field_names), ship_raw=ship_raw)
        with stage_span('decode'):
            return plan.execute(table, partition_keys or {},
                                fragment_path=fragment_path)

    def _decode_plan(self, field_names, ship_raw=True):
        """Memoized decode-plan compilation for one output field tuple."""
        setup = self._setup
        device_fields = setup.device_decode_fields if ship_raw else frozenset()
        key = (field_names, bool(device_fields))
        plan = self._decode_plans.get(key)
        if plan is None:
            plan = decode_engine.compile_decode_plan(
                setup.schema, list(field_names),
                partition_field_names=setup.partition_field_names,
                decode=setup.decode,
                device_decode_fields=device_fields)
            self._decode_plans[key] = plan
        return plan

    # --------------------------------------------------------------- shuffle

    def _shuffle(self, columns, num_rows, piece_index):
        setup = self._setup
        if not setup.shuffle_rows:
            return columns
        with stage_span('shuffle'):
            seed = None if setup.seed is None else (setup.seed + piece_index) % (2 ** 31)
            permutation = np.random.RandomState(seed).permutation(num_rows)
            return {name: _take(col, permutation) for name, col in columns.items()}

    # ------------------------------------------------------------- transform

    def _apply_transform(self, columns, num_rows):
        setup = self._setup
        spec = setup.transform_spec
        if spec is None:
            return columns, num_rows
        with stage_span('transform'):
            if spec.func is None:
                # Vectorized pre-pass (docs/performance.md "Vectorized decode
                # engine"): a spec that only deletes/selects/redeclares fields
                # needs no row or frame materialization — the decoded columns
                # pass through untouched, reordered to the result schema.
                return ({name: columns[name]
                         for name in setup.result_schema.fields}, num_rows)
            if setup.batched_output:
                import pandas as pd
                frame = pd.DataFrame({name: list(col) if not isinstance(col, list)
                                      else col
                                      for name, col in columns.items()})
                frame = spec.func(frame)
                out = {}
                for name in setup.result_schema.fields:
                    field = setup.result_schema.fields[name]
                    values = list(frame[name])
                    out[name] = _stack_if_uniform(values, field)
                return out, len(frame)
            if spec.batched:
                # Declared-batched row-path func: whole decoded columns in, whole
                # columns out — the second half of the vectorized pre-pass.
                out_columns = spec.func(dict(columns))
                out = {}
                out_rows = num_rows
                for name in setup.result_schema.fields:
                    field = setup.result_schema.fields[name]
                    values = out_columns[name]
                    if not isinstance(values, np.ndarray):
                        values = _stack_if_uniform(list(values), field)
                    out[name] = values
                    out_rows = len(values)
                return out, out_rows
            # Row path: func operates on one row dict at a time (reference:
            # py_dict_reader_worker.py:40-54).
            rows = [{name: col[i] for name, col in columns.items()}
                    for i in range(num_rows)]
            rows = [spec.func(row) for row in rows]
            out = {}
            for name in setup.result_schema.fields:
                field = setup.result_schema.fields[name]
                values = [row[name] for row in rows]
                out[name] = _stack_if_uniform(values, field)
            return out, len(rows)

    # ----------------------------------------------------------------- ngram

    def _process_ngram(self, piece_index, fragment_path, row_group_id, partition_keys,
                       worker_predicate, shuffle_row_drop_partition, epoch_index=0):
        from petastorm_tpu.ngram_worker import process_ngram_piece
        return process_ngram_piece(self, piece_index, fragment_path, row_group_id,
                                   partition_keys, worker_predicate,
                                   shuffle_row_drop_partition, epoch_index)


# ------------------------------------------------------------------ helpers

def _predicate_token(worker_predicate):
    """Stable cache token for a predicate; None when no stable identity exists (caller
    must then bypass the cache)."""
    if worker_predicate is None:
        return 'nopred'
    try:
        import pickle
        return hashlib.md5(pickle.dumps(worker_predicate)).hexdigest()[:12]
    except Exception:  # noqa: BLE001 - ANY pickling failure means "no stable identity"
        # swallowing is the contract here: an unpicklable predicate just
        # bypasses the rowgroup cache (caller checks for None) — but say so,
        # or "cache never warms" is undebuggable
        logger.debug('predicate %s has no stable cache token; bypassing the '
                     'rowgroup cache for it', type(worker_predicate).__name__,
                     exc_info=True)
        return None


def _take(col, indices):
    if isinstance(col, np.ndarray):
        return col[indices]
    return [col[i] for i in indices]


# Promoted into the strict-typed decode engine (satellite fixes included:
# one asarray pass in stack_if_uniform, Arrow-native object arrays for
# string/binary columns); aliased here for this module's internal callers.
_stack_if_uniform = decode_engine.stack_if_uniform
_arrow_to_numpy = decode_engine.arrow_to_numpy
