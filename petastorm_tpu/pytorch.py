"""PyTorch adapters (reference: petastorm/pytorch.py:126-496) — thin wrappers over the
columnar core for capability parity (SURVEY.md §7.1 item 8); the JAX loader
(petastorm_tpu.parallel) is the primary device path.

``DataLoader`` — row-based with optional shuffling buffer and decimal-friendly collate.
``BatchedDataLoader`` — columnar fast path over batched readers.
``InMemBatchedDataLoader`` — loads once, then epochs of in-memory random batches.
All yield dicts of torch tensors.
"""

import decimal
from collections.abc import Mapping

import numpy as np

from petastorm_tpu.parallel.shuffling_buffer import (NoopShufflingBuffer,
                                                     RandomShufflingBuffer)


def _sanitize_value(name, value):
    """Dtype sanitization (reference: pytorch.py:40-65): bool->uint8, unsigned promote,
    Decimal->float64; None and strings are rejected with the field named."""
    if value is None:
        raise TypeError('Field {!r} is None; use a TransformSpec or schema_fields to '
                        'drop nullable fields before the torch loader'.format(name))
    if isinstance(value, decimal.Decimal):
        return np.float64(value)
    if isinstance(value, (str, bytes)):
        raise TypeError('Field {!r} is a string; torch tensors cannot hold strings — '
                        'drop it via schema_fields'.format(name))
    arr = np.asarray(value)
    if arr.dtype == np.bool_:
        return arr.astype(np.uint8)
    if arr.dtype == np.uint16:
        return arr.astype(np.int32)
    if arr.dtype == np.uint32:
        return arr.astype(np.int64)
    if arr.dtype.kind == 'M':
        return arr.astype('datetime64[ns]').astype(np.int64)
    if arr.dtype == object:
        raise TypeError('Field {!r} has object dtype (strings/None?); drop it via '
                        'schema_fields'.format(name))
    return arr


def _writable_contiguous(arr):
    """Contiguous, writable copy-on-need: Arrow-backed columns are read-only views and
    torch.as_tensor cannot safely alias them."""
    arr = np.ascontiguousarray(arr)
    return arr if arr.flags.writeable else arr.copy()


def decimal_friendly_collate(rows):
    """Collate a list of rows (dicts, tuples/namedtuples, or leaves) into stacked
    torch tensors with the same nesting (reference: pytorch.py:68-90 — its collate
    recurses into mappings AND tuples)."""
    import torch
    first = rows[0]
    if isinstance(first, Mapping):
        return {name: decimal_friendly_collate([row[name] for row in rows])
                for name in first}
    if isinstance(first, tuple):
        collated = [decimal_friendly_collate(list(col)) for col in zip(*rows)]
        if hasattr(first, '_fields'):  # namedtuple: rebuild the same row type
            return type(first)(*collated)
        return type(first)(collated)
    sanitized = [_sanitize_value('<collate>', v) for v in rows]
    return torch.as_tensor(np.stack(sanitized))


class LoaderBase(object):
    """Iteration guards shared by all loaders (reference: pytorch.py:98-123): no
    concurrent iteration, auto reader reset on re-iteration, error latching."""

    def __init__(self, reader):
        self.reader = reader
        self._in_iter = False
        self._error = None
        self._started = False

    def __iter__(self):
        if self._in_iter:
            raise RuntimeError('Concurrent iteration of a loader is not allowed')
        if self._error is not None:
            raise RuntimeError('Loader previously failed') from self._error
        if self._started and getattr(self.reader, 'last_row_consumed', False):
            self.reader.reset()
        self._started = True
        self._in_iter = True
        try:
            yield from self._iter_impl()
        except Exception as exc:
            self._error = exc
            raise
        finally:
            self._in_iter = False

    def _iter_impl(self):
        raise NotImplementedError()

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()


class DataLoader(LoaderBase):
    """Row-based loader: reader rows -> optional RandomShufflingBuffer -> fixed-size
    collated batches (reference: pytorch.py:126-251)."""

    def __init__(self, reader, batch_size=1, collate_fn=decimal_friendly_collate,
                 shuffling_queue_capacity=0, seed=None):
        super().__init__(reader)
        self.batch_size = batch_size
        self.collate_fn = collate_fn
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed

    def _iter_impl(self):
        batch = []
        for window in self._row_stream():
            batch.append(window)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch:
            yield self.collate_fn(batch)

    def _row_stream(self):
        if self.shuffling_queue_capacity > 0:
            rng = np.random.default_rng(self._seed)
            buffer = []
            for row in self.reader:
                row_dict = {k: _sanitize_value(k, v) for k, v in row._asdict().items()}
                if len(buffer) < self.shuffling_queue_capacity:
                    buffer.append(row_dict)
                    continue
                index = rng.integers(len(buffer))
                yield buffer[index]
                buffer[index] = row_dict
            rng.shuffle(buffer)
            yield from buffer
        else:
            for row in self.reader:
                yield {k: _sanitize_value(k, v) for k, v in row._asdict().items()}


class BatchedDataLoader(LoaderBase):
    """Columnar fast path over a batched reader (reference: pytorch.py:254-365).

    Columns are converted to torch tensors via ``transform_fn`` (default
    ``torch.as_tensor``) *before* entering the shuffling buffer, so when
    ``transform_fn`` places tensors on an accelerator the buffer gathers/concats
    device-resident tensors — the reference's CUDA batched-buffer behavior
    (pytorch_shuffling_buffer.py:22-279) with one unified buffer implementation."""

    def __init__(self, reader, batch_size=1, transform_fn=None,
                 shuffling_queue_capacity=0, seed=None):
        super().__init__(reader)
        if not getattr(reader, 'is_batched_reader', False):
            raise ValueError('BatchedDataLoader requires a make_batch_reader reader')
        self.batch_size = batch_size
        self.shuffling_queue_capacity = shuffling_queue_capacity
        self._seed = seed
        if transform_fn is None:
            import torch
            transform_fn = torch.as_tensor
        self.transform_fn = transform_fn

    def _iter_impl(self):
        if self.shuffling_queue_capacity > 0:
            buffer = RandomShufflingBuffer(self.shuffling_queue_capacity,
                                           self.shuffling_queue_capacity // 2,
                                           seed=self._seed)
        else:
            buffer = NoopShufflingBuffer()
        for batch in self.reader:
            columns = {name: self.transform_fn(_writable_contiguous(
                           _sanitize_value(name, col)))
                       for name, col in batch._asdict().items()}
            buffer.add_many(columns)
            while buffer.can_retrieve(self.batch_size):
                yield buffer.retrieve(self.batch_size)
        buffer.finish()
        while buffer.can_retrieve(1):
            yield buffer.retrieve(self.batch_size)


class InMemBatchedDataLoader(LoaderBase):
    """Loads up to ``rows_capacity`` rows once, then serves ``num_epochs`` of seeded
    random (or sequential) batches from memory — avoids re-IO across epochs (reference:
    pytorch.py:368-496)."""

    def __init__(self, reader, batch_size=1, rows_capacity=None, num_epochs=1,
                 shuffle=True, seed=0):
        super().__init__(reader)
        self.batch_size = batch_size
        self._num_epochs = num_epochs
        self._shuffle = shuffle
        self._seed = seed
        self._columns = None
        self._rows = 0
        self._capacity = rows_capacity

    def _fill(self):
        import torch
        parts = []
        count = 0
        if getattr(self.reader, 'is_batched_reader', False):
            for batch in self.reader:
                columns = {k: _sanitize_value(k, v) for k, v in batch._asdict().items()}
                parts.append(columns)
                count += len(next(iter(columns.values())))
                if self._capacity is not None and count >= self._capacity:
                    break
        else:
            rows = []
            for row in self.reader:
                rows.append({k: _sanitize_value(k, v) for k, v in row._asdict().items()})
                count += 1
                if self._capacity is not None and count >= self._capacity:
                    break
            if rows:
                parts.append({name: np.stack([r[name] for r in rows])
                              for name in rows[0]})
        # Stop the reader right away: avoids deadlocking an infinite-epoch reader
        # (reference: pytorch.py:420-424).
        self.reader.stop()
        self.reader.join()
        if not parts:
            raise ValueError('Reader produced no rows to preload')
        merged = {name: np.concatenate([p[name] for p in parts])[:self._capacity]
                  for name in parts[0]}
        self._columns = {name: torch.as_tensor(col) for name, col in merged.items()}
        self._rows = len(next(iter(merged.values())))

    def _iter_impl(self):
        import torch
        if self._columns is None:
            self._fill()
        for epoch in range(self._num_epochs):
            if self._shuffle:
                generator = torch.Generator()
                generator.manual_seed(self._seed + epoch)
                order = torch.randperm(self._rows, generator=generator)
            else:
                order = torch.arange(self._rows)
            for start in range(0, self._rows - self.batch_size + 1, self.batch_size):
                indices = order[start:start + self.batch_size]
                yield {name: col[indices] for name, col in self._columns.items()}

    def __iter__(self):
        # Unlike the streaming loaders, re-iteration is always allowed (data is in
        # memory) and the reader is already stopped.
        if self._in_iter:
            raise RuntimeError('Concurrent iteration of a loader is not allowed')
        self._in_iter = True
        try:
            yield from self._iter_impl()
        finally:
            self._in_iter = False
