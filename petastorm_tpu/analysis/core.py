"""pipecheck core: the AST analysis framework under the rule families.

This module is deliberately dependency-free (stdlib ``ast`` + ``tokenize``
only) and never *imports* the code it analyzes — every check is static, so
``pipecheck`` can run in environments where the data plane's optional
dependencies (zmq, pyarrow, jax) are absent, and a module with an import-time
bug can still be analyzed.

Building blocks:

- :class:`Finding` — one rule violation: ``(rule, path, line, message)``.
- :class:`SourceModule` — one parsed source file: text, AST, the per-line
  comment map (via ``tokenize``, so ``#`` inside string literals never counts),
  and the parsed :class:`Suppression` directives.
- :class:`Rule` — base class; rules implement :meth:`Rule.check_module` (per
  file) and optionally :meth:`Rule.finalize` (cross-file set matching, run
  after every file has been visited — the protocol-conformance shape).
- :func:`run_analysis` — collect files, parse, run rules, apply suppressions,
  return a :class:`Report`.

Suppression syntax (docs/static-analysis.md): a trailing comment

    # pipecheck: disable=<rule>[,<rule>...] -- <reason>

suppresses findings of the named rules **on that physical line** (for a
``try/except`` handler, the ``except`` line). The reason is mandatory: a
suppression without one is itself reported under the ``suppression-hygiene``
rule — an undocumented opt-out is exactly the silent drift this tool exists
to prevent. ``disable=all`` suppresses every rule on the line.
"""

from __future__ import annotations

import ast
import io
import json
import re
import time
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

#: rule id for files the parser rejects (not suppressible — a file that cannot
#: be parsed cannot carry a suppression comment for its own syntax error)
PARSE_ERROR_RULE = 'parse-error'
#: rule id for malformed suppression directives (missing reason, unknown form)
SUPPRESSION_RULE = 'suppression-hygiene'

_SUPPRESSION_RE = re.compile(
    r'#\s*pipecheck:\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(\S.*))?')


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        """Human one-liner: ``path:line: [rule] message``."""
        return '{}:{}: [{}] {}'.format(self.path, self.line, self.rule,
                                       self.message)

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe form for ``--json`` output."""
        return {'rule': self.rule, 'path': self.path, 'line': self.line,
                'message': self.message}


@dataclass
class Suppression:
    """A parsed ``# pipecheck: disable=...`` directive on one line."""

    line: int
    rules: Tuple[str, ...]
    reason: str


class SourceModule:
    """One parsed source file: raw text, AST, per-line comments, suppressions.

    ``display`` is the path rules report findings under (repo-relative when
    the file lives under the analyzed root, absolute otherwise); ``name`` is
    the basename, which codebase-specific rules use for role matching (a file
    named ``process_worker_main.py`` plays the worker-producer role wherever
    it lives — that is what lets fixture trees exercise the cross-file
    rules)."""

    def __init__(self, path: Path, display: str, text: str,
                 tree: ast.Module) -> None:
        self.path = path
        self.display = display
        self.text = text
        self.tree = tree
        self.name = path.name
        #: physical line -> full comment text (tokenize-accurate)
        self.comments: Dict[int, str] = {}
        #: physical line -> parsed suppression directive
        self.suppressions: Dict[int, Suppression] = {}
        self._index_comments()

    def posix(self) -> str:
        """The absolute path with ``/`` separators (for suffix matching)."""
        return self.path.as_posix()

    def _index_comments(self) -> None:
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.text).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # ast.parse accepted the file, so this is a tokenizer corner case;
            # losing comments only costs suppression support for this file.
            return
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            self.comments[tok.start[0]] = tok.string
            match = _SUPPRESSION_RE.search(tok.string)
            if match is not None:
                rules = tuple(r.strip() for r in match.group(1).split(',')
                              if r.strip())
                self.suppressions[tok.start[0]] = Suppression(
                    line=tok.start[0], rules=rules,
                    reason=(match.group(2) or '').strip())


class AnalysisContext:
    """Shared state for one :func:`run_analysis` pass.

    ``modules`` is every parsed file; ``state`` gives cross-file rules a
    private scratch dict (keyed by rule name) populated during
    :meth:`Rule.check_module` and consumed in :meth:`Rule.finalize`."""

    def __init__(self, config: Any, roots: Sequence[Path]) -> None:
        self.config = config
        self.roots: List[Path] = list(roots)
        self.modules: List[SourceModule] = []
        self.state: Dict[str, Any] = {}
        #: rule-appended caveats surfaced in Report.notes ("rule X did not
        #: run because ...") — a skipped check must never look like a passed
        #: one
        self.notes: List[str] = []

    def rule_state(self, rule_name: str) -> Dict[str, Any]:
        """The per-rule cross-file scratch dict (created on first use)."""
        return self.state.setdefault(rule_name, {})

    def find_module(self, posix_suffix: str) -> Optional[SourceModule]:
        """First analyzed module whose absolute posix path ends with
        ``posix_suffix`` (e.g. ``'telemetry/spans.py'``)."""
        for module in self.modules:
            if module.posix().endswith(posix_suffix):
                return module
        return None


class Rule:
    """Base class for pipecheck rules.

    Subclasses set ``name`` (the id used in findings and suppression
    comments) and ``description`` (one line for ``--list-rules`` and the
    docs), and override :meth:`check_module`; rules that need the whole file
    set (protocol conformance) accumulate into
    ``ctx.rule_state(self.name)`` and emit from :meth:`finalize`."""

    name = ''
    description = ''

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        """Per-file pass; yield :class:`Finding` objects."""
        return ()

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        """Cross-file pass, run once after every module was visited."""
        return ()


@dataclass
class Report:
    """The outcome of one analysis pass."""

    findings: List[Finding]
    suppressed: int
    files: int
    rules: List[str]
    notes: List[str] = field(default_factory=list)
    #: wall seconds each rule spent (check_module + finalize), for the
    #: ``--json`` CLI output and the bench wall-time guard
    rule_seconds: Dict[str, float] = field(default_factory=dict)
    #: functions indexed by the shared call graph this pass (0 when no
    #: graph-backed rule ran)
    callgraph_functions: int = 0

    @property
    def clean(self) -> bool:
        """True when no finding survived suppression."""
        return not self.findings

    def by_rule(self) -> Dict[str, int]:
        """``{rule: finding_count}`` for summaries (doctor, bench)."""
        counts: Dict[str, int] = {}
        for finding in self.findings:
            counts[finding.rule] = counts.get(finding.rule, 0) + 1
        return counts

    def as_dict(self) -> Dict[str, Any]:
        """JSON-safe form (the ``--json`` CLI output)."""
        return {'clean': self.clean,
                'finding_count': len(self.findings),
                'suppressed': self.suppressed,
                'files': self.files,
                'rules': list(self.rules),
                'by_rule': self.by_rule(),
                'rule_seconds': {rule: round(seconds, 4) for rule, seconds
                                 in sorted(self.rule_seconds.items())},
                'callgraph_functions': self.callgraph_functions,
                'findings': [f.as_dict() for f in self.findings],
                'notes': list(self.notes)}

    def to_json(self) -> str:
        """One JSON document (indent=2) of :meth:`as_dict`."""
        return json.dumps(self.as_dict(), indent=2, sort_keys=True)

    def format_human(self) -> str:
        """Flake8-style listing plus a one-line verdict (and any notes)."""
        lines = [finding.format() for finding in self.findings]
        lines.extend('pipecheck: note: ' + note for note in self.notes)
        if self.clean:
            lines.append('pipecheck: clean — {} file(s), {} rule(s), {} '
                         'suppression(s) honored'.format(
                             self.files, len(self.rules), self.suppressed))
        else:
            lines.append('pipecheck: {} finding(s) ({} suppressed) across {} '
                         'file(s)'.format(len(self.findings), self.suppressed,
                                          self.files))
        return '\n'.join(lines)


def iter_python_files(paths: Sequence[Path]) -> Iterator[Path]:
    """Expand files/directories into the ``.py`` files to analyze (sorted;
    ``__pycache__`` and dot-directories *below the analyzed root* skipped —
    the root itself may live under one, e.g. a ``.venv`` site-packages
    install)."""
    seen = set()
    for path in paths:
        if path.is_file():
            candidates: Iterable[Tuple[Path, Tuple[str, ...]]] = [(path, ())]
        else:
            candidates = ((c, c.relative_to(path).parts)
                          for c in sorted(path.rglob('*.py')))
        for candidate, rel_parts in candidates:
            if '__pycache__' in rel_parts or any(
                    part.startswith('.') for part in rel_parts):
                continue
            resolved = candidate.resolve()
            if resolved in seen:
                continue
            seen.add(resolved)
            yield candidate


def load_module(path: Path, root: Optional[Path] = None
                ) -> Tuple[Optional[SourceModule], Optional[Finding]]:
    """Read + parse one file. Returns ``(module, None)`` or, when the file
    cannot be read/parsed, ``(None, parse_error_finding)``."""
    display = _display_path(path, root)
    try:
        text = path.read_text(encoding='utf-8')
        tree = ast.parse(text, filename=str(path))
    except (OSError, SyntaxError, ValueError) as exc:
        line = getattr(exc, 'lineno', None) or 1
        return None, Finding(PARSE_ERROR_RULE, display, int(line),
                             'cannot analyze: {!r}'.format(exc))
    return SourceModule(path, display, text, tree), None


def _display_path(path: Path, root: Optional[Path]) -> str:
    if root is not None:
        base = root if root.is_dir() else root.parent
        try:
            return (Path(base.name) / path.relative_to(base)).as_posix() \
                if base.name else path.relative_to(base).as_posix()
        except ValueError:
            pass
    return path.as_posix()


def run_analysis(paths: Sequence[Path], rules: Sequence[Rule],
                 config: Any) -> Report:
    """Run ``rules`` over every Python file under ``paths``.

    Suppression is applied here, uniformly: a finding whose ``line`` carries a
    ``# pipecheck: disable=`` directive naming its rule (or ``all``) is
    dropped and counted in :attr:`Report.suppressed`; directives without a
    reason surface as :data:`SUPPRESSION_RULE` findings."""
    ctx = AnalysisContext(config, [Path(p) for p in paths])
    raw: List[Finding] = []
    parse_errors: List[Finding] = []
    files = 0
    by_display: Dict[str, SourceModule] = {}
    for file_path in iter_python_files(ctx.roots):
        root = _owning_root(file_path, ctx.roots)
        module, error = load_module(file_path, root)
        files += 1
        if error is not None:
            parse_errors.append(error)
            continue
        assert module is not None
        ctx.modules.append(module)
        by_display[module.display] = module
    rule_seconds: Dict[str, float] = {rule.name: 0.0 for rule in rules}
    for module in ctx.modules:
        for rule in rules:
            started = time.perf_counter()
            raw.extend(rule.check_module(module, ctx))
            rule_seconds[rule.name] += time.perf_counter() - started
    for rule in rules:
        started = time.perf_counter()
        raw.extend(rule.finalize(ctx))
        rule_seconds[rule.name] += time.perf_counter() - started

    findings: List[Finding] = list(parse_errors)
    suppressed = 0
    for finding in raw:
        module = by_display.get(finding.path)
        directive = (module.suppressions.get(finding.line)
                     if module is not None else None)
        if directive is not None and (
                finding.rule in directive.rules or 'all' in directive.rules):
            suppressed += 1
            continue
        findings.append(finding)
    for module in ctx.modules:
        for directive in module.suppressions.values():
            if not directive.reason:
                findings.append(Finding(
                    SUPPRESSION_RULE, module.display, directive.line,
                    'suppression without a reason: append " -- <why this is '
                    'safe>" (docs/static-analysis.md)'))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # duck-typed so core never imports callgraph (rules own that layer):
    # whatever the graph-backed rules cached under their shared state key
    # reports its function count here
    graph = ctx.state.get('__callgraph__')
    graph_functions = len(getattr(graph, 'functions', ()) or ())
    return Report(findings=findings, suppressed=suppressed, files=files,
                  rules=[rule.name for rule in rules], notes=list(ctx.notes),
                  rule_seconds=rule_seconds,
                  callgraph_functions=graph_functions)


def _owning_root(path: Path, roots: Sequence[Path]) -> Optional[Path]:
    resolved = path.resolve()
    for root in roots:
        base = root.resolve()
        if resolved == base or base in resolved.parents:
            return root
    return None


# --------------------------------------------------------------------------
# Small AST helpers shared by the rule families
# --------------------------------------------------------------------------

def const_str(node: ast.AST) -> Optional[str]:
    """The value of a ``str`` constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def const_bytes(node: ast.AST) -> Optional[bytes]:
    """The value of a ``bytes`` constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, bytes):
        return node.value
    return None


def literal_str_values(node: ast.AST) -> List[Tuple[str, int]]:
    """String literals an argument expression can evaluate to, with lines:
    a plain constant yields one, a conditional expression
    (``'a' if c else 'b'``) yields both branches — the shape
    ``record_stage('cache_hit' if hit else 'cache_miss', ...)`` takes."""
    value = const_str(node)
    if value is not None:
        return [(value, node.lineno)]
    if isinstance(node, ast.IfExp):
        return literal_str_values(node.body) + literal_str_values(node.orelse)
    return []


def extract_string_tuple(tree: ast.Module, name: str) -> Optional[List[str]]:
    """The string elements of a module-level ``NAME = ('a', 'b', ...)``
    assignment (tuple or list; ``AnnAssign`` accepted). None when ``name``
    is not assigned a literal sequence in ``tree``."""
    for node in tree.body:
        target: Optional[ast.expr] = None
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        if not (isinstance(target, ast.Name) and target.id == name):
            continue
        if isinstance(value, (ast.Tuple, ast.List)):
            out = []
            for element in value.elts:
                text = const_str(element)
                if text is None:
                    return None
                out.append(text)
            return out
    return None


def module_bytes_constants(tree: ast.Module) -> Dict[str, bytes]:
    """Module-level ``NAME = b'...'`` bindings, including tuple unpacking
    (``A, B = b'a', b'b'``) — how ``process_pool.py`` declares its ``MSG_*``
    message kinds."""
    out: Dict[str, bytes] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                value = const_bytes(node.value)
                if value is not None:
                    out[target.id] = value
            elif (isinstance(target, ast.Tuple)
                  and isinstance(node.value, ast.Tuple)
                  and len(target.elts) == len(node.value.elts)):
                for sub_target, sub_value in zip(target.elts, node.value.elts):
                    if isinstance(sub_target, ast.Name):
                        value = const_bytes(sub_value)
                        if value is not None:
                            out[sub_target.id] = value
    return out


def walk_skipping_functions(stmts: Sequence[ast.stmt]) -> Iterator[ast.AST]:
    """Yield every node under ``stmts`` without descending into nested
    function/class definitions or lambdas — 'the statements that execute in
    this scope', which is what the lock- and exception-body checks mean."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)
