"""pipecheck CLI: ``python -m petastorm_tpu.analysis [paths...]``.

Also reachable as ``petastorm-tpu-throughput pipecheck`` and the
``petastorm-tpu-pipecheck`` console script. With no paths, analyzes the
installed ``petastorm_tpu`` package — the self-application mode the tier-1
test keeps green. Exit codes: 0 clean, 1 findings, 2 usage error.

    $ petastorm-tpu-pipecheck                        # self-check the package
    $ petastorm-tpu-pipecheck path/to/tree --json    # machine-readable
    $ petastorm-tpu-pipecheck --rules clock-discipline,mypy-ratchet src/
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from petastorm_tpu.analysis.config import AnalysisConfig, default_config
from petastorm_tpu.analysis.core import Report, run_analysis
from petastorm_tpu.analysis.rules import ALL_RULES, default_rules


def package_root() -> Path:
    """The installed ``petastorm_tpu`` package directory (the default
    analysis target)."""
    import petastorm_tpu
    return Path(os.path.dirname(os.path.abspath(petastorm_tpu.__file__)))


def run_pipecheck(paths: Optional[Sequence[str]] = None,
                  rules: Optional[Sequence[str]] = None,
                  mypy_ini: Optional[str] = None,
                  manifest: Optional[str] = None) -> Report:
    """Programmatic entry (doctor, bench, tests): analyze ``paths`` (default:
    the installed package) with the shipped rules and return the
    :class:`~petastorm_tpu.analysis.core.Report`."""
    config = default_config()
    if mypy_ini is not None or manifest is not None:
        config = AnalysisConfig(mypy_ini_path=mypy_ini, manifest_path=manifest)
    targets = [Path(p) for p in paths] if paths else [package_root()]
    return run_analysis(targets, default_rules(rules), config)


def build_parser() -> argparse.ArgumentParser:
    """The pipecheck argument parser (split out for doc/tests)."""
    parser = argparse.ArgumentParser(
        prog='pipecheck',
        description='AST-based invariant analyzer for the petastorm_tpu '
                    'cross-process data plane (docs/static-analysis.md)')
    parser.add_argument('paths', nargs='*',
                        help='files/directories to analyze (default: the '
                             'installed petastorm_tpu package)')
    parser.add_argument('--json', action='store_true',
                        help='print one JSON document instead of the '
                             'flake8-style listing')
    parser.add_argument('--rules',
                        help='comma-separated rule subset (see --list-rules)')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule catalog and exit')
    parser.add_argument('--mypy-ini',
                        help='explicit mypy.ini path for the mypy-ratchet '
                             'rule (default: walk up from the analyzed '
                             'paths)')
    parser.add_argument('--manifest',
                        help='explicit strict-module manifest path (default: '
                             'the packaged analysis/strict_modules.txt)')
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Console entry: parse args, run the analysis, print, return the exit
    code (0 clean / 1 findings / 2 usage error)."""
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_rules:
        for cls in ALL_RULES:
            print('{:24s} {}'.format(cls.name, cls.description))
        return 0
    selected: Optional[List[str]] = None
    if args.rules:
        selected = [name.strip() for name in args.rules.split(',')
                    if name.strip()]
    try:
        report = run_pipecheck(paths=args.paths or None, rules=selected,
                               mypy_ini=args.mypy_ini,
                               manifest=args.manifest)
    except ValueError as exc:
        print('pipecheck: {}'.format(exc), file=sys.stderr)
        return 2
    print(report.to_json() if args.json else report.format_human())
    return 0 if report.clean else 1


if __name__ == '__main__':
    sys.exit(main())
