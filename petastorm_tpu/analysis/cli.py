"""pipecheck CLI: ``python -m petastorm_tpu.analysis [paths...]``.

Also reachable as ``petastorm-tpu-throughput pipecheck`` and the
``petastorm-tpu-pipecheck`` console script. With no paths, analyzes the
installed ``petastorm_tpu`` package — the self-application mode the tier-1
test keeps green. Exit codes: 0 clean, 1 findings, 2 usage error.

    $ petastorm-tpu-pipecheck                        # self-check the package
    $ petastorm-tpu-pipecheck path/to/tree --json    # machine-readable
    $ petastorm-tpu-pipecheck --rules clock-discipline,mypy-ratchet src/
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from dataclasses import replace
from pathlib import Path
from typing import List, Optional, Sequence, Set

from petastorm_tpu.analysis.config import AnalysisConfig, default_config
from petastorm_tpu.analysis.core import Report, run_analysis
from petastorm_tpu.analysis.rules import ALL_RULES, default_rules


def package_root() -> Path:
    """The installed ``petastorm_tpu`` package directory (the default
    analysis target)."""
    import petastorm_tpu
    return Path(os.path.dirname(os.path.abspath(petastorm_tpu.__file__)))


def run_pipecheck(paths: Optional[Sequence[str]] = None,
                  rules: Optional[Sequence[str]] = None,
                  mypy_ini: Optional[str] = None,
                  manifest: Optional[str] = None,
                  diff_base: Optional[str] = None) -> Report:
    """Programmatic entry (doctor, bench, tests): analyze ``paths`` (default:
    the installed package) with the shipped rules and return the
    :class:`~petastorm_tpu.analysis.core.Report`.

    ``diff_base`` restricts the *reported* findings to files changed vs the
    given git ref — the analysis itself still runs over the whole tree
    (cross-file rules need full context), so the filter narrows the output
    without weakening the checks."""
    config = default_config()
    if mypy_ini is not None or manifest is not None:
        config = AnalysisConfig(mypy_ini_path=mypy_ini, manifest_path=manifest)
    targets = [Path(p) for p in paths] if paths else [package_root()]
    report = run_analysis(targets, default_rules(rules), config)
    if diff_base is not None:
        report = _restrict_to_diff(report, diff_base, targets)
    return report


def _changed_paths(diff_base: str, targets: Sequence[Path]) -> Set[str]:
    """Repo-relative posix paths changed vs ``diff_base`` in the repo(s)
    owning ``targets``. Raises ``ValueError`` when git cannot diff (bad
    ref, not a repository) — surfaced as a usage error (exit 2)."""
    changed: Set[str] = set()
    seen_tops: Set[str] = set()
    for target in targets:
        anchor = target if target.is_dir() else target.parent
        try:
            top = subprocess.run(
                ['git', '-C', str(anchor), 'rev-parse', '--show-toplevel'],
                capture_output=True, text=True, check=True).stdout.strip()
            if top in seen_tops:
                continue
            seen_tops.add(top)
            diff = subprocess.run(
                ['git', '-C', top, 'diff', '--name-only', diff_base, '--'],
                capture_output=True, text=True, check=True).stdout
        except (OSError, subprocess.CalledProcessError) as exc:
            stderr = getattr(exc, 'stderr', '') or ''
            raise ValueError(
                '--diff-base {!r}: git diff failed under {} ({})'.format(
                    diff_base, anchor, stderr.strip() or exc))
        changed.update(line.strip() for line in diff.splitlines()
                       if line.strip())
    return changed


def _restrict_to_diff(report: Report, diff_base: str,
                      targets: Sequence[Path]) -> Report:
    """Drop findings whose file did not change vs ``diff_base`` (matched by
    path suffix either way, so display paths and repo-relative git paths
    agree without a common anchor)."""
    changed = _changed_paths(diff_base, targets)

    def touched(display: str) -> bool:
        for path in changed:
            if (display == path or display.endswith('/' + path)
                    or path.endswith('/' + display)):
                return True
        return False

    kept = [finding for finding in report.findings
            if touched(finding.path)]
    note = ('--diff-base {}: reporting {} of {} finding(s) in {} changed '
            'file(s)'.format(diff_base, len(kept), len(report.findings),
                             len(changed)))
    return replace(report, findings=kept,
                   notes=list(report.notes) + [note])


def build_parser() -> argparse.ArgumentParser:
    """The pipecheck argument parser (split out for doc/tests)."""
    parser = argparse.ArgumentParser(
        prog='pipecheck',
        description='AST-based invariant analyzer for the petastorm_tpu '
                    'cross-process data plane (docs/static-analysis.md)')
    parser.add_argument('paths', nargs='*',
                        help='files/directories to analyze (default: the '
                             'installed petastorm_tpu package)')
    parser.add_argument('--json', action='store_true',
                        help='print one JSON document instead of the '
                             'flake8-style listing')
    parser.add_argument('--rules',
                        help='comma-separated rule subset (see --list-rules)')
    parser.add_argument('--diff-base', metavar='REF',
                        help='report only findings in files changed vs this '
                             'git ref (analysis still runs whole-program; '
                             'keeps the CI gate fast as the tree grows)')
    parser.add_argument('--list-rules', action='store_true',
                        help='print the rule catalog and exit')
    parser.add_argument('--mypy-ini',
                        help='explicit mypy.ini path for the mypy-ratchet '
                             'rule (default: walk up from the analyzed '
                             'paths)')
    parser.add_argument('--manifest',
                        help='explicit strict-module manifest path (default: '
                             'the packaged analysis/strict_modules.txt)')
    return parser


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Console entry: parse args, run the analysis, print, return the exit
    code (0 clean / 1 findings / 2 usage error)."""
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_rules:
        for cls in ALL_RULES:
            print('{:24s} {}'.format(cls.name, cls.description))
        return 0
    selected: Optional[List[str]] = None
    if args.rules:
        selected = [name.strip() for name in args.rules.split(',')
                    if name.strip()]
    try:
        report = run_pipecheck(paths=args.paths or None, rules=selected,
                               mypy_ini=args.mypy_ini,
                               manifest=args.manifest,
                               diff_base=args.diff_base)
    except ValueError as exc:
        print('pipecheck: {}'.format(exc), file=sys.stderr)
        return 2
    print(report.to_json() if args.json else report.format_human())
    return 0 if report.clean else 1


if __name__ == '__main__':
    sys.exit(main())
