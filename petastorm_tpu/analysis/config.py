"""pipecheck configuration: which files play which role in each invariant.

The rule *mechanisms* (set matching over produced/consumed wire literals,
catalog membership, clock/lock/exception discipline — ``analysis/rules/``)
are generic; this module pins them to the petastorm_tpu data plane: which
basenames are the ZMQ protocol peers, which modules must never read the wall
clock directly, where the telemetry catalog and the mypy ratchet manifest
live. Matching is by **basename / path suffix**, not import path, so fixture
trees (``tests/data/pipecheck/``) and mutated copies under a temp dir
exercise exactly the shipped configuration.

Override points (CLI flags map onto these): ``mypy_ini_path`` /
``manifest_path`` for the ratchet rule; everything else via
:func:`dataclasses.replace` from test code.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

#: files forming the cross-process ZMQ peer set: every message kind one of
#: them produces (``send`` / ``send_multipart``) must be dispatched on by one
#: of them, and vice versa (docs/static-analysis.md, protocol-conformance)
PROTOCOL_PEER_FILES: Tuple[str, ...] = ('process_pool.py',
                                        'process_worker_main.py')

#: the disaggregated input service's peer set (docs/service.md): dispatcher,
#: service worker and client transport speak their own kind literals over
#: TCP — an independent group, set-matched exactly like the in-process pair
SERVICE_PEER_FILES: Tuple[str, ...] = ('dispatcher.py', 'service_worker.py',
                                       'service_client.py')

#: basenames whose ``to_bytes``/``from_bytes`` JSON descriptor key sets must
#: match (shm slot descriptors; service registration/shm-result descriptors)
DESCRIPTOR_FILES: Tuple[str, ...] = ('shm_ring.py', 'wire.py')

#: modules under the injectable-clock discipline: direct ``time.time()`` /
#: ``time.monotonic()`` / ``time.perf_counter()`` calls are findings — retry,
#: backoff, deadline and breaker arithmetic must flow through the injected
#: ``clock``/``sleep`` callables so tests stay deterministic (PR-4
#: discipline). ``cost_schedule.py`` is here for a sharper reason: the
#: cost-aware schedule must be a pure function of (ledger, policy, seed) —
#: a wall-clock read anywhere in it would make epoch order irreproducible
#: (docs/performance.md "Cost-aware scheduling").
#: the storage ingest engine joins the discipline: hedge-deadline and
#: fetch-duration arithmetic must flow through the injected ``clock`` so
#: the hedging tests stay deterministic (docs/performance.md "Object-store
#: ingest engine")
CLOCK_DISCIPLINED_FILES: Tuple[str, ...] = ('resilience.py',
                                            'cost_schedule.py',
                                            'range_planner.py',
                                            'fetcher.py',
                                            'metadata_cache.py',
                                            'engine.py')

#: directory name marking worker/data-plane process code, where the
#: exception-hygiene bar is highest: a broad except that can swallow needs an
#: explicit reason comment even when it logs
WORKER_DIR: str = 'workers'

#: basenames of data-path modules where ``raise Exception(...)`` /
#: ``raise BaseException(...)`` are findings (use the errors.py taxonomy)
DATAPATH_FILES: Tuple[str, ...] = ('reader_worker.py', 'reader.py',
                                   'cache.py', 'fs_utils.py',
                                   'resilience.py', 'cost_schedule.py',
                                   'range_planner.py', 'fetcher.py',
                                   'metadata_cache.py', 'engine.py')

#: where the telemetry stage/counter catalog lives (path suffix); the rule
#: falls back to the installed ``petastorm_tpu.telemetry.spans`` when the
#: analyzed tree does not contain it
STAGE_CATALOG_SUFFIX: str = 'telemetry/spans.py'

#: where the declared quarantine-reason registry lives (path suffix)
QUARANTINE_REGISTRY_SUFFIX: str = 'resilience.py'

#: where the durable dispatcher ledger's declared record-kind registry
#: lives (path suffix): every ``append_record('x')`` / ``_journal('x')``
#: call site and every ``kind == 'x'`` replay compare must name a kind in
#: its ``LEDGER_RECORD_KINDS`` tuple (protocol-conformance rule,
#: docs/service.md "Failure modes")
LEDGER_FILE_SUFFIX: str = 'ledger.py'

#: where the topology membership journal's declared record-kind registry
#: lives (path suffix): the same two-sided conformance contract as the
#: dispatcher ledger, against ``TOPOLOGY_RECORD_KINDS`` (protocol-
#: conformance rule, docs/robustness.md "Elastic pod-scale sharding")
TOPOLOGY_FILE_SUFFIX: str = 'topology.py'

#: where the cost profiler's declared stage tuple lives (path suffix); its
#: ``COST_STAGES`` entries must be a subset of the spans catalog's ``STAGES``
#: (telemetry-names rule, docs/observability.md "Cost profiler")
COST_MODEL_SUFFIX: str = 'telemetry/cost_model.py'

#: where the autotuner's knob-id catalog lives (path suffix); ``Knob(...)``
#: constructions and ``catalog.knob(...)`` references are checked against its
#: ``KNOB_IDS`` tuple (telemetry-names rule, docs/autotuning.md)
KNOB_CATALOG_SUFFIX: str = 'autotune/knobs.py'

#: mypy option names a ratchet entry's section must set to True
STRICT_FLAGS: Tuple[str, ...] = ('disallow_untyped_defs',
                                 'disallow_incomplete_defs',
                                 'no_implicit_optional',
                                 'warn_return_any')

#: leakable resource table for the resource-lifecycle rule. Each row is
#: ``(constructor, release_methods, releaser_funcs, exempt_kwargs, label,
#: paths_sensitive)``: a call whose terminal name equals ``constructor``
#: acquires the resource; a call of one of ``release_methods`` on the
#: binding (or passing the binding to a function named in
#: ``releaser_funcs``) releases it; a truthy keyword from ``exempt_kwargs``
#: at the construction site waives tracking (``Thread(daemon=True)`` dies
#: with the process); ``paths_sensitive`` rows must ALSO release on
#: exception paths (finally / ``with``), the PR-2 ``/dev/shm`` leak class.
#: The pseudo-constructors ``mkstemp:fd`` / ``mkstemp:path`` describe the
#: two halves of ``fd, path = tempfile.mkstemp(...)``.
LEAKABLE_TYPES: Tuple[Tuple[str, Tuple[str, ...], Tuple[str, ...],
                            Tuple[str, ...], str, bool], ...] = (
    ('SharedMemory', ('close', 'unlink'), (), (),
     'shared-memory segment', True),
    ('TemporaryDirectory', ('cleanup',), (), (),
     'temporary directory', True),
    ('Thread', ('join',), (), ('daemon',), 'thread', False),
    ('Context', ('term', 'destroy'), (), (), 'zmq context', True),
    ('socket', ('close',), (), (), 'socket', True),
    ('TokenLedger', ('close',), (), (), 'token ledger', False),
    ('MembershipJournal', ('close', 'abandon'), (), (),
     'membership journal', False),
    ('ShmRing', ('close', 'close_and_unlink', 'unlink'), (), (),
     'shm ring', False),
    ('mkstemp:fd', (), ('fdopen', 'close'), (),
     'mkstemp file descriptor', True),
    ('mkstemp:path', (), ('replace', 'unlink', 'remove', 'rename'), (),
     'mkstemp temp path', True),
)

#: lineage-covered modules (path suffixes, ``/``-anchored) under the
#: determinism discipline: unseeded randomness, unordered iteration feeding
#: an order-sensitive sink, and ``id()``-keyed containers are findings —
#: the static twin of ``compose_global_digest``'s runtime proof
#: (docs/robustness.md "Provable determinism at any topology")
DETERMINISM_MODULES: Tuple[str, ...] = ('reader.py',
                                        'workers/ventilator.py',
                                        'schedule/cost_schedule.py',
                                        'parallel/topology.py',
                                        'parallel/loader.py',
                                        'parallel/inmem_loader.py',
                                        'service/dispatcher.py',
                                        'telemetry/lineage.py')

#: call names whose argument order IS the reproducibility contract: digest
#: folds, journal appends, shard deals, progress notes. Unordered iteration
#: (sets, ``os.listdir``, ``glob``, raw dict views) flowing into one of
#: these without an intervening ``sorted()`` is a determinism finding.
ORDER_SENSITIVE_SINKS: Tuple[str, ...] = ('append_record', '_journal',
                                          'fold_digest', 'deal_assignment',
                                          'reshard_assignments',
                                          'note_join', 'note_leave',
                                          'note_progress', 'note_reshard',
                                          'note_lease')

#: the append-only CRC-framed journals and their closed record registries,
#: for the journal-discipline rule. Each row is ``(file_suffix,
#: registry_name, writer_call_names, kind_label, import_name)``: inside the
#: journal module every ``kind == 'x'`` replay compare, and everywhere any
#: literal first argument to one of ``writer_call_names``, must name an
#: entry of ``registry_name`` (declared in the journal module; resolved
#: from the installed ``import_name`` when the analyzed tree lacks it).
JOURNAL_REGISTRIES: Tuple[Tuple[str, str, Tuple[str, ...], str, str],
                          ...] = (
    ('ledger.py', 'LEDGER_RECORD_KINDS', ('append_record', '_journal'),
     'ledger record kind', 'petastorm_tpu.service.ledger'),
    ('topology.py', 'TOPOLOGY_RECORD_KINDS', ('append_record', '_journal'),
     'topology record kind', 'petastorm_tpu.parallel.topology'),
    ('history.py', 'RUN_RECORD_OWNERS', ('build_run_record',),
     'run-record owner', 'petastorm_tpu.telemetry.history'),
)


@dataclass(frozen=True)
class AnalysisConfig:
    """Resolved configuration for one pipecheck run (defaults above)."""

    protocol_peer_files: Tuple[str, ...] = PROTOCOL_PEER_FILES
    service_peer_files: Tuple[str, ...] = SERVICE_PEER_FILES
    descriptor_files: Tuple[str, ...] = DESCRIPTOR_FILES
    clock_disciplined_files: Tuple[str, ...] = CLOCK_DISCIPLINED_FILES
    worker_dir: str = WORKER_DIR
    datapath_files: Tuple[str, ...] = DATAPATH_FILES
    stage_catalog_suffix: str = STAGE_CATALOG_SUFFIX
    quarantine_registry_suffix: str = QUARANTINE_REGISTRY_SUFFIX
    ledger_file_suffix: str = LEDGER_FILE_SUFFIX
    topology_file_suffix: str = TOPOLOGY_FILE_SUFFIX
    knob_catalog_suffix: str = KNOB_CATALOG_SUFFIX
    cost_model_suffix: str = COST_MODEL_SUFFIX
    strict_flags: Tuple[str, ...] = STRICT_FLAGS
    leakable_types: Tuple[Tuple[str, Tuple[str, ...], Tuple[str, ...],
                                Tuple[str, ...], str, bool],
                          ...] = LEAKABLE_TYPES
    determinism_modules: Tuple[str, ...] = DETERMINISM_MODULES
    order_sensitive_sinks: Tuple[str, ...] = ORDER_SENSITIVE_SINKS
    journal_registries: Tuple[Tuple[str, str, Tuple[str, ...], str, str],
                              ...] = JOURNAL_REGISTRIES
    #: explicit mypy.ini path; None = walk up from the analyzed roots
    mypy_ini_path: Optional[str] = None
    #: explicit ratchet manifest path; None = the packaged
    #: ``analysis/strict_modules.txt``
    manifest_path: Optional[str] = None


def default_config() -> AnalysisConfig:
    """The shipped configuration (what the CLI and tier-1 self-check use)."""
    return AnalysisConfig()
