"""pipecheck rule registry: the shipped rule families, by name.

Rules register here so the CLI (``--rules``, ``--list-rules``), the doctor
summary and the bench check phase all see one canonical set. Adding a rule =
subclass :class:`petastorm_tpu.analysis.core.Rule` in a module under this
package and list it in :data:`ALL_RULES` (docs/static-analysis.md "Adding a
rule").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from petastorm_tpu.analysis.core import Rule
from petastorm_tpu.analysis.rules.clock import ClockDisciplineRule
from petastorm_tpu.analysis.rules.determinism import DeterminismRule
from petastorm_tpu.analysis.rules.exceptions import ExceptionHygieneRule
from petastorm_tpu.analysis.rules.journal import JournalDisciplineRule
from petastorm_tpu.analysis.rules.lifecycle import ResourceLifecycleRule
from petastorm_tpu.analysis.rules.locks import LockDisciplineRule
from petastorm_tpu.analysis.rules.protocol import ProtocolConformanceRule
from petastorm_tpu.analysis.rules.ratchet import MypyRatchetRule
from petastorm_tpu.analysis.rules.telemetry_names import TelemetryNamesRule

#: every shipped rule class, in the order reports list them
ALL_RULES: List[Type[Rule]] = [
    ProtocolConformanceRule,
    TelemetryNamesRule,
    ClockDisciplineRule,
    ExceptionHygieneRule,
    LockDisciplineRule,
    ResourceLifecycleRule,
    DeterminismRule,
    JournalDisciplineRule,
    MypyRatchetRule,
]

#: rule name -> class
RULES_BY_NAME: Dict[str, Type[Rule]] = {cls.name: cls for cls in ALL_RULES}


def default_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the shipped rules; ``names`` (when given) selects a
    subset and raises ``ValueError`` on an unknown rule name."""
    if names is None:
        return [cls() for cls in ALL_RULES]
    unknown = [name for name in names if name not in RULES_BY_NAME]
    if unknown:
        raise ValueError('unknown rule(s) {}; known: {}'.format(
            unknown, sorted(RULES_BY_NAME)))
    return [RULES_BY_NAME[name]() for name in names]
