"""resource-lifecycle: every acquired leakable resource reaches its release.

The runtime failure class this pins down statically is the one that has
shipped twice: a ``/dev/shm`` segment created and then orphaned when an
exception fired between ``SharedMemory(create=True)`` and ``close()``
(the PR-2 leak), and worker rings left undrained at teardown (PR 6). The
rule walks the call-graph resource summaries (:mod:`..callgraph`,
config ``LEAKABLE_TYPES``) and reports, per acquisition:

- **never released** — no release, no escape: the object is simply dropped
  (``Thread`` without ``join`` and without ``daemon=True``, a socket bound
  to a local and forgotten);
- **leaks on exception paths** — released on the straight-line path only,
  while a may-raise call sits between the acquire and the release; for
  ``paths_sensitive`` resource types the release must be in a ``finally``
  or the acquisition context-managed (``with`` / ``closing``);
- **rebound before release** — the binding was reassigned or ``del``'d
  while still owning a live resource (the v2 rebinding bugfix: the old
  object can never be released again through that name);
- **escapes to an owner that never releases it** — ``self._x = acquire()``
  is fine *only if* some method of the class releases ``self._x``
  (close/join/stop/del or handing it to a helper) — escape-to-owner.

Escapes to a caller (returned), into a container, or as an argument to a
non-releasing call transfer ownership and end tracking — the receiving
scope is analyzed on its own terms (a function that acquires-and-returns
makes each of its call sites an acquisition, so a leak through a helper
factory is still caught at the caller).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from petastorm_tpu.analysis.callgraph import (CallGraph, FunctionSummary,
                                              Tracked, _LeakSpecView,
                                              _leak_specs, build_summaries,
                                              get_callgraph)
from petastorm_tpu.analysis.core import AnalysisContext, Finding, Rule


class ResourceLifecycleRule(Rule):
    """Leakable-resource acquire/release/escape discipline (module doc)."""

    name = 'resource-lifecycle'
    description = ('acquired leakable resources (shm segments, sockets, '
                   'threads, journals, temp dirs) must reach their release '
                   'on all paths or escape to an owner that releases them')

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        graph = get_callgraph(ctx)
        summaries = build_summaries(ctx, graph)
        specs = _leak_specs(ctx.config)
        findings: List[Finding] = []
        for summary in summaries.values():
            info = summary.info
            display = info.module.display
            for tracked in summary.tracked:
                spec = specs[tracked.spec_index]
                finding = self._judge(tracked, spec, summary, graph)
                if finding is not None:
                    findings.append(Finding(self.name, display,
                                            tracked.line, finding))
        return findings

    def _judge(self, tracked: Tracked, spec: _LeakSpecView,
               summary: FunctionSummary,
               graph: CallGraph) -> Optional[str]:
        """The finding message for one acquisition, or None when clean."""
        label = spec.label
        release_words = ', '.join(
            tuple('.{}()'.format(r) for r in spec.releases)
            + tuple('{}(...)'.format(r) for r in spec.releaser_funcs))
        if tracked.exempt:
            return None
        if tracked.killed_line is not None:
            return ('{} acquired here is rebound/deleted at line {} before '
                    'being released — the original object leaks; release it '
                    '({}) before reusing the name'.format(
                        label, tracked.killed_line, release_words))
        if tracked.escaped_self_attr is not None:
            info = summary.info
            if info.class_name is not None and not graph.owner_releases(
                    info.module, info.class_name, tracked.escaped_self_attr):
                return ('{} escapes to self.{} but no method of {} releases '
                        'it ({}) — the owner must take over the lifecycle '
                        'it was handed'.format(
                            label, tracked.escaped_self_attr,
                            info.class_name, release_words))
            return None
        if tracked.escaped:
            return None
        if not tracked.released and tracked.release_in_finally:
            return ('{} is released only on the error path (inside an '
                    'except handler) — the normal path leaks it; release '
                    'it ({}) on the straight-line path too'.format(
                        label, release_words))
        if not tracked.released:
            return ('{} acquired here is never released ({}) and never '
                    'escapes — it leaks on every path'.format(
                        label, release_words))
        if (spec.paths_sensitive
                and not tracked.release_in_finally
                and tracked.risk_line is not None):
            return ('{} is released only on the normal path — the call at '
                    'line {} can raise between the acquire and the release, '
                    'leaking it; move the release into a finally/with'.format(
                        label, tracked.risk_line))
        return None
