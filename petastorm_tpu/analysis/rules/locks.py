"""lock-discipline: no blocking calls while holding a lock.

The data plane's locks (``_state_lock`` in the process pool, the registry
shard locks, the loader accounting lock) guard *bookkeeping*, and the
comments around them promise short critical sections. A blocking call inside
``with lock:`` — a sleep, a socket receive, a thread join — turns every
other participant's fast path into that call's wait, and under the consumer/
ventilator thread split it is one step from deadlock (the PR-1 pool design
notes say exactly this about ROUTER sends vs ``_state_lock``).

Detection: a ``with`` item whose context expression's terminal name is
lock-ish (``lock``, ``*_lock``, ``*lock``) opens a critical section; inside
its body (not descending into nested ``def``/``lambda``) these calls are
findings:

- ``time.sleep(...)`` / bare ``sleep(...)``
- socket receives: ``.recv(...)``, ``.recv_multipart(...)``,
  ``.recv_string(...)``, ``.recv_pyobj(...)``, ``.recv_json(...)``,
  ``.accept(...)``
- thread/process joins: ``.join()`` with no arguments, a numeric-literal
  timeout, or a ``timeout=`` keyword (the argument heuristic is what keeps
  ``', '.join(parts)`` and ``os.path.join(a, b)`` out)
- ``subprocess.run/call/check_call/check_output(...)`` and ``input()``

``Condition.wait`` is deliberately NOT flagged: condition variables must be
waited on with their lock held — that is their protocol, not a violation.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule,
                                         walk_skipping_functions)

_RECV_ATTRS = frozenset({'recv', 'recv_multipart', 'recv_string',
                         'recv_pyobj', 'recv_json', 'accept'})
_SUBPROCESS_FUNCS = frozenset({'run', 'call', 'check_call', 'check_output'})


def _terminal_name(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def is_lockish(node: ast.expr) -> bool:
    """True when a ``with`` context expression names a lock (by the repo's
    naming convention: ``lock``, ``_lock``, ``state_lock``, ...)."""
    name = _terminal_name(node)
    if name is None:
        return False
    lowered = name.lower()
    return lowered == 'lock' or lowered.endswith('lock')


def _blocking_call(node: ast.Call) -> Optional[str]:
    """A human-readable description when ``node`` is a blocking call."""
    func = node.func
    if isinstance(func, ast.Name):
        if func.id == 'sleep':
            return 'sleep()'
        if func.id == 'input':
            return 'input()'
        return None
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == 'sleep':
        return '{}.sleep()'.format(_terminal_name(func.value) or '?')
    if func.attr in _RECV_ATTRS:
        return '.{}()'.format(func.attr)
    if (func.attr in _SUBPROCESS_FUNCS
            and isinstance(func.value, ast.Name)
            and func.value.id == 'subprocess'):
        return 'subprocess.{}()'.format(func.attr)
    if func.attr == 'join':
        if not node.args and not node.keywords:
            return '.join()'
        if any(kw.arg == 'timeout' for kw in node.keywords):
            return '.join(timeout=...)'
        if (len(node.args) == 1 and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, (int, float))):
            return '.join({})'.format(node.args[0].value)
    return None


class LockDisciplineRule(Rule):
    """Flag blocking calls inside ``with lock:`` bodies (module doc)."""

    name = 'lock-discipline'
    description = ('no sleep / blocking recv / join inside a "with lock:" '
                   'body — critical sections must stay bookkeeping-short')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_names = [
                _terminal_name(item.context_expr) or 'lock'
                for item in node.items if is_lockish(item.context_expr)]
            if not lock_names:
                continue
            for inner in walk_skipping_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                blocked = _blocking_call(inner)
                if blocked is not None:
                    findings.append(Finding(
                        self.name, module.display, inner.lineno,
                        'blocking call {} while holding {!r} — move it '
                        'outside the critical section (snapshot under the '
                        'lock, block outside)'.format(
                            blocked, lock_names[0])))
        return findings
