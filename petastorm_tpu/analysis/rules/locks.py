"""lock-discipline: no blocking calls while holding a lock.

The data plane's locks (``_state_lock`` in the process pool, the registry
shard locks, the loader accounting lock) guard *bookkeeping*, and the
comments around them promise short critical sections. A blocking call inside
``with lock:`` — a sleep, a socket receive, a thread join — turns every
other participant's fast path into that call's wait, and under the consumer/
ventilator thread split it is one step from deadlock (the PR-1 pool design
notes say exactly this about ROUTER sends vs ``_state_lock``).

Detection: a ``with`` item whose context expression's terminal name is
lock-ish (``lock``, ``*_lock``, ``*lock``) opens a critical section; inside
its body (not descending into nested ``def``/``lambda``) these calls are
findings:

- ``time.sleep(...)`` / bare ``sleep(...)``
- socket receives: ``.recv(...)``, ``.recv_multipart(...)``,
  ``.recv_string(...)``, ``.recv_pyobj(...)``, ``.recv_json(...)``,
  ``.accept(...)``
- thread/process joins: ``.join()`` with no arguments, a numeric-literal
  timeout, or a ``timeout=`` keyword (the argument heuristic is what keeps
  ``', '.join(parts)`` and ``os.path.join(a, b)`` out)
- ``subprocess.run/call/check_call/check_output(...)`` and ``input()``

**Interprocedural (pipecheck v2):** the same check now follows the call
graph — a call inside the critical section that resolves (confidently —
same module, ``self.method``, or project-unique name) to a function whose
transitive closure reaches a blocking call is flagged too, with the chain
spelled out (``_helper() -> _drain() -> time.sleep()``). A blocking call
two helpers deep inside a ``with lock:`` body is no longer invisible.

``Condition.wait`` is deliberately NOT flagged: condition variables must be
waited on with their lock held — that is their protocol, not a violation.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from petastorm_tpu.analysis.callgraph import (blocking_call, get_callgraph,
                                              terminal_name)
from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule,
                                         walk_skipping_functions)

_blocking_call = blocking_call
_terminal_name = terminal_name


def is_lockish(node: ast.expr) -> bool:
    """True when a ``with`` context expression names a lock (by the repo's
    naming convention: ``lock``, ``_lock``, ``state_lock``, ...)."""
    name = _terminal_name(node)
    if name is None:
        return False
    lowered = name.lower()
    return lowered == 'lock' or lowered.endswith('lock')


def _lock_names(node: ast.AST) -> List[str]:
    return [_terminal_name(item.context_expr) or 'lock'
            for item in getattr(node, 'items', [])
            if is_lockish(item.context_expr)]


class LockDisciplineRule(Rule):
    """Flag blocking calls inside ``with lock:`` bodies (module doc)."""

    name = 'lock-discipline'
    description = ('no sleep / blocking recv / join inside a "with lock:" '
                   'body — directly or through any resolvable helper chain; '
                   'critical sections must stay bookkeeping-short')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            lock_names = _lock_names(node)
            if not lock_names:
                continue
            for inner in walk_skipping_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                blocked = _blocking_call(inner)
                if blocked is not None:
                    findings.append(Finding(
                        self.name, module.display, inner.lineno,
                        'blocking call {} while holding {!r} — move it '
                        'outside the critical section (snapshot under the '
                        'lock, block outside)'.format(
                            blocked, lock_names[0])))
        return findings

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        """The interprocedural pass: calls under a lock that resolve to a
        transitively-blocking function."""
        graph = get_callgraph(ctx)
        findings: List[Finding] = []
        for info in graph.functions.values():
            for node in walk_skipping_functions(info.body()):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                lock_names = _lock_names(node)
                if not lock_names:
                    continue
                for inner in walk_skipping_functions(node.body):
                    if not isinstance(inner, ast.Call):
                        continue
                    if _blocking_call(inner) is not None:
                        continue  # direct — already flagged per-module
                    callee = graph.resolve_call(inner, info)
                    if callee is None or callee.key == info.key:
                        continue
                    chain = graph.blocking_chain(callee)
                    if chain is None:
                        continue
                    findings.append(Finding(
                        self.name, info.module.display, inner.lineno,
                        'call while holding {!r} blocks through its helper '
                        'chain: {} — snapshot under the lock, block '
                        'outside'.format(lock_names[0],
                                         ' -> '.join(chain))))
        return findings
