"""mypy-ratchet: the strict-module manifest and mypy.ini may only move
together, forward.

The repo types new modules strictly (full signatures, no implicit Any) and
records each promotion as a ``[mypy-<module>]`` section with the four strict
flags (``analysis/config.py: STRICT_FLAGS``). Nothing stops a later refactor
from quietly dropping a section — mypy would simply check less. The ratchet
pins the floor:

- ``analysis/strict_modules.txt`` (one module pattern per line, ``#``
  comments allowed) is the checked-in manifest of promoted modules;
- every manifest entry must have a ``[mypy-<entry>]`` section in ``mypy.ini``
  with all strict flags true — a dropped/weakened section is a finding;
- every mypy.ini section that already has all strict flags true must be in
  the manifest — that is how the manifest grows in the same commit as the
  promotion;
- the manifest must be sorted and duplicate-free (merge-conflict hygiene).

Shrinking the manifest itself cannot be seen statically (no git history at
analysis time) — that half of the ratchet is what review of a
``strict_modules.txt`` deletion is for; this rule makes the deletion loud by
forcing it to be explicit.

``mypy.ini`` is located by walking up from the analyzed roots (override:
``--mypy-ini``); when none is found the rule is skipped with a note — e.g.
when pipecheck runs against an installed site-packages tree.
"""

from __future__ import annotations

import configparser
from pathlib import Path
from typing import Iterable, List, Optional, Tuple

from petastorm_tpu.analysis.core import AnalysisContext, Finding, Rule

#: packaged manifest location (next to this rules package)
DEFAULT_MANIFEST = Path(__file__).resolve().parent.parent / 'strict_modules.txt'


def read_manifest(path: Path) -> List[str]:
    """Manifest entries (one per line, ``#`` comments and blanks skipped)."""
    entries = []
    for raw in path.read_text(encoding='utf-8').splitlines():
        line = raw.split('#', 1)[0].strip()
        if line:
            entries.append(line)
    return entries


def locate_mypy_ini(roots: Iterable[Path]) -> Optional[Path]:
    """Walk up (3 levels) from each analyzed root looking for ``mypy.ini``."""
    for root in roots:
        base = root if root.is_dir() else root.parent
        for candidate_dir in [base, *list(base.parents)[:3]]:
            candidate = candidate_dir / 'mypy.ini'
            if candidate.is_file():
                return candidate
    return None


class MypyRatchetRule(Rule):
    """Manifest/mypy.ini strict-section consistency (module doc)."""

    name = 'mypy-ratchet'
    description = ('the strict-module manifest (analysis/strict_modules.txt) '
                   'and mypy.ini strict sections must stay in lockstep; '
                   'strict coverage can only grow')

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        manifest_path = (Path(ctx.config.manifest_path)
                         if ctx.config.manifest_path else DEFAULT_MANIFEST)
        if not manifest_path.is_file():
            return [Finding(self.name, manifest_path.as_posix(), 1,
                            'strict-module manifest not found — the mypy '
                            'ratchet has no floor to enforce')]
        mypy_path = (Path(ctx.config.mypy_ini_path)
                     if ctx.config.mypy_ini_path
                     else locate_mypy_ini(ctx.roots))
        if mypy_path is None or not mypy_path.is_file():
            # not a source checkout; nothing to ratchet against — but say so:
            # a skipped check must never read as a passed one
            ctx.notes.append(
                'mypy-ratchet did NOT run: no mypy.ini found near the '
                'analyzed paths (pass --mypy-ini to point at one)')
            return []
        entries = read_manifest(manifest_path)
        findings: List[Finding] = []
        findings.extend(self._check_manifest_hygiene(manifest_path, entries))
        parser = configparser.ConfigParser()
        try:
            parser.read(mypy_path, encoding='utf-8')
        except configparser.Error as exc:
            return findings + [Finding(
                self.name, mypy_path.as_posix(), 1,
                'mypy.ini is unparseable: {!r}'.format(exc))]
        strict_sections = self._strict_sections(parser, ctx)
        manifest_display = manifest_path.as_posix()
        mypy_display = mypy_path.as_posix()
        for entry in entries:
            section = 'mypy-' + entry
            if not parser.has_section(section):
                findings.append(Finding(
                    self.name, mypy_display, 1,
                    'strict module {!r} is in the ratchet manifest but '
                    '[{}] is missing from mypy.ini — strict coverage may '
                    'only grow'.format(entry, section)))
                continue
            missing = [flag for flag in ctx.config.strict_flags
                       if not parser.getboolean(section, flag, fallback=False)]
            if missing:
                findings.append(Finding(
                    self.name, mypy_display, 1,
                    'strict section [{}] no longer sets {} — the ratchet '
                    'forbids weakening a promoted module'.format(
                        section, ', '.join(missing))))
        for entry in sorted(strict_sections - set(entries)):
            findings.append(Finding(
                self.name, manifest_display, 1,
                'mypy.ini promotes {!r} to strict but the ratchet manifest '
                'does not list it — add it to strict_modules.txt so the '
                'promotion cannot be silently reverted'.format(entry)))
        return findings

    def _strict_sections(self, parser: configparser.ConfigParser,
                         ctx: AnalysisContext) -> set:
        out = set()
        for section in parser.sections():
            if not section.startswith('mypy-'):
                continue
            if all(parser.getboolean(section, flag, fallback=False)
                   for flag in ctx.config.strict_flags):
                out.add(section[len('mypy-'):])
        return out

    def _check_manifest_hygiene(self, path: Path,
                                entries: List[str]) -> List[Finding]:
        findings = []
        display = path.as_posix()
        if entries != sorted(entries):
            findings.append(Finding(
                self.name, display, 1,
                'manifest entries are not sorted — keep them ordered so '
                'merges stay conflict-free'))
        duplicates: List[Tuple[str, int]] = []
        seen = set()
        for index, entry in enumerate(entries, start=1):
            if entry in seen:
                duplicates.append((entry, index))
            seen.add(entry)
        for entry, index in duplicates:
            findings.append(Finding(
                self.name, display, index,
                'duplicate manifest entry {!r}'.format(entry)))
        return findings
