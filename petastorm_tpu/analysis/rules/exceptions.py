"""exception-hygiene: broad excepts must justify themselves; data-path raises
must use the errors.py taxonomy.

Two sub-checks:

**Broad-except swallows.** A handler catching ``Exception`` /
``BaseException`` / everything (bare ``except:``) is judged by what its body
can do:

- if every path through the body re-raises, it is a translation/cleanup
  handler — fine; since pipecheck v2 this is judged *interprocedurally*: a
  handler whose trailing statement calls a function that (transitively)
  always raises — a ``_fail()`` / ``_reraise_as()`` helper — counts as
  re-raising, via the call graph's raise closure;
- if it can *swallow* (complete without raising), it must either carry a
  trailing comment on the ``except`` line stating the reason (the house
  convention: ``except Exception:  # noqa: BLE001 - <why>``), or — outside
  worker modules — at least log (``logger.*`` / ``warnings.warn`` /
  ``traceback.print_exc``);
- inside worker/data-plane process modules (``workers/``) logging alone is
  not enough: a worker loop that eats an exception keeps publishing results
  from unknown state, so the reason must be written at the site.

**Raise taxonomy.** In the data-path modules (``config.DATAPATH_FILES`` and
everything under ``workers/``), ``raise Exception(...)`` /
``raise BaseException(...)`` are findings: generic raises carry zero
machine-readable structure, while the :mod:`petastorm_tpu.errors` taxonomy
is what the retry classifier, quarantine ledger and doctor key on.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Sequence

from petastorm_tpu.analysis.callgraph import (CallGraph, FunctionInfo,
                                              get_callgraph)
from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule,
                                         walk_skipping_functions)

_BROAD_NAMES = frozenset({'Exception', 'BaseException'})

#: bare tool markers that justify nothing by themselves — a reason must
#: follow (``# noqa: BLE001 - <why>``), or the comment must be actual prose
_MARKER_RE = re.compile(
    r'^(noqa(:\s*[A-Z0-9, ]+)?|type:\s*ignore(\[[^\]]*\])?'
    r'|pragma:\s*no\s*cover)\s*', re.IGNORECASE)
_LOG_ATTRS = frozenset({'debug', 'info', 'warning', 'error', 'exception',
                        'critical', 'log', 'warn', 'print_exc'})
_GENERIC_RAISES = frozenset({'Exception', 'BaseException'})


def _exception_names(type_node: ast.expr) -> List[str]:
    """Exception class names a handler catches (``Name``/``Attribute``
    terminals; tuples flattened)."""
    if isinstance(type_node, ast.Tuple):
        out: List[str] = []
        for element in type_node.elts:
            out.extend(_exception_names(element))
        return out
    if isinstance(type_node, ast.Name):
        return [type_node.id]
    if isinstance(type_node, ast.Attribute):
        return [type_node.attr]
    return []


def is_broad_handler(handler: ast.ExceptHandler) -> bool:
    """True for ``except:``, ``except Exception``, ``except BaseException``
    (including inside a tuple)."""
    if handler.type is None:
        return True
    return any(name in _BROAD_NAMES
               for name in _exception_names(handler.type))


def always_raises(stmts: Sequence[ast.stmt]) -> bool:
    """Conservatively true when every path through ``stmts`` ends in a
    ``raise`` — i.e. the handler translates/annotates, never swallows."""
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, ast.Raise):
        return True
    if isinstance(last, ast.If):
        return (bool(last.orelse) and always_raises(last.body)
                and always_raises(last.orelse))
    if isinstance(last, ast.With):
        return always_raises(last.body)
    return False


def comment_states_reason(comment: Optional[str]) -> bool:
    """True when a trailing comment actually *states a reason*: after
    stripping bare tool markers (``noqa``/``type: ignore``/``pragma: no
    cover``), at least two words of prose remain. ``# TODO`` or a lone
    ``# noqa: BLE001`` justify nothing."""
    if not comment:
        return False
    text = comment.lstrip('#').strip()
    text = _MARKER_RE.sub('', text).lstrip('-—:').strip()
    return len(text.split()) >= 2


def body_logs(stmts: Sequence[ast.stmt]) -> bool:
    """True when the handler body contains a logging/warning call."""
    for node in walk_skipping_functions(stmts):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _LOG_ATTRS):
            return True
    return False


class ExceptionHygieneRule(Rule):
    """Broad-except and raise-taxonomy checks (module doc)."""

    name = 'exception-hygiene'
    description = ('broad excepts that can swallow need a reason comment '
                   '(workers/) or at least logging (elsewhere); data-path '
                   'raises must use the errors.py taxonomy, not bare '
                   'Exception')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        in_workers = ('/' + ctx.config.worker_dir + '/') in module.posix()
        if in_workers or module.name in ctx.config.datapath_files:
            findings.extend(self._check_raises(module))
        return findings

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        """The broad-except pass runs here so the raise closure can accept
        handlers that delegate to an always-raising helper."""
        graph = get_callgraph(ctx)
        findings: List[Finding] = []
        for module in ctx.modules:
            in_workers = ('/' + ctx.config.worker_dir
                          + '/') in module.posix()
            enclosing = self._handler_owners(graph, module)
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if not is_broad_handler(node):
                    continue
                if (comment_states_reason(module.comments.get(node.lineno))
                        and node.lineno not in module.suppressions):
                    # reason documented at the site (house style); a bare
                    # marker or `# TODO` is not a reason, and a pipecheck
                    # directive instead flows through the framework's
                    # suppression accounting, so opt-outs stay countable
                    continue
                caller = enclosing.get(id(node)) or FunctionInfo(
                    module=module, node=module.tree, name='<module>',
                    qualname='<module>', class_name=None)
                if graph.stmts_always_raise(node.body, caller):
                    continue  # translation handler, never swallows
                if in_workers:
                    findings.append(Finding(
                        self.name, module.display, node.lineno,
                        'broad except can swallow in a worker module: '
                        'narrow the type, re-raise, or state the reason in '
                        'a trailing comment on this line'))
                elif not body_logs(node.body):
                    findings.append(Finding(
                        self.name, module.display, node.lineno,
                        'broad except swallows without logging or '
                        're-raise: narrow the type, log-and-continue, or '
                        'add a reason comment'))
        return findings

    @staticmethod
    def _handler_owners(graph: CallGraph, module: SourceModule
                        ) -> dict:
        """Map each except-handler (by ``id``) to its innermost enclosing
        function — the resolution scope for the raise closure (smallest
        line span wins, so a handler in a nested def resolves there)."""
        owners: dict = {}
        spans: dict = {}
        for info in graph.functions.values():
            if info.module is not module:
                continue
            start = int(getattr(info.node, 'lineno', 0))
            end = int(getattr(info.node, 'end_lineno', start) or start)
            span = end - start
            for inner in ast.walk(info.node):  # type: ignore[arg-type]
                if not isinstance(inner, ast.ExceptHandler):
                    continue
                key = id(inner)
                if key not in owners or span < spans[key]:
                    owners[key] = info
                    spans[key] = span
        return owners

    def _check_raises(self, module: SourceModule) -> List[Finding]:
        findings = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            raised = None
            if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
                raised = exc.func.id
            elif isinstance(exc, ast.Name):
                raised = exc.id
            if raised in _GENERIC_RAISES:
                findings.append(Finding(
                    self.name, module.display, node.lineno,
                    'data-path code raises bare {} — raise a '
                    'petastorm_tpu.errors type (or a specific builtin) so '
                    'the retry classifier and quarantine ledger can key on '
                    'it'.format(raised)))
        return findings
