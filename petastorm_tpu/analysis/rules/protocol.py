"""protocol-conformance: producer/consumer set matching over the wire literals.

The cross-process data plane speaks in string/bytes literals that no type
checker relates to each other: ZMQ message ``kind`` prefixes
(``process_worker_main.py`` publishes ``b'result_shm'``,
``process_pool.py`` dispatches on ``kind == MSG_RESULT_SHM``), shm descriptor
JSON keys (``ShmSlotDescriptor.to_bytes``/``from_bytes``), the results-channel
sidecar keys (``ArrowIpcSerializer.serialize`` writes ``meta_extra``,
``deserialize`` reads them back), and quarantine ``reason`` values. A typo or
a one-sided addition compiles, imports, and fails only at runtime on the slow
path — the exact drift class this rule pins down statically:

- **message kinds**: every bytes literal produced as a kind (first or second
  element of a ``send_multipart`` list, or a plain ``send``) by one of the
  protocol peer files must be *dispatched on* (compared against a kind
  expression: ``kind``, ``frames[0]``/``frames[1]``, ``...recv()``) by a peer,
  and vice versa. Cross-checks fire only when at least two peer files are in
  the analyzed set, so a lone fixture file is never half-judged. Two
  independent peer groups are matched: the in-process pool pair
  (``process_pool.py``/``process_worker_main.py``) and the input service's
  trio (``dispatcher.py``/``service_worker.py``/``service_client.py`` —
  docs/service.md), each against its own kind set.
- **shm descriptor keys**: the JSON keys ``to_bytes`` writes must equal the
  keys ``from_bytes`` reads (file: ``shm_ring.py``).
- **sidecar keys**: the ``meta_extra`` keys ``serialize`` writes must each be
  read by ``deserialize`` (file: ``serializers.py``; the codec's own
  ``num_rows``/``columns`` are allowed extra reads).
- **quarantine reasons**: every ``QuarantineRecord(..., reason='x')`` literal
  must appear in the ``QUARANTINE_REASONS`` registry in ``resilience.py``.

The journal record-kind registries (dispatcher ledger, topology membership
journal, run historian) moved to the dedicated ``journal-discipline`` rule
in pipecheck v2 — one data-driven check over config ``JOURNAL_REGISTRIES``
instead of a per-journal method here.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule, const_bytes, const_str,
                                         extract_string_tuple,
                                         module_bytes_constants)

#: extra keys ``deserialize`` may read that ``serialize`` does not write via
#: ``meta_extra`` — they are written by the shared columnar codec
#: (``encode_columnar``), not the sidecar dict
_CODEC_META_KEYS = frozenset({'num_rows', 'columns'})

#: names whose subscripts ``[0]``/``[1]`` count as kind expressions
_FRAME_NAMES = frozenset({'frames', 'parts'})


def _unwrap_bytes_call(node: ast.expr) -> ast.expr:
    """Strip a ``bytes(...)``/``memoryview(...)`` wrapper so
    ``bytes(frames[1]) == b'ready'`` matches like ``frames[1] == b'ready'``."""
    while (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
           and node.func.id in ('bytes', 'memoryview') and len(node.args) == 1):
        node = node.args[0]
    return node


def _is_kind_expr(node: ast.expr) -> bool:
    """True when ``node`` reads a message kind: the ``kind`` variable, the
    first/second frame of a multipart receive, or a direct ``recv()``."""
    node = _unwrap_bytes_call(node)
    if isinstance(node, ast.Name):
        return node.id == 'kind'
    if isinstance(node, ast.Subscript):
        base = node.value
        index = node.slice
        if isinstance(base, ast.Name) and base.id in _FRAME_NAMES:
            return (isinstance(index, ast.Constant)
                    and index.value in (0, 1))
        return False
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return node.func.attr == 'recv'
    return False


class _PeerExtraction:
    """Produced/consumed kind literals of one protocol peer file."""

    def __init__(self) -> None:
        self.produced: Dict[bytes, Tuple[str, int]] = {}
        self.consumed: Dict[bytes, Tuple[str, int]] = {}


def extract_wire_kinds(module: SourceModule) -> _PeerExtraction:
    """Collect the message kinds ``module`` produces and dispatches on.

    Produced: bytes literals in the first two elements of a
    ``send_multipart([...])`` list (ROUTER sends put the routing identity
    first, the kind second), resolving a list-valued local name
    (``ready_msg = [b'ready', ...]``) and a ``[...] + frames`` concatenation;
    plus the sole argument of a plain ``send(b'...')``. Consumed: bytes
    literals (or module-level bytes constants, the ``MSG_*`` convention)
    compared with ``==``/``!=`` against a kind expression."""
    out = _PeerExtraction()
    constants = module_bytes_constants(module.tree)
    list_assigns: Dict[str, ast.List] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.List):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    list_assigns[target.id] = node.value

    def resolve_bytes(node: ast.expr) -> Optional[bytes]:
        value = const_bytes(node)
        if value is not None:
            return value
        if isinstance(node, ast.Name):
            return constants.get(node.id)
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        if func.attr == 'send' and node.args:
            value = resolve_bytes(node.args[0])
            if value is not None:
                out.produced.setdefault(value,
                                        (module.display, node.lineno))
        if func.attr == 'send_multipart' and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
                arg = arg.left
            if isinstance(arg, ast.Name):
                arg = list_assigns.get(arg.id, arg)
            if isinstance(arg, ast.List):
                for element in arg.elts[:2]:
                    value = resolve_bytes(element)
                    if value is not None:
                        out.produced.setdefault(
                            value, (module.display, element.lineno))
                        break

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Compare):
            continue
        if not all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            continue
        sides = [node.left] + list(node.comparators)
        if not any(_is_kind_expr(side) for side in sides):
            continue
        for side in sides:
            value = resolve_bytes(side)
            if value is not None:
                out.consumed.setdefault(value, (module.display, node.lineno))
    return out


def _function_defs(tree: ast.Module, name: str) -> List[ast.FunctionDef]:
    return [node for node in ast.walk(tree)
            if isinstance(node, ast.FunctionDef) and node.name == name]


def _dict_keys_written(func: ast.FunctionDef, var_names: Set[str]
                       ) -> Dict[str, int]:
    """str keys of dict literals assigned to ``var_names`` inside ``func``
    (plain and annotated assignments), plus keys of ``var['k'] = ...``
    subscript stores on those names."""
    out: Dict[str, int] = {}
    for node in ast.walk(func):
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = list(node.targets), node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        for target in targets:
            if (isinstance(target, ast.Name) and target.id in var_names
                    and isinstance(value, ast.Dict)):
                for key in value.keys:
                    text = const_str(key) if key is not None else None
                    if text is not None:
                        out.setdefault(text, key.lineno)  # type: ignore[union-attr]
            if (isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in var_names):
                text = const_str(target.slice)
                if text is not None:
                    out.setdefault(text, target.lineno)
    return out


def _dict_keys_read(func: ast.FunctionDef, var_names: Set[str]
                    ) -> Dict[str, int]:
    """str keys read from ``var_names`` inside ``func``: ``var['k']`` loads
    and ``var.get('k', ...)`` calls."""
    out: Dict[str, int] = {}
    for node in ast.walk(func):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)
                and isinstance(node.value, ast.Name)
                and node.value.id in var_names):
            text = const_str(node.slice)
            if text is not None:
                out.setdefault(text, node.lineno)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == 'get'
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in var_names and node.args):
            text = const_str(node.args[0])
            if text is not None:
                out.setdefault(text, node.lineno)
    return out


class ProtocolConformanceRule(Rule):
    """Cross-file producer/consumer matching of wire literals (module doc)."""

    name = 'protocol-conformance'
    description = ('ZMQ message kinds, shm descriptor keys, sidecar keys and '
                   'quarantine reasons must match between producer and '
                   'consumer sites')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        state = ctx.rule_state(self.name)
        if module.name in ctx.config.protocol_peer_files:
            state.setdefault('peers', {})[module.display] = \
                extract_wire_kinds(module)
        if module.name in ctx.config.service_peer_files:
            # the input service's own peer group (dispatcher <-> service
            # worker <-> client transport) — matched independently of the
            # in-process pool pair, same mechanism
            state.setdefault('service_peers', {})[module.display] = \
                extract_wire_kinds(module)
        if module.name in ctx.config.descriptor_files:
            findings.extend(self._check_descriptor_keys(module))
        if module.name == 'serializers.py':
            findings.extend(self._check_sidecar_keys(module))
        findings.extend(
            self._collect_quarantine_reasons(module, state,
                                             ctx.config.quarantine_registry_suffix))
        return findings

    # ------------------------------------------------------- message kinds

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        state = ctx.rule_state(self.name)
        findings: List[Finding] = []
        for group_key in ('peers', 'service_peers'):
            findings.extend(self._match_peer_group(state.get(group_key, {})))
        findings.extend(self._check_quarantine_registry(ctx, state))
        return findings

    def _match_peer_group(self,
                          peers: Dict[str, _PeerExtraction]) -> List[Finding]:
        """Set-match one peer group's produced vs dispatched-on kinds
        (cross-checks fire only with >= 2 peer files in the analyzed set)."""
        findings: List[Finding] = []
        if len(peers) < 2:
            return findings
        produced: Dict[bytes, Tuple[str, int]] = {}
        consumed: Dict[bytes, Tuple[str, int]] = {}
        for extraction in peers.values():
            for kind, site in extraction.produced.items():
                produced.setdefault(kind, site)
            for kind, site in extraction.consumed.items():
                consumed.setdefault(kind, site)
        for kind in sorted(set(produced) - set(consumed)):
            path, line = produced[kind]
            findings.append(Finding(
                self.name, path, line,
                'message kind {!r} is sent but no protocol peer '
                'dispatches on it — a consumer will drop or misroute it '
                '(peers: {})'.format(kind, ', '.join(sorted(peers)))))
        for kind in sorted(set(consumed) - set(produced)):
            path, line = consumed[kind]
            findings.append(Finding(
                self.name, path, line,
                'message kind {!r} is dispatched on but never sent by '
                'any protocol peer — dead dispatch arm or a renamed '
                'producer (peers: {})'.format(kind,
                                              ', '.join(sorted(peers)))))
        return findings

    # --------------------------------------------------- descriptor/sidecar

    def _check_descriptor_keys(self, module: SourceModule) -> List[Finding]:
        writers = _function_defs(module.tree, 'to_bytes')
        readers = _function_defs(module.tree, 'from_bytes')
        if not writers or not readers:
            return []
        written: Dict[str, int] = {}
        read: Dict[str, int] = {}
        for func in writers:
            written.update(_dict_keys_written(func, {'spec'}))
        for func in readers:
            read.update(_dict_keys_read(func, {'spec'}))
        findings = []
        for key in sorted(set(written) - set(read)):
            findings.append(Finding(
                self.name, module.display, written[key],
                'shm descriptor key {!r} is written by to_bytes but never '
                'read by from_bytes'.format(key)))
        for key in sorted(set(read) - set(written)):
            findings.append(Finding(
                self.name, module.display, read[key],
                'shm descriptor key {!r} is read by from_bytes but never '
                'written by to_bytes'.format(key)))
        return findings

    def _check_sidecar_keys(self, module: SourceModule) -> List[Finding]:
        writers = _function_defs(module.tree, 'serialize')
        readers = _function_defs(module.tree, 'deserialize')
        if not writers or not readers:
            return []
        written: Dict[str, int] = {}
        read: Dict[str, int] = {}
        for func in writers:
            written.update(_dict_keys_written(func, {'meta_extra'}))
        for func in readers:
            read.update(_dict_keys_read(func, {'meta'}))
        if not written:
            return []
        findings = []
        for key in sorted(set(written) - set(read)):
            findings.append(Finding(
                self.name, module.display, written[key],
                'sidecar key {!r} is written into meta_extra by serialize '
                'but never read back by deserialize — it silently vanishes '
                'on the consumer side'.format(key)))
        for key in sorted(set(read) - set(written) - _CODEC_META_KEYS):
            findings.append(Finding(
                self.name, module.display, read[key],
                'deserialize reads sidecar key {!r} that serialize never '
                'writes — it is always absent'.format(key)))
        return findings

    # -------------------------------------------------- quarantine reasons

    def _collect_quarantine_reasons(self, module: SourceModule,
                                    state: Dict[str, object],
                                    registry_suffix: str) -> List[Finding]:
        if module.posix().endswith(registry_suffix):
            declared = extract_string_tuple(module.tree, 'QUARANTINE_REASONS')
            if declared is not None:
                state['declared_reasons'] = (declared, module.display)
            return []
        uses = state.setdefault('reason_uses', [])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            func_name = func.id if isinstance(func, ast.Name) else (
                func.attr if isinstance(func, ast.Attribute) else None)
            if func_name != 'QuarantineRecord':
                continue
            for keyword in node.keywords:
                if keyword.arg != 'reason':
                    continue
                value = const_str(keyword.value)
                if value is not None:
                    uses.append((value, module.display,  # type: ignore[attr-defined]
                                 keyword.value.lineno))
        return []

    def _check_quarantine_registry(self, ctx: AnalysisContext,
                                   state: Dict[str, object]) -> List[Finding]:
        declared_entry = state.get('declared_reasons')
        uses = state.get('reason_uses') or []
        if declared_entry is None:
            declared = self._installed_quarantine_reasons(ctx)
            if declared is None:
                return []
        else:
            declared = declared_entry[0]  # type: ignore[index]
        findings = []
        for value, path, line in uses:  # type: ignore[union-attr]
            if value not in declared:
                findings.append(Finding(
                    self.name, path, line,
                    'quarantine reason {!r} is not declared in '
                    'QUARANTINE_REASONS ({}) — dashboards and ledger '
                    'consumers will not recognize it'.format(
                        value, tuple(declared))))
        return findings

    @staticmethod
    def _installed_quarantine_reasons(ctx: AnalysisContext
                                      ) -> Optional[List[str]]:
        """Fallback registry from the installed resilience module's source,
        so fixture trees without a ``resilience.py`` still validate against
        the shipped reason set."""
        try:
            import petastorm_tpu.resilience as resilience_module
            source_path = resilience_module.__file__
            if source_path is None:
                return None
            tree = ast.parse(open(source_path, encoding='utf-8').read())
        except (ImportError, OSError, SyntaxError):
            return None
        return extract_string_tuple(tree, 'QUARANTINE_REASONS')
