"""telemetry-names: every metric name used must exist in the spans catalog.

The telemetry subsystem (docs/observability.md) intentionally creates metrics
on first use — ``registry.observe('decodee', ...)`` raises nothing, it mints
a fresh histogram that no dashboard, no ``attribute_bottleneck`` knob-map
entry, and no doc row knows about. This rule closes that hole statically:

- ``stage_span('x')`` / ``record_stage('x', ...)`` → ``x`` must be in
  ``STAGES`` (``telemetry/spans.py``);
- ``<registry>.observe('x', ...)`` → ``x`` in ``STAGES`` or
  ``SIZE_HISTOGRAMS``;
- ``<registry>.inc('x')`` → ``x`` in ``COUNTERS``;
- ``<registry>.gauge('x')`` → ``x`` in ``GAUGES`` (the SLO / service gauge
  surface — docs/observability.md "Efficiency SLOs");
- ``COST_STAGES`` declared in ``telemetry/cost_model.py`` → every entry in
  ``STAGES`` (a drifted entry would make the cost profiler silently ingest
  nothing for it);
- ``trace_instant('x', ...)`` → ``x`` in ``TRACE_INSTANTS`` (the
  flight-recorder anomaly catalog — docs/observability.md "Flight recorder");
- ``trace_complete('x', ...)`` → ``x`` in ``STAGES`` (a traced span IS a
  stage span, just on the timeline instead of a histogram);
- ``Knob('x', ...)`` / ``<catalog>.knob('x')`` → ``x`` in ``KNOB_IDS``
  (``autotune/knobs.py`` — the autotuner's knob-id catalog,
  docs/autotuning.md): a typo'd knob id names a knob nobody turns.

Conditional names (``'cache_hit' if hit else 'cache_miss'``) check both
branches; non-literal names are skipped (they are register-time plumbing, not
call sites). The catalog is read from the analyzed tree's
``telemetry/spans.py`` when present (so a mutated copy is judged against its
own catalog), else from the installed ``petastorm_tpu.telemetry.spans``.
"""

from __future__ import annotations

import ast
import importlib
from typing import Callable, Iterable, List, Optional, Tuple, TypeVar

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule, extract_string_tuple,
                                         literal_str_values)

#: call forms checked against STAGES (observe_traced is the loader's
#: histogram+timeline dual-emission helper)
_NAME_FUNCS = ('stage_span', 'record_stage', 'trace_complete',
               'observe_traced')
#: call form checked against TRACE_INSTANTS (flight-recorder anomaly markers)
_INSTANT_FUNCS = ('trace_instant',)
#: call forms checked against KNOB_IDS: Knob construction and catalog lookup
_KNOB_CTOR = 'Knob'
_KNOB_ACCESSOR = 'knob'


class _Catalog:
    """The declared telemetry names, split by metric family."""

    def __init__(self, stages: Tuple[str, ...], counters: Tuple[str, ...],
                 size_histograms: Tuple[str, ...],
                 trace_instants: Tuple[str, ...], origin: str,
                 gauges: Tuple[str, ...] = ()) -> None:
        self.stages = frozenset(stages)
        self.counters = frozenset(counters)
        self.size_histograms = frozenset(size_histograms)
        self.trace_instants = frozenset(trace_instants)
        self.gauges = frozenset(gauges)
        self.origin = origin


class _KnobCatalog:
    """The declared autotuner knob ids (``KNOB_IDS`` in autotune/knobs.py)."""

    def __init__(self, knob_ids: Tuple[str, ...], origin: str) -> None:
        self.knob_ids = frozenset(knob_ids)
        self.origin = origin


def _catalog_from_tree(tree: ast.Module, origin: str) -> Optional[_Catalog]:
    stages = extract_string_tuple(tree, 'STAGES')
    if stages is None:
        return None
    counters = extract_string_tuple(tree, 'COUNTERS') or []
    size_histograms = extract_string_tuple(tree, 'SIZE_HISTOGRAMS') or []
    trace_instants = extract_string_tuple(tree, 'TRACE_INSTANTS') or []
    gauges = extract_string_tuple(tree, 'GAUGES') or []
    return _Catalog(tuple(stages), tuple(counters), tuple(size_histograms),
                    tuple(trace_instants), origin, gauges=tuple(gauges))


_CatalogT = TypeVar('_CatalogT')


def _resolve_catalog(ctx: AnalysisContext, state_key: str, suffix: str,
                     installed_module: str,
                     from_tree: Callable[[ast.Module, str],
                                         Optional[_CatalogT]]
                     ) -> Optional[_CatalogT]:
    """The ONE resolution dance every declared-name catalog uses: analyzed
    tree first (a mutated copy is judged against its own declarations), then
    the installed package source, cached in the rule state."""
    state = ctx.rule_state(TelemetryNamesRule.name)
    cached = state.get(state_key)
    if cached is not None:
        return cached  # type: ignore[return-value]
    catalog: Optional[_CatalogT] = None
    module = ctx.find_module(suffix)
    if module is not None:
        catalog = from_tree(module.tree, module.display)
    if catalog is None:
        try:
            installed = importlib.import_module(installed_module)
            path = installed.__file__
            if path is not None:
                tree = ast.parse(open(path, encoding='utf-8').read())
                catalog = from_tree(tree, path)
        except (ImportError, OSError, SyntaxError):
            catalog = None
    if catalog is not None:
        state[state_key] = catalog
    return catalog


def load_catalog(ctx: AnalysisContext) -> Optional[_Catalog]:
    """Resolve the stage/counter catalog (analyzed tree first, then the
    installed package source)."""
    return _resolve_catalog(ctx, 'catalog', ctx.config.stage_catalog_suffix,
                            'petastorm_tpu.telemetry.spans',
                            _catalog_from_tree)


def _knob_catalog_from_tree(tree: ast.Module,
                            origin: str) -> Optional[_KnobCatalog]:
    knob_ids = extract_string_tuple(tree, 'KNOB_IDS')
    if knob_ids is None:
        return None
    return _KnobCatalog(tuple(knob_ids), origin)


def load_knob_catalog(ctx: AnalysisContext) -> Optional[_KnobCatalog]:
    """Resolve the autotuner knob-id catalog — same resolution order as the
    stage catalog, so a mutated copy is judged against its own ids."""
    return _resolve_catalog(ctx, 'knob_catalog',
                            ctx.config.knob_catalog_suffix,
                            'petastorm_tpu.autotune.knobs',
                            _knob_catalog_from_tree)


class TelemetryNamesRule(Rule):
    """Flag telemetry names missing from the spans.py catalog (module doc)."""

    name = 'telemetry-names'
    description = ('stage_span/record_stage/observe/inc/gauge/trace_complete/'
                   'trace_instant names must exist in the telemetry catalog '
                   '(STAGES / COUNTERS / SIZE_HISTOGRAMS / GAUGES / '
                   'TRACE_INSTANTS in telemetry/spans.py); '
                   'Knob()/catalog.knob() ids must exist in KNOB_IDS '
                   '(autotune/knobs.py); the cost profiler\'s COST_STAGES '
                   '(telemetry/cost_model.py) must be a subset of STAGES')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        if module.posix().endswith(ctx.config.stage_catalog_suffix):
            return []  # the catalog itself
        catalog = load_catalog(ctx)
        if catalog is None:
            return []
        knob_catalog = load_knob_catalog(ctx)
        is_knob_catalog_module = module.posix().endswith(
            ctx.config.knob_catalog_suffix)
        findings: List[Finding] = []
        if module.posix().endswith(ctx.config.cost_model_suffix):
            # the cost profiler's declared stage tuple must name real stages
            # — a drifted entry would silently profile nothing
            declared = extract_string_tuple(module.tree, 'COST_STAGES')
            for value in declared or ():
                if value not in catalog.stages:
                    findings.append(Finding(
                        self.name, module.display, 1,
                        'cost-model stage {!r} (COST_STAGES) is not declared '
                        'in STAGES (catalog: {}) — the profiler would '
                        'silently ingest no spans for it'.format(
                            value, catalog.origin)))
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            func_name: Optional[str] = None
            attr_name: Optional[str] = None
            if isinstance(func, ast.Name):
                func_name = func.id
            elif isinstance(func, ast.Attribute):
                attr_name = func.attr
            names: List[Tuple[str, int]] = []
            allowed: Optional[frozenset] = None
            family = ''
            origin = catalog.origin
            if func_name in _NAME_FUNCS or attr_name in _NAME_FUNCS:
                names = literal_str_values(node.args[0])
                allowed = catalog.stages
                family = 'STAGES'
            elif func_name in _INSTANT_FUNCS or attr_name in _INSTANT_FUNCS:
                names = literal_str_values(node.args[0])
                allowed = catalog.trace_instants
                family = 'TRACE_INSTANTS'
            elif attr_name == 'observe':
                names = literal_str_values(node.args[0])
                allowed = catalog.stages | catalog.size_histograms
                family = 'STAGES or SIZE_HISTOGRAMS'
            elif attr_name == 'inc':
                names = literal_str_values(node.args[0])
                allowed = catalog.counters
                family = 'COUNTERS'
            elif attr_name == 'gauge':
                # <registry>.gauge('x') — the SLO/service gauge surface
                # (docs/observability.md "Efficiency SLOs")
                names = literal_str_values(node.args[0])
                allowed = catalog.gauges
                family = 'GAUGES'
            elif ((func_name == _KNOB_CTOR or attr_name == _KNOB_CTOR
                   or attr_name == _KNOB_ACCESSOR)
                  and knob_catalog is not None and not is_knob_catalog_module):
                # Knob('x', ...) construction / catalog.knob('x') lookup
                # (first positional literal; kwarg-only constructions are
                # register-time plumbing and skipped like any non-literal).
                # The catalog module itself is exempt so KNOB_IDS can be
                # grown alongside the Knob builders that first use an id.
                names = literal_str_values(node.args[0])
                allowed = knob_catalog.knob_ids
                family = 'KNOB_IDS'
                origin = knob_catalog.origin
            if not names or allowed is None:
                continue
            for value, line in names:
                if value not in allowed:
                    findings.append(Finding(
                        self.name, module.display, line,
                        'telemetry name {!r} is not declared in {} '
                        '(catalog: {}) — it would mint an orphan metric no '
                        'dashboard or bottleneck map knows'.format(
                            value, family, origin)))
        return findings
