"""journal-discipline: one rule for every append-only CRC-framed journal.

The tree now carries three durable frame journals — the dispatcher token
ledger (``service/ledger.py``), the topology membership journal
(``parallel/topology.py``) and the run historian (``telemetry/history.py``)
— and each is a wire protocol with the FUTURE: the process replaying a
journal may be a newer build than the one that wrote it. PR 18 proved the
registry check for the topology journal inside protocol-conformance; this
rule generalizes it, data-driven over config ``JOURNAL_REGISTRIES``, and
adds the two write/read disciplines the chaos harness assumes:

- **closed record registry**: every literal record kind journaled through a
  writer call (``append_record('x')`` / ``_journal('x')`` /
  ``build_run_record('x')``) anywhere in the tree, and every ``kind ==
  'x'`` replay compare inside the journal module itself, must name an entry
  of the journal's declared registry tuple. Modules are routed to exactly
  one journal: a file matching a journal's own suffix checks against that
  journal's registry, everything else against the ledger's (callers of the
  other journals must go through their typed ``note_*`` wrappers — that
  routing is the same contract PR 18 enforced). When the analyzed tree
  lacks the journal module (fixture trees), the registry is resolved from
  the installed module's source.
- **flush per append**: inside a journal module, any function that writes a
  frame (``.write(...)`` in a module that declares ``_FRAME_HEADER``) must
  also flush (``.flush()`` / ``os.fsync``) before returning — an appended
  frame that sits in userspace buffers is a frame the crash-replay contract
  silently never had.
- **counted drops on CRC mismatch**: in a journal module, an ``if`` branch
  testing a CRC condition that bails (``continue``/``break``/``return``)
  must account the drop (a ``drop``-named counter update or call) — a bare
  ``continue`` silently reads *past* corruption, which is exactly the
  "never guess" failure the chaos harness exists to prevent.
"""

from __future__ import annotations

import ast
import importlib
from typing import Dict, Iterable, List, Optional, Tuple

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule, const_str,
                                         extract_string_tuple,
                                         walk_skipping_functions)


class _JournalSpec:
    """Normalized view over one ``JOURNAL_REGISTRIES`` config row."""

    def __init__(self, row: Tuple[str, str, Tuple[str, ...], str,
                                  str]) -> None:
        (self.suffix, self.registry_name, self.writer_calls,
         self.kind_label, self.import_name) = row

    def matches(self, module: SourceModule) -> bool:
        posix = module.posix()
        return posix.endswith('/' + self.suffix) or posix == self.suffix


def _journal_specs(ctx: AnalysisContext) -> List[_JournalSpec]:
    return [_JournalSpec(row) for row in ctx.config.journal_registries]


def _installed_registry(import_name: str,
                        registry_name: str) -> Optional[List[str]]:
    """Fallback registry parsed from the installed journal module's source,
    so fixture trees still validate against the shipped kind set."""
    try:
        module = importlib.import_module(import_name)
        source_path = module.__file__
        if source_path is None:
            return None
        tree = ast.parse(open(source_path, encoding='utf-8').read())
    except (ImportError, OSError, SyntaxError):
        return None
    return extract_string_tuple(tree, registry_name)


class JournalDisciplineRule(Rule):
    """Registry / flush / drop-accounting checks for the frame journals
    (module doc)."""

    name = 'journal-discipline'
    description = ('append-only frame journals: record kinds must be '
                   'declared in the closed registry, every append must '
                   'flush, and CRC-mismatch drops must be counted — never '
                   'silently skipped')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        state = ctx.rule_state(self.name)
        specs = _journal_specs(ctx)
        owner = next((s for s in specs if s.matches(module)), None)
        if owner is not None:
            declared = extract_string_tuple(module.tree,
                                            owner.registry_name)
            if declared is not None:
                state.setdefault('declared', {})[owner.suffix] = declared
            self._collect_kind_compares(module, state, owner)
            self._collect_writer_literals(module, state, owner)
            findings.extend(self._check_flush_per_append(module))
            findings.extend(self._check_drop_accounting(module))
        else:
            # non-journal modules: writer-call literals route to the journal
            # whose writer name they use — build_run_record() to the
            # historian, append_record()/_journal() to the ledger (the
            # membership journal is only ever written through its typed
            # note_* wrappers; PR 18 routing)
            for spec in specs:
                if spec.suffix == 'topology.py':
                    continue
                self._collect_writer_literals(module, state, spec)
        return findings

    def finalize(self, ctx: AnalysisContext) -> Iterable[Finding]:
        state = ctx.rule_state(self.name)
        declared_map: Dict[str, List[str]] = state.get('declared', {})
        findings: List[Finding] = []
        for spec in _journal_specs(ctx):
            uses = state.get('uses:' + spec.suffix) or []
            if not uses:
                continue
            declared = declared_map.get(spec.suffix)
            if declared is None:
                declared = _installed_registry(spec.import_name,
                                               spec.registry_name)
            if declared is None:
                ctx.notes.append(
                    'journal-discipline: no {} registry found for {} — '
                    'kind conformance not checked'.format(
                        spec.registry_name, spec.suffix))
                continue
            for value, path, line in uses:
                if value not in declared:
                    findings.append(Finding(
                        self.name, path, line,
                        '{} {!r} is not declared in {} ({}) — a replayer '
                        'built from this registry will silently skip the '
                        'record and resume from wrong state'.format(
                            spec.kind_label, value, spec.registry_name,
                            tuple(declared))))
        return findings

    # ------------------------------------------------------------ registry

    def _collect_kind_compares(self, module: SourceModule,
                               state: Dict[str, object],
                               spec: _JournalSpec) -> None:
        uses = state.setdefault('uses:' + spec.suffix, [])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not all(isinstance(op, (ast.Eq, ast.NotEq))
                       for op in node.ops):
                continue
            sides = [node.left] + list(node.comparators)
            if not any(isinstance(side, ast.Name) and side.id == 'kind'
                       for side in sides):
                continue
            for side in sides:
                value = const_str(side)
                if value is not None:
                    uses.append((value, module.display,  # type: ignore[attr-defined]
                                 side.lineno))

    def _collect_writer_literals(self, module: SourceModule,
                                 state: Dict[str, object],
                                 spec: _JournalSpec) -> None:
        uses = state.setdefault('uses:' + spec.suffix, [])
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            func_name: Optional[str] = None
            if isinstance(func, ast.Attribute):
                func_name = func.attr
            elif isinstance(func, ast.Name):
                func_name = func.id
            if func_name not in spec.writer_calls:
                continue
            if not node.args:
                continue
            value = const_str(node.args[0])
            if value is not None:
                uses.append((value, module.display,  # type: ignore[attr-defined]
                             node.args[0].lineno))

    # ----------------------------------------------------- write discipline

    @staticmethod
    def _declares_frame_header(module: SourceModule) -> bool:
        for node in module.tree.body:
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if (isinstance(target, ast.Name)
                            and target.id == '_FRAME_HEADER'):
                        return True
        return False

    def _check_flush_per_append(self,
                                module: SourceModule) -> List[Finding]:
        if not self._declares_frame_header(module):
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            writes: List[int] = []
            flushes = False
            for inner in walk_skipping_functions(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                if isinstance(inner.func, ast.Attribute):
                    if inner.func.attr == 'write':
                        writes.append(inner.lineno)
                    if inner.func.attr in ('flush', 'fsync'):
                        flushes = True
                elif (isinstance(inner.func, ast.Name)
                      and inner.func.id == 'fsync'):
                    flushes = True
            if writes and not flushes:
                findings.append(Finding(
                    self.name, module.display, writes[0],
                    'journal frame written in {}() without a flush/fsync '
                    'on the same path — a crash replays a journal this '
                    'append never durably joined'.format(node.name)))
        return findings

    # ------------------------------------------------------ drop accounting

    @staticmethod
    def _mentions(node: ast.AST, needle: str) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and needle in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) and needle in sub.attr.lower():
                return True
        return False

    def _check_drop_accounting(self, module: SourceModule) -> List[Finding]:
        if not self._declares_frame_header(module):
            return []
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.If):
                continue
            if not self._mentions(node.test, 'crc'):
                continue
            bails = [inner for inner in walk_skipping_functions(node.body)
                     if isinstance(inner, (ast.Continue, ast.Break,
                                           ast.Return))]
            if not bails:
                continue
            accounted = any(self._mentions(inner, 'drop')
                            for inner in node.body)
            if not accounted:
                findings.append(Finding(
                    self.name, module.display, node.lineno,
                    'CRC-mismatch branch bails without counting the drop — '
                    'a bare continue/break reads past corruption silently; '
                    'increment the dropped-frame counter before bailing'))
        return findings
