"""determinism: lineage-covered modules must not depend on unordered state.

``compose_global_digest`` *proves* at runtime that same seed + any topology
gives one byte-identical sample order; this rule is its static twin. In the
lineage-covered modules (config ``DETERMINISM_MODULES`` — reader,
ventilator, cost schedule, topology, loaders, service dispatcher, lineage
itself) three nondeterminism sources are findings:

- **unseeded randomness**: any ``random.*`` / ``np.random.*`` module-level
  call (``random.shuffle``, ``np.random.permutation``) — randomness must
  flow through a seeded ``Random``/``RandomState``/``default_rng`` instance
  so the draw stream is part of the lineage identity;
- **unordered iteration into order-sensitive sinks**: a ``set`` (literal,
  ``set()``/``frozenset()`` call, set comprehension, or a local bound to
  one), ``os.listdir``/``glob``/``scandir``/``iterdir`` results, or raw
  dict views (``.keys()``/``.values()``/``.items()``) feeding a sink from
  config ``ORDER_SENSITIVE_SINKS`` (digest folds, journal appends, shard
  deals) without an intervening ``sorted()``. ``sorted()`` at any wrap
  point launders the iteration; dict views are flagged only directly inside
  a sink argument (insertion order is deterministic per-process but not a
  cross-host contract), while set/listdir/glob iteration is also flagged
  when a ``for`` loop over it drives sink calls in its body;
- **``id()``-keyed containers**: ``id(x)`` as a dict key, subscript index
  or sort key — identity hashes differ across processes and runs, so any
  order or grouping built on them diverges host-to-host.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Optional, Sequence, Set

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule,
                                         walk_skipping_functions)

#: ``random.<x>`` calls that construct/seed an explicit generator (allowed)
_SEEDED_RANDOM_FACTORIES = frozenset({'Random', 'SystemRandom'})
#: ``np.random.<x>`` constructors of seeded generators (allowed)
_SEEDED_NP_FACTORIES = frozenset({'RandomState', 'default_rng', 'Generator',
                                  'SeedSequence', 'PCG64', 'Philox'})
_NP_NAMES = frozenset({'np', 'numpy'})
#: calls returning filesystem-order (or otherwise unordered) iterables
_FS_ORDER_CALLS = frozenset({'listdir', 'glob', 'iglob', 'scandir',
                             'iterdir', 'walk', 'rglob'})
_DICT_VIEW_ATTRS = frozenset({'keys', 'values', 'items'})
_SET_CALLS = frozenset({'set', 'frozenset'})


def _is_determinism_module(module: SourceModule,
                           suffixes: Sequence[str]) -> bool:
    posix = module.posix()
    return any(posix.endswith('/' + suffix) or posix == suffix
               for suffix in suffixes)


def _unseeded_random(node: ast.Call) -> Optional[str]:
    """A description when ``node`` is a module-level (unseeded) random
    call, e.g. ``random.shuffle`` or ``np.random.permutation``."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if (isinstance(func.value, ast.Name) and func.value.id == 'random'
            and func.attr not in _SEEDED_RANDOM_FACTORIES):
        return 'random.{}()'.format(func.attr)
    if (isinstance(func.value, ast.Attribute)
            and func.value.attr == 'random'
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id in _NP_NAMES
            and func.attr not in _SEEDED_NP_FACTORIES):
        return '{}.random.{}()'.format(func.value.value.id, func.attr)
    return None


def _walk_outside_sorted(node: ast.AST) -> Iterator[ast.AST]:
    """Walk ``node`` skipping subtrees wrapped in ``sorted(...)`` — a
    ``sorted()`` at any wrap point launders unordered iteration."""
    stack: List[ast.AST] = [node]
    while stack:
        current = stack.pop()
        if (isinstance(current, ast.Call)
                and isinstance(current.func, ast.Name)
                and current.func.id == 'sorted'):
            continue
        yield current
        stack.extend(ast.iter_child_nodes(current))


class _UnorderedSources:
    """Per-module index of expressions/bindings with unordered iteration
    order: strong (sets, listdir/glob — order differs run-to-run) and weak
    (dict views — deterministic per-process, not a cross-host contract)."""

    def __init__(self, tree: ast.Module) -> None:
        self.set_bindings: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if (isinstance(target, ast.Name)
                            and self.is_strong(node.value)):
                        self.set_bindings.add(target.id)

    def is_strong(self, node: ast.AST) -> bool:
        """Set-valued or filesystem-order expression (flagged anywhere)."""
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _SET_CALLS):
                return True
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _FS_ORDER_CALLS):
                return True
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _FS_ORDER_CALLS):
                return True
        if isinstance(node, ast.Name) and node.id in self.set_bindings:
            return True
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.BitOr, ast.BitAnd, ast.Sub)):
            # set algebra on a known set binding stays a set
            return (self.is_strong(node.left)
                    or self.is_strong(node.right))
        return False

    @staticmethod
    def is_weak(node: ast.AST) -> bool:
        """Raw dict-view call (flagged only directly in sink args)."""
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DICT_VIEW_ATTRS
                and not node.args)

    def describe(self, node: ast.AST) -> str:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return 'a set'
        if isinstance(node, ast.Call):
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name in _SET_CALLS:
                return '{}()'.format(name)
            if name in _FS_ORDER_CALLS:
                return '{}() (filesystem order)'.format(name)
            if name in _DICT_VIEW_ATTRS:
                return '.{}() (raw dict view)'.format(name)
        if isinstance(node, ast.Name):
            return 'set-valued local {!r}'.format(node.id)
        return 'an unordered iterable'


class DeterminismRule(Rule):
    """Unseeded randomness / unordered-iteration / id()-keys (module doc)."""

    name = 'determinism'
    description = ('lineage-covered modules must not feed unseeded '
                   'randomness, unsorted set/listdir/dict-view iteration or '
                   'id()-keys into order-sensitive sinks (digests, '
                   'journals, shard deals)')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        if not _is_determinism_module(module,
                                      ctx.config.determinism_modules):
            return []
        findings: List[Finding] = []
        sinks = frozenset(ctx.config.order_sensitive_sinks)
        sources = _UnorderedSources(module.tree)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                findings.extend(self._check_random(module, node))
                findings.extend(self._check_sink_args(module, node, sinks,
                                                      sources))
            if isinstance(node, (ast.For, ast.AsyncFor)):
                findings.extend(self._check_loop(module, node, sinks,
                                                 sources))
        findings.extend(self._check_id_keys(module))
        return findings

    def _check_random(self, module: SourceModule,
                      node: ast.Call) -> List[Finding]:
        described = _unseeded_random(node)
        if described is None:
            return []
        return [Finding(
            self.name, module.display, node.lineno,
            'unseeded {} in a lineage-covered module — draw through a '
            'seeded Random/RandomState/default_rng instance so the stream '
            'is part of the run identity'.format(described))]

    def _is_sink(self, node: ast.Call, sinks: frozenset) -> Optional[str]:
        func = node.func
        if isinstance(func, ast.Name) and func.id in sinks:
            return func.id
        if isinstance(func, ast.Attribute) and func.attr in sinks:
            return func.attr
        return None

    def _check_sink_args(self, module: SourceModule, node: ast.Call,
                         sinks: frozenset,
                         sources: _UnorderedSources) -> List[Finding]:
        sink = self._is_sink(node, sinks)
        if sink is None:
            return []
        findings: List[Finding] = []
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            offender = self._unordered_in_arg(arg, sources)
            if offender is None:
                continue
            findings.append(Finding(
                self.name, module.display, offender.lineno,
                '{} iterates into order-sensitive sink {}() without '
                'sorted() — iteration order is not a reproducibility '
                'contract; wrap it in sorted(...)'.format(
                    sources.describe(offender), sink)))
        return findings

    def _unordered_in_arg(self, arg: ast.expr,
                          sources: _UnorderedSources) -> Optional[ast.AST]:
        """The first unordered expression *iterated* inside a sink argument
        (comprehension iters, starred unpacking, or the argument itself),
        ignoring anything laundered through ``sorted()``."""
        for node in _walk_outside_sorted(arg):
            if isinstance(node, (ast.GeneratorExp, ast.ListComp,
                                 ast.SetComp, ast.DictComp)):
                for generator in node.generators:
                    candidate = generator.iter
                    if any(isinstance(sub, ast.Call)
                           and isinstance(sub.func, ast.Name)
                           and sub.func.id == 'sorted'
                           for sub in [candidate]):
                        continue
                    if (sources.is_strong(candidate)
                            or sources.is_weak(candidate)):
                        return candidate
            if isinstance(node, ast.Starred):
                if sources.is_strong(node.value):
                    return node.value
        # the argument itself passed through whole (e.g. `fold(set_of_ids)`)
        stripped = arg
        if sources.is_strong(stripped) or sources.is_weak(stripped):
            return stripped
        return None

    def _check_loop(self, module: SourceModule, node: ast.AST,
                    sinks: frozenset,
                    sources: _UnorderedSources) -> List[Finding]:
        iter_expr = getattr(node, 'iter', None)
        if iter_expr is None or not sources.is_strong(iter_expr):
            return []
        if (isinstance(iter_expr, ast.Call)
                and isinstance(iter_expr.func, ast.Name)
                and iter_expr.func.id == 'sorted'):
            return []
        body = getattr(node, 'body', [])
        for inner in walk_skipping_functions(body):
            if (isinstance(inner, ast.Call)
                    and self._is_sink(inner, sinks) is not None):
                return [Finding(
                    self.name, module.display, int(getattr(node, 'lineno',
                                                           1)),
                    'loop over {} drives order-sensitive sink {}() in its '
                    'body — iterate sorted(...) so every host folds/deals '
                    'in one order'.format(
                        sources.describe(iter_expr),
                        self._is_sink(inner, sinks)))]
        return []

    def _check_id_keys(self, module: SourceModule) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            spots: List[ast.expr] = []
            if isinstance(node, ast.Subscript):
                spots.append(node.slice)
            elif isinstance(node, ast.Dict):
                spots.extend(k for k in node.keys if k is not None)
            elif isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg == 'key':
                        spots.append(kw.value)
            for spot in spots:
                if self._mentions_id_call(spot):
                    findings.append(Finding(
                        self.name, module.display, spot.lineno,
                        'id() used as a key — identity hashes differ '
                        'across processes and runs, so any order or '
                        'grouping keyed on them diverges host-to-host; '
                        'key on a stable field instead'))
        return findings

    @staticmethod
    def _mentions_id_call(node: ast.expr) -> bool:
        if isinstance(node, ast.Name) and node.id == 'id':
            return True
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
                    and sub.func.id == 'id'):
                return True
        return False
