"""clock-discipline: no direct wall-clock reads in clock-injected modules.

The resilience layer's contract (PR 4) is that every time-dependent decision
— backoff budgets, breaker cooldowns, deadlines — flows through an
*injectable* clock (``clock: Callable[[], float] = time.monotonic``), which
is what makes breaker transitions and retry schedules deterministic under
test. A direct ``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()``
**call** inside such a module silently escapes the injected clock: tests
with a fake clock pass while production behavior differs.

References are fine — ``clock=time.monotonic`` as a default argument *is*
the discipline; only call sites are findings. The module set is configured
in :mod:`petastorm_tpu.analysis.config` (``CLOCK_DISCIPLINED_FILES``,
default: ``resilience.py``).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from petastorm_tpu.analysis.core import (AnalysisContext, Finding, Rule,
                                         SourceModule)

#: the ``time`` module functions that read a clock
_CLOCK_ATTRS = frozenset({'time', 'monotonic', 'perf_counter',
                          'time_ns', 'monotonic_ns', 'perf_counter_ns'})


class ClockDisciplineRule(Rule):
    """Flag direct clock calls in clock-disciplined modules (module doc)."""

    name = 'clock-discipline'
    description = ('no direct time.time()/time.monotonic()/'
                   'time.perf_counter() calls in injectable-clock modules '
                   '(resilience.py) — pass the clock in')

    def check_module(self, module: SourceModule,
                     ctx: AnalysisContext) -> Iterable[Finding]:
        if module.name not in ctx.config.clock_disciplined_files:
            return []
        from_time_imports: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module == 'time':
                for alias in node.names:
                    if alias.name in _CLOCK_ATTRS:
                        from_time_imports.add(alias.asname or alias.name)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            called = None
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id == 'time'
                    and func.attr in _CLOCK_ATTRS):
                called = 'time.' + func.attr
            elif isinstance(func, ast.Name) and func.id in from_time_imports:
                called = func.id
            if called is not None:
                findings.append(Finding(
                    self.name, module.display, node.lineno,
                    'direct {}() call in a clock-disciplined module — route '
                    'it through the injected clock/sleep callable so tests '
                    'stay deterministic'.format(called)))
        return findings
