"""pipecheck call graph: the whole-program layer under the v2 rule families.

Pipecheck v1 rules were per-file set matchers; the failure classes that
actually shipped — the ``/dev/shm`` segment leak, blocking calls reached
through a helper inside a ``with lock:`` body, resources handed to an owner
object that never releases them — all cross function (and file) boundaries.
This module builds, once per analysis pass, a project-wide index of every
function/method definition plus three per-function summary layers:

- **call resolution** (:meth:`CallGraph.resolve_call`): a call site resolves
  to its definition by confidence tiers — same-module function, ``self.m()``
  method of the enclosing class, then a *dynamic-dispatch fallback to
  name-match* that only fires when exactly one definition of that name
  exists project-wide (an ambiguous name resolves to nothing rather than to
  a guess; ``obj.close()`` with forty ``close`` definitions is never
  followed).
- **blocking closure** (:meth:`CallGraph.blocking_chain`): does calling this
  function (transitively, through resolvable edges) reach a blocking call —
  ``time.sleep``, a socket ``recv``, a ``join``? Cycle-safe memoized DFS;
  the chain is reported so a finding can say *how* the lock body blocks.
- **raise closure** (:meth:`CallGraph.always_raises_transitively`): does
  every path through this function end in a ``raise`` — directly, or by
  tail-calling a function that does? Lets exception-hygiene accept
  translation handlers that delegate to a ``_fail()`` helper.
- **resource summaries** (:class:`FunctionSummary` via
  :func:`build_summaries`): which leakable resources (config
  ``LEAKABLE_TYPES``) a function acquires, whether each acquisition reaches
  a release on all paths (exception paths included), escapes to a caller
  (returned / stored on ``self`` / handed to another call), or leaks. A
  function that acquires-and-returns is itself an acquisition site for its
  callers (``returns_spec``), which is how a leak through a helper factory
  stays visible.

Binding discipline (the v2 rebinding bugfix): the summary scanner tracks
resources by local-variable binding and **kills the tracked binding on
reassignment or ``del``** — after ``seg = SharedMemory(...)`` followed by
``seg = SharedMemory(...)``, a later ``seg.close()`` releases only the
second object; the first is reported leaked at the rebind site instead of
being silently credited with the close.

Everything here is still stdlib-``ast`` static analysis: the graph is an
approximation (no aliasing, no higher-order flow), tuned so that every
finding built on it points at a concrete call chain a reviewer can follow.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from petastorm_tpu.analysis.core import (AnalysisContext, SourceModule,
                                         walk_skipping_functions)

#: key under ``AnalysisContext.state`` where the shared graph is cached so
#: every graph-backed rule (and the final Report) sees one build per pass
CALLGRAPH_STATE_KEY = '__callgraph__'

_RECV_ATTRS = frozenset({'recv', 'recv_multipart', 'recv_string',
                         'recv_pyobj', 'recv_json', 'accept'})
_SUBPROCESS_FUNCS = frozenset({'run', 'call', 'check_call', 'check_output'})

#: calls treated as non-raising when deciding whether an exception can fire
#: between an acquire and its release (precision heuristic: these are the
#: bookkeeping builtins that sit between ``acquire()`` and ``close()`` in
#: straight-line code)
_SAFE_CALLS = frozenset({'len', 'max', 'min', 'abs', 'int', 'float', 'str',
                         'bool', 'repr', 'format', 'id', 'hash', 'getattr',
                         'isinstance', 'issubclass', 'tuple', 'list', 'dict',
                         'set', 'frozenset', 'range', 'enumerate', 'zip',
                         'sorted', 'monotonic', 'perf_counter', 'time',
                         'append', 'startswith', 'endswith'})


def terminal_name(node: ast.expr) -> Optional[str]:
    """The rightmost identifier of a ``Name``/``Attribute`` expression
    (``zmq.Context`` -> ``'Context'``), else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _self_attr_name(node: ast.expr) -> Optional[str]:
    """``'x'`` for a plain ``self.x`` expression, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == 'self'):
        return node.attr
    return None


def blocking_call(node: ast.Call) -> Optional[str]:
    """A human-readable description when ``node`` is a *directly* blocking
    call (sleep / socket recv / subprocess / unbounded-or-timed join /
    input). ``Condition.wait`` is deliberately not blocking here: waiting
    with the lock held is the condition-variable protocol."""
    func = node.func
    if isinstance(func, ast.Name):
        if func.id == 'sleep':
            return 'sleep()'
        if func.id == 'input':
            return 'input()'
        return None
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr == 'sleep':
        return '{}.sleep()'.format(terminal_name(func.value) or '?')
    if func.attr in _RECV_ATTRS:
        return '.{}()'.format(func.attr)
    if (func.attr in _SUBPROCESS_FUNCS
            and isinstance(func.value, ast.Name)
            and func.value.id == 'subprocess'):
        return 'subprocess.{}()'.format(func.attr)
    if func.attr == 'join':
        if not node.args and not node.keywords:
            return '.join()'
        if any(kw.arg == 'timeout' for kw in node.keywords):
            return '.join(timeout=...)'
        if (len(node.args) == 1 and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, (int, float))):
            return '.join({})'.format(node.args[0].value)
    return None


@dataclass
class FunctionInfo:
    """One function/method definition in the analyzed tree."""

    module: SourceModule
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    name: str
    qualname: str  # 'Class.method' or 'function'
    class_name: Optional[str]

    @property
    def key(self) -> str:
        """Globally unique id: ``<display>::<qualname>``."""
        return '{}::{}'.format(self.module.display, self.qualname)

    @property
    def line(self) -> int:
        return int(getattr(self.node, 'lineno', 1))

    def body(self) -> Sequence[ast.stmt]:
        return list(getattr(self.node, 'body', []))


class CallGraph:
    """Project-wide function index + resolution + transitive closures."""

    def __init__(self) -> None:
        self.functions: Dict[str, FunctionInfo] = {}
        self._module_level: Dict[Tuple[str, str], FunctionInfo] = {}
        self._methods: Dict[Tuple[str, str, str], FunctionInfo] = {}
        self._by_bare_name: Dict[str, List[FunctionInfo]] = {}
        #: (display, class name) -> attribute names some method releases
        #: (``self._x.close()`` / ``del self._x``) — the escape-to-owner check
        self._class_released_attrs: Dict[Tuple[str, str], Set[str]] = {}
        self._blocking_memo: Dict[str, Optional[List[str]]] = {}
        self._raises_memo: Dict[str, bool] = {}

    # ------------------------------------------------------------- building

    @classmethod
    def build(cls, modules: Sequence[SourceModule]) -> 'CallGraph':
        graph = cls()
        for module in modules:
            graph._index_module(module)
        return graph

    def _index_module(self, module: SourceModule) -> None:
        release_attr_re = ('close', 'unlink', 'join', 'cleanup', 'term',
                           'destroy', 'stop', 'release', 'shutdown',
                           'close_and_unlink', 'terminate', 'abandon')

        def visit(node: ast.AST, class_name: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = ('{}.{}'.format(class_name, child.name)
                            if class_name else child.name)
                    info = FunctionInfo(module=module, node=child,
                                        name=child.name, qualname=qual,
                                        class_name=class_name)
                    if info.key not in self.functions:
                        self.functions[info.key] = info
                        self._by_bare_name.setdefault(child.name,
                                                      []).append(info)
                        if class_name is None:
                            self._module_level.setdefault(
                                (module.display, child.name), info)
                        else:
                            self._methods.setdefault(
                                (module.display, class_name, child.name),
                                info)
                            self._note_released_attrs(
                                module, class_name, child, release_attr_re)
                    # nested defs are indexed too (closures can block/raise)
                    visit(child, class_name)
                else:
                    visit(child, class_name)

        visit(module.tree, None)

    def _note_released_attrs(self, module: SourceModule, class_name: str,
                             func: ast.AST,
                             release_attrs: Tuple[str, ...]) -> None:
        released = self._class_released_attrs.setdefault(
            (module.display, class_name), set())
        # local aliases of self-attributes: `thread = self._thread` and
        # `for sock in (self._a, self._b):` — a release call on the alias
        # releases every attribute it may name
        aliases: Dict[str, Set[str]] = {}
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                attr = _self_attr_name(node.value)
                if attr is not None:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            aliases.setdefault(target.id, set()).add(attr)
            elif (isinstance(node, (ast.For, ast.AsyncFor))
                    and isinstance(node.target, ast.Name)
                    and isinstance(node.iter, (ast.Tuple, ast.List))):
                for element in node.iter.elts:
                    attr = _self_attr_name(element)
                    if attr is not None:
                        aliases.setdefault(node.target.id, set()).add(attr)
        for node in ast.walk(func):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in release_attrs):
                attr = _self_attr_name(node.func.value)
                if attr is not None:
                    released.add(attr)
                elif isinstance(node.func.value, ast.Name):
                    released.update(aliases.get(node.func.value.id, ()))
            if isinstance(node, ast.Delete):
                for target in node.targets:
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == 'self'):
                        released.add(target.attr)
            # handing the attribute to another call (e.g. a shutdown helper,
            # `_drain(self._ring)`) also counts as the owner taking care of it
            if isinstance(node, ast.Call):
                for arg in node.args:
                    if (isinstance(arg, ast.Attribute)
                            and isinstance(arg.value, ast.Name)
                            and arg.value.id == 'self'
                            and isinstance(node.func, (ast.Name,
                                                       ast.Attribute))
                            and (terminal_name(node.func) or '')
                            not in ('append', 'add', 'put', 'register')):
                        released.add(arg.attr)

    # ----------------------------------------------------------- resolution

    def owner_releases(self, module: SourceModule, class_name: str,
                       attr: str) -> bool:
        """True when some method of ``class_name`` (in ``module``) releases
        ``self.<attr>`` — close/join/stop/del or hands it to a helper."""
        return attr in self._class_released_attrs.get(
            (module.display, class_name), set())

    def resolve_call(self, call: ast.Call,
                     caller: FunctionInfo) -> Optional[FunctionInfo]:
        """The unique definition a call site reaches, or None.

        Tiers: same-module function for ``f()``; the enclosing class's
        method for ``self.m()``; then the dynamic-dispatch fallback — a bare
        or attribute name that has exactly ONE definition project-wide.
        Ambiguity resolves to None (never guess)."""
        func = call.func
        display = caller.module.display
        if isinstance(func, ast.Name):
            info = self._module_level.get((display, func.id))
            if info is not None:
                return info
            return self._unique_by_name(func.id, methods=False)
        if isinstance(func, ast.Attribute):
            if (isinstance(func.value, ast.Name) and func.value.id == 'self'
                    and caller.class_name is not None):
                info = self._methods.get(
                    (display, caller.class_name, func.attr))
                if info is not None:
                    return info
                return self._unique_by_name(func.attr, methods=True)
            return self._unique_by_name(func.attr, methods=True)
        return None

    def _unique_by_name(self, name: str,
                        methods: bool) -> Optional[FunctionInfo]:
        candidates = [info for info in self._by_bare_name.get(name, [])
                      if (info.class_name is not None) == methods]
        if len(candidates) == 1:
            return candidates[0]
        # name-match fallback across both namespaces when still unique
        everything = self._by_bare_name.get(name, [])
        if len(everything) == 1:
            return everything[0]
        return None

    # --------------------------------------------------- transitive closure

    def blocking_chain(self, info: FunctionInfo) -> Optional[List[str]]:
        """The call chain (``['helper()', 'time.sleep()']``) through which
        calling ``info`` reaches a blocking call, or None. Memoized,
        cycle-safe (a cycle with no blocking call resolves to None)."""
        return self._blocking_dfs(info, visiting=set())

    def _blocking_dfs(self, info: FunctionInfo,
                      visiting: Set[str]) -> Optional[List[str]]:
        if info.key in self._blocking_memo:
            return self._blocking_memo[info.key]
        if info.key in visiting:
            return None
        visiting.add(info.key)
        result: Optional[List[str]] = None
        for node in walk_skipping_functions(info.body()):
            if not isinstance(node, ast.Call):
                continue
            direct = blocking_call(node)
            if direct is not None:
                result = ['{}()'.format(info.qualname), direct]
                break
            callee = self.resolve_call(node, info)
            if callee is None or callee.key == info.key:
                continue
            sub = self._blocking_dfs(callee, visiting)
            if sub is not None:
                result = ['{}()'.format(info.qualname)] + sub
                break
        visiting.discard(info.key)
        self._blocking_memo[info.key] = result
        return result

    def always_raises_transitively(self, info: FunctionInfo) -> bool:
        """True when every path through ``info`` ends in a ``raise`` —
        directly, or by tail-calling a function that does."""
        return self._raises_dfs(info, visiting=set())

    def _raises_dfs(self, info: FunctionInfo, visiting: Set[str]) -> bool:
        if info.key in self._raises_memo:
            return self._raises_memo[info.key]
        if info.key in visiting:
            return False
        visiting.add(info.key)
        result = self._stmts_always_raise(list(info.body()), info, visiting)
        visiting.discard(info.key)
        self._raises_memo[info.key] = result
        return result

    def stmts_always_raise(self, stmts: Sequence[ast.stmt],
                           caller: FunctionInfo) -> bool:
        """Interprocedural ``always_raises`` over a statement list (e.g. an
        except-handler body): every path ends in a raise, where a trailing
        call to an always-raising function counts as raising."""
        return self._stmts_always_raise(stmts, caller, visiting=set())

    def _stmts_always_raise(self, stmts: Sequence[ast.stmt],
                            caller: FunctionInfo,
                            visiting: Set[str]) -> bool:
        if not stmts:
            return False
        last = stmts[-1]
        if isinstance(last, ast.Raise):
            return True
        if isinstance(last, ast.If):
            return (bool(last.orelse)
                    and self._stmts_always_raise(last.body, caller, visiting)
                    and self._stmts_always_raise(last.orelse, caller,
                                                 visiting))
        if isinstance(last, ast.With):
            return self._stmts_always_raise(last.body, caller, visiting)
        if isinstance(last, ast.Expr) and isinstance(last.value, ast.Call):
            callee = self.resolve_call(last.value, caller)
            if callee is not None:
                return self._raises_dfs(callee, visiting)
        return False


def get_callgraph(ctx: AnalysisContext) -> CallGraph:
    """The per-pass shared graph (built lazily on first rule access)."""
    graph = ctx.state.get(CALLGRAPH_STATE_KEY)
    if not isinstance(graph, CallGraph):
        graph = CallGraph.build(ctx.modules)
        ctx.state[CALLGRAPH_STATE_KEY] = graph
    return graph


# ---------------------------------------------------------------------------
# Resource lifecycle summaries
# ---------------------------------------------------------------------------

@dataclass
class Tracked:
    """One tracked acquisition inside one function."""

    binding: Optional[str]  # local variable name; None = value discarded
    spec_index: int  # index into config.leakable_types
    line: int
    released: bool = False
    release_in_finally: bool = False
    escaped: bool = False
    escaped_self_attr: Optional[str] = None
    returned: bool = False
    exempt: bool = False  # e.g. Thread(daemon=True)
    risk_line: Optional[int] = None  # first may-raise call before release
    killed_line: Optional[int] = None  # rebound / del'd before release


@dataclass
class FunctionSummary:
    """Per-function resource-lifecycle facts for the lifecycle rule."""

    info: FunctionInfo
    tracked: List[Tracked] = field(default_factory=list)
    #: spec index when the function acquires a resource and returns it —
    #: its call sites become acquisition sites for the caller
    returns_spec: Optional[int] = None


class _LeakSpecView:
    """Normalized view over one ``LEAKABLE_TYPES`` config row."""

    def __init__(self, row: Tuple[str, Tuple[str, ...], Tuple[str, ...],
                                  Tuple[str, ...], str, bool]) -> None:
        (self.constructor, self.releases, self.releaser_funcs,
         self.exempt_kwargs, self.label, self.paths_sensitive) = row


def _leak_specs(config: object) -> List[_LeakSpecView]:
    rows = getattr(config, 'leakable_types', ())
    return [_LeakSpecView(row) for row in rows]


_BROAD_EXC_NAMES = frozenset({'Exception', 'BaseException'})


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    """``except:`` / ``except Exception`` / ``except BaseException`` — a
    handler wide enough that a release inside it covers (approximately)
    every exception path; a narrow ``except OSError:`` cleanup does NOT,
    which is exactly the leak class the paths-sensitive check exists for."""
    if handler.type is None:
        return True
    names = (handler.type.elts if isinstance(handler.type, ast.Tuple)
             else [handler.type])
    return any(terminal_name(name) in _BROAD_EXC_NAMES
               for name in names if isinstance(name, ast.expr))


def _iter_statements(body: Sequence[ast.stmt], in_finally: bool = False,
                     in_broad_handler: bool = False
                     ) -> Iterator[Tuple[ast.stmt, bool, bool]]:
    """Yield every statement in source order with two position flags:
    ``in_finally`` (a ``finally:`` block — a release here covers every
    path) and ``in_broad_handler`` (a *broad* except handler — a release
    here covers the exception paths but NOT the normal one). Never descends
    into nested function/class definitions."""
    for stmt in body:
        yield stmt, in_finally, in_broad_handler
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for fname, value in ast.iter_fields(stmt):
            if not isinstance(value, list):
                continue
            stmt_children = [item for item in value
                             if isinstance(item, ast.stmt)]
            if stmt_children:
                yield from _iter_statements(
                    stmt_children,
                    in_finally or (isinstance(stmt, ast.Try)
                                   and fname == 'finalbody'),
                    in_broad_handler)
            else:
                for item in value:
                    if isinstance(item, ast.ExceptHandler):
                        yield from _iter_statements(
                            item.body, in_finally,
                            in_broad_handler or _broad_handler(item))


def _name_used_in(node: ast.AST, name: str) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id == name:
            return True
    return False


def _match_constructor(call: ast.Call,
                       specs: List[_LeakSpecView]) -> Optional[int]:
    name = terminal_name(call.func)
    if name is None:
        return None
    for index, spec in enumerate(specs):
        if spec.constructor == name:
            return index
    return None


def _exempt_by_kwargs(call: ast.Call, spec: _LeakSpecView) -> bool:
    for kw in call.keywords:
        if (kw.arg in spec.exempt_kwargs
                and isinstance(kw.value, ast.Constant)
                and bool(kw.value.value)):
            return True
    return False


class _FunctionScanner:
    """Scan one function body, producing its :class:`FunctionSummary`.

    The scan is a linear, source-order walk: acquisitions open a tracked
    binding, releases/escapes close it, and an assignment or ``del`` of a
    tracked name *kills* the binding — later events on that name belong to
    the new object, never the old one (the rebinding bugfix)."""

    def __init__(self, info: FunctionInfo, specs: List[_LeakSpecView],
                 summaries: Dict[str, FunctionSummary],
                 graph: CallGraph) -> None:
        self.info = info
        self.specs = specs
        self.summaries = summaries
        self.graph = graph
        self.summary = FunctionSummary(info=info)
        self.active: Dict[str, Tracked] = {}
        # local aliases of tracked bindings: `s = sock` and the teardown
        # idiom `for sock in (a, b, c): sock.close()` — a release method on
        # the alias releases every binding it may name
        self.aliases: Dict[str, Set[str]] = {}

    # -- helpers ----------------------------------------------------------

    def _acquisition_spec(self, call: ast.Call) -> Optional[int]:
        direct = _match_constructor(call, self.specs)
        if direct is not None:
            return direct
        callee = self.graph.resolve_call(call, self.info)
        if callee is not None:
            callee_summary = self.summaries.get(callee.key)
            if callee_summary is not None:
                return callee_summary.returns_spec
        return None

    def _spec(self, tracked: Tracked) -> _LeakSpecView:
        return self.specs[tracked.spec_index]

    @staticmethod
    def _mark_release(tracked: Tracked, in_finally: bool,
                      in_broad: bool) -> None:
        """Record a release by position: a ``finally`` covers every path;
        a broad except handler covers the exception paths but NOT the
        normal one (deleting the straight-line release while keeping the
        cleanup handler is still a leak); anywhere else is the normal
        path."""
        if in_finally:
            tracked.released = True
            tracked.release_in_finally = True
        elif in_broad:
            tracked.release_in_finally = True
        else:
            tracked.released = True

    def _kill(self, name: str, line: int) -> None:
        tracked = self.active.pop(name, None)
        if tracked is None:
            return
        if not (tracked.released or tracked.escaped or tracked.exempt):
            tracked.killed_line = line
        self.summary.tracked.append(tracked)

    def _finish(self) -> FunctionSummary:
        for tracked in self.active.values():
            self.summary.tracked.append(tracked)
        self.active = {}
        for tracked in self.summary.tracked:
            if tracked.returned and self.summary.returns_spec is None:
                self.summary.returns_spec = tracked.spec_index
        return self.summary

    # -- the scan ---------------------------------------------------------

    def scan(self) -> FunctionSummary:
        for stmt, in_finally, in_broad in _iter_statements(
                self.info.body()):
            self._scan_statement(stmt, in_finally, in_broad)
        return self._finish()

    def _scan_statement(self, stmt: ast.stmt, in_finally: bool,
                        in_broad: bool = False) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._scan_with(stmt)
            return
        if isinstance(stmt, (ast.Return,)):
            if stmt.value is not None:
                self._scan_uses(stmt.value, in_finally, in_broad,
                                returning=True)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    self._kill(target.id, stmt.lineno)
            return
        if isinstance(stmt, ast.Assign):
            self._scan_uses(stmt.value, in_finally, in_broad)
            for target in stmt.targets:
                self._scan_assign_target(target, stmt.value, stmt.lineno)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._scan_uses(stmt.value, in_finally, in_broad)
            self._scan_assign_target(stmt.target, stmt.value, stmt.lineno)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_uses(stmt.value, in_finally, in_broad)
            self._scan_discarded(stmt.value)
            return
        if (isinstance(stmt, (ast.For, ast.AsyncFor))
                and isinstance(stmt.target, ast.Name)
                and isinstance(stmt.iter, (ast.Tuple, ast.List))):
            names = {element.id for element in stmt.iter.elts
                     if isinstance(element, ast.Name)}
            if names & set(self.active):
                self.aliases.setdefault(stmt.target.id, set()).update(names)
                return
        # compound statements: only their own header expressions here
        # (bodies arrive as separate statements from _iter_statements)
        for fname, value in ast.iter_fields(stmt):
            if isinstance(value, ast.expr):
                self._scan_uses(value, in_finally, in_broad)

    def _scan_with(self, stmt: ast.stmt) -> None:
        items = list(getattr(stmt, 'items', []))
        for item in items:
            expr = item.context_expr
            # `with x:` / `with closing(x):` releases x on every path
            inner = expr
            if (isinstance(inner, ast.Call)
                    and terminal_name(inner.func) == 'closing'
                    and inner.args):
                inner = inner.args[0]
            if isinstance(inner, ast.Name) and inner.id in self.active:
                tracked = self.active[inner.id]
                tracked.released = True
                tracked.release_in_finally = True
                continue
            if isinstance(expr, ast.Call):
                # `with SharedMemory(...) as x:` — context-managed from
                # birth; nothing to track
                if _match_constructor(expr, self.specs) is not None:
                    continue
                self._scan_uses(expr, in_finally=False)

    def _scan_assign_target(self, target: ast.expr, value: ast.expr,
                            line: int) -> None:
        spec_index: Optional[int] = None
        acquisition_call: Optional[ast.Call] = None
        if isinstance(value, ast.Call):
            spec_index = self._acquisition_spec(value)
            acquisition_call = value
        if isinstance(target, ast.Name):
            if isinstance(value, ast.Name) and value.id in self.active:
                # plain alias (`thread = self._thread` shape, local form):
                # a release through the alias credits the original binding
                self.aliases.setdefault(target.id, set()).add(value.id)
                return
            # reassignment kills the old binding first (bugfix: a later
            # `x.close()` must never be credited to the replaced object)
            self._kill(target.id, line)
            if spec_index is not None and acquisition_call is not None:
                tracked = Tracked(binding=target.id, spec_index=spec_index,
                                  line=line)
                if _exempt_by_kwargs(acquisition_call,
                                     self.specs[spec_index]):
                    tracked.exempt = True
                self.active[target.id] = tracked
            return
        if (isinstance(target, ast.Tuple) and isinstance(value, ast.Call)
                and terminal_name(value.func) == 'mkstemp'
                and len(target.elts) == 2):
            # fd, path = tempfile.mkstemp(...) — track both halves
            for part_index, part in enumerate(target.elts):
                if not isinstance(part, ast.Name):
                    continue
                part_spec = self._mkstemp_spec(part_index)
                if part_spec is None:
                    continue
                self._kill(part.id, line)
                self.active[part.id] = Tracked(binding=part.id,
                                               spec_index=part_spec,
                                               line=line)
            return
        if spec_index is not None:
            # stored somewhere non-local at birth: self attribute means the
            # owner check applies; anything else is an escape
            tracked = Tracked(binding=None, spec_index=spec_index, line=line)
            if (acquisition_call is not None
                    and _exempt_by_kwargs(acquisition_call,
                                          self.specs[spec_index])):
                tracked.exempt = True
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == 'self'):
                tracked.escaped = True
                tracked.escaped_self_attr = target.attr
            else:
                tracked.escaped = True
            self.summary.tracked.append(tracked)

    def _mkstemp_spec(self, part_index: int) -> Optional[int]:
        wanted = 'mkstemp:fd' if part_index == 0 else 'mkstemp:path'
        for index, spec in enumerate(self.specs):
            if spec.constructor == wanted:
                return index
        return None

    def _scan_discarded(self, value: ast.expr) -> None:
        """An expression statement that constructs a leakable and drops it
        (possibly via a method chain: ``Thread(...).start()``)."""
        call = value
        while (isinstance(call, ast.Call)
               and isinstance(call.func, ast.Attribute)
               and isinstance(call.func.value, ast.Call)):
            call = call.func.value
        if not isinstance(call, ast.Call):
            return
        spec_index = _match_constructor(call, self.specs)
        if spec_index is None:
            return
        spec = self.specs[spec_index]
        tracked = Tracked(binding=None, spec_index=spec_index,
                          line=call.lineno)
        if _exempt_by_kwargs(call, spec):
            tracked.exempt = True
        # Thread(...).join() and friends: the chained method may itself be
        # the release
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and value.func.attr in spec.releases):
            tracked.released = True
        self.summary.tracked.append(tracked)

    def _scan_uses(self, expr: ast.expr, in_finally: bool,
                   in_broad: bool = False,
                   returning: bool = False) -> None:
        """Classify every use of a tracked binding inside ``expr``:
        release method call, release-by-arg, or escape; any other call is a
        may-raise risk for the still-open bindings."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._scan_call(node, in_finally, in_broad)
        if returning:
            for name, tracked in list(self.active.items()):
                if _name_used_in(expr, name):
                    tracked.escaped = True
                    tracked.returned = True
            # `return SharedMemory(...)` — a fresh acquisition escapes to
            # the caller: this function is a factory
            if isinstance(expr, ast.Call):
                spec_index = self._acquisition_spec(expr)
                if spec_index is not None:
                    tracked = Tracked(binding=None, spec_index=spec_index,
                                      line=expr.lineno, escaped=True,
                                      returned=True)
                    self.summary.tracked.append(tracked)

    def _scan_call(self, call: ast.Call, in_finally: bool,
                   in_broad: bool = False) -> None:
        func = call.func
        handled_names: Set[str] = set()
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name):
            receivers = [func.value.id]
            receivers.extend(self.aliases.get(func.value.id, ()))
            for receiver in receivers:
                tracked = self.active.get(receiver)
                if tracked is not None and func.attr in self._spec(
                        tracked).releases:
                    self._mark_release(tracked, in_finally, in_broad)
                    handled_names.add(receiver)
        func_name = terminal_name(func) if isinstance(
            func, (ast.Name, ast.Attribute)) else None
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            # only the binding itself as a whole argument is an ownership
            # handoff — `f(seg)` escapes, `f(seg.buf)` / `f(seg._name)` are
            # mere uses of the still-owned object; a binding placed in a
            # container literal (`Popen([exe, path])`) also escapes
            for literal in ast.walk(arg):
                if isinstance(literal, (ast.List, ast.Tuple, ast.Set)):
                    for element in literal.elts:
                        if (isinstance(element, ast.Name)
                                and element.id in self.active
                                and element.id not in handled_names):
                            self.active[element.id].escaped = True
                            handled_names.add(element.id)
            sub = arg.value if isinstance(arg, ast.Starred) else arg
            if not isinstance(sub, ast.Name):
                continue
            tracked = self.active.get(sub.id)
            if tracked is None or sub.id in handled_names:
                continue
            spec = self._spec(tracked)
            if func_name is not None and func_name in spec.releaser_funcs:
                self._mark_release(tracked, in_finally, in_broad)
            else:
                tracked.escaped = True
            handled_names.add(sub.id)
        # every other call is a potential raise between acquire and release
        if func_name in _SAFE_CALLS:
            return
        for tracked in self.active.values():
            if (tracked.binding is not None
                    and tracked.binding not in handled_names
                    and not tracked.released and not tracked.escaped
                    and tracked.risk_line is None
                    and call.lineno > tracked.line):
                tracked.risk_line = call.lineno


def build_summaries(ctx: AnalysisContext,
                    graph: CallGraph) -> Dict[str, FunctionSummary]:
    """Acquire/release/escape summaries for every function in the graph.

    Two passes plus a small fixpoint: factories (acquire-and-return) found
    in pass N make their call sites acquisitions in pass N+1, so a leak
    through a helper function converges after a couple of rounds."""
    specs = _leak_specs(ctx.config)
    summaries: Dict[str, FunctionSummary] = {}
    for _ in range(3):
        changed = False
        for info in graph.functions.values():
            scanner = _FunctionScanner(info, specs, summaries, graph)
            summary = scanner.scan()
            previous = summaries.get(info.key)
            if (previous is None
                    or previous.returns_spec != summary.returns_spec
                    or len(previous.tracked) != len(summary.tracked)):
                changed = True
            summaries[info.key] = summary
        if not changed:
            break
    return summaries
