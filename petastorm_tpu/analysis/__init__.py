"""pipecheck: AST-based invariant analyzer for the cross-process data plane.

The multi-process pipeline's correctness rests on invariants no general tool
checks: ZMQ message kinds and shm descriptor fields must match between
``process_worker_main.py`` / ``shm_ring.py`` (producers) and
``process_pool.py`` (consumer); results-channel sidecar keys written by
``serializers.serialize`` must be read back by ``deserialize``; telemetry
stage names must exist in the ``spans.py`` catalog; retry/breaker/deadline
code must never read the wall clock directly; broad excepts in worker loops
must justify themselves; the mypy-strict module set may only grow. Protocol
drift between processes otherwise fails only at runtime, on the slow path,
under load — pipecheck pins each invariant statically and runs as a tier-1
test (self-application must stay clean).

Entry points: ``python -m petastorm_tpu.analysis``,
``petastorm-tpu-pipecheck``, ``petastorm-tpu-throughput pipecheck``, the
doctor's ``report['pipecheck']`` block, and bench.py's ``pipecheck``
section. Full rule catalog + suppression syntax: docs/static-analysis.md.
"""

from petastorm_tpu.analysis.cli import main, run_pipecheck
from petastorm_tpu.analysis.config import AnalysisConfig, default_config
from petastorm_tpu.analysis.core import Finding, Report, Rule, run_analysis
from petastorm_tpu.analysis.rules import ALL_RULES, default_rules

__all__ = ['AnalysisConfig', 'ALL_RULES', 'Finding', 'Report', 'Rule',
           'default_config', 'default_rules', 'main', 'run_analysis',
           'run_pipecheck']
