"""Vectorized columnar decode engine: per-schema compiled decode plans and Arrow
predicate pushdown (docs/performance.md "Vectorized decode engine").

The rowgroup worker used to dispatch per field *and per cell* — a Python branch
chain re-evaluated for every value of every column. This module compiles that
dispatch ONCE per (schema, field set) into a :class:`DecodePlan`: each output
field maps to exactly one whole-column kernel chosen at compile time
(partition-constant fill, codec ``decode_arrow_column``, shaped-pylist
materialization, or native Arrow-to-numpy conversion), so executing a rowgroup
is a flat loop over pre-bound kernels with no per-cell Python dispatch.

The same compile-once idea applies to worker predicates: :func:`compile_predicate`
lowers the built-in predicate classes (``in_set``/``in_negate``/``in_reduce``/
``in_pseudorandom_split``) into a mask evaluator that runs directly on the
*pre-decode* Arrow predicate table — ``pyarrow.compute.is_in`` for exact-match
leaves, and the predicates' own vectorized array mode (fed by this module's
decode kernels) where Arrow compute cannot express the semantics (md5 bucket
splits, float set membership). ``in_lambda`` and unknown predicate subclasses
are not compiled — callers fall back to the per-row path, which
:func:`evaluate_predicate_mask` also speeds up (one vectorized ``do_include``
call for the built-in classes, a chunk-friendly zip loop for the rest).

Everything here is pure compute over Arrow/numpy containers: no filesystem, no
telemetry (callers keep their existing ``stage_span('decode')`` envelopes), no
process state beyond an optional decode thread pool owned by the codec layer.
"""

from __future__ import annotations

import logging
import time
from typing import (Any, Callable, Dict, List, Mapping, Optional, Sequence,
                    Set, Tuple)

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (CompressedNdarrayCodec, DctImageCodec,
                                  NdarrayCodec, ScalarCodec, _cached_npy_meta,
                                  _column_blobs, _npz_raw_member)
from petastorm_tpu.errors import DecodeFieldError
from petastorm_tpu.predicates import (PredicateBase, in_intersection,
                                      in_negate, in_pseudorandom_split,
                                      in_reduce, in_set)

logger = logging.getLogger(__name__)

#: decoded columns of one rowgroup: ``{field_name: ndarray | list}``
Columns = Dict[str, Any]

#: one compiled per-field kernel: ``(table, partition_keys, num_rows) -> column``
FieldKernel = Callable[[Any, Mapping[str, Any], int], Any]

#: one compiled predicate node: ``(table, lazy_decoded_columns) -> (n,) bool mask``
_MaskFn = Callable[[Any, Any], np.ndarray]


# ------------------------------------------------------- promoted helpers
# (moved from reader_worker.py so the whole decode path lives in one
# strict-typed module; reader_worker keeps aliases for its internal callers)

def stack_if_uniform(values: Sequence[Any], field: Any) -> Any:
    """Stack per-row arrays into one ``(n,) + shape`` array when shapes are uniform
    and the field declares no variable dims; otherwise keep a list (ragged).
    Each value is converted through ``np.asarray`` exactly once."""
    if not values:
        return np.empty((0,) + tuple(d or 0 for d in (field.shape if field else ())))
    if field is not None and field.shape == ():
        first = values[0]
        if isinstance(first, (str, bytes)) or first is None:
            return np.array(values, dtype=object)
        return np.asarray(values)
    if any(v is None for v in values):
        return list(values)
    arrays = [np.asarray(v) for v in values]
    if len({a.shape for a in arrays}) == 1:
        return np.stack(arrays)
    return list(values)


def arrow_to_numpy(arrow_col: Any) -> Any:
    """Native column to numpy: scalars to typed arrays, strings/binary/decimal to
    object arrays via Arrow's own ``to_numpy`` object path (no ``to_pylist``
    round-trip), lists to lists of numpy arrays (reference:
    arrow_reader_worker.py:44-85)."""
    import pyarrow.types as patypes
    col_type = arrow_col.type
    if patypes.is_list(col_type) or patypes.is_large_list(col_type):
        return [None if v is None else np.asarray(v) for v in arrow_col.to_pylist()]
    if (patypes.is_string(col_type) or patypes.is_large_string(col_type)
            or patypes.is_binary(col_type) or patypes.is_large_binary(col_type)
            or patypes.is_decimal(col_type)):
        out = arrow_col.to_numpy(zero_copy_only=False)
        if out.dtype != np.dtype(object):
            # older pyarrow may hand back fixed-width unicode; keep the
            # documented object-array contract
            out = out.astype(object)
        return out
    return arrow_col.to_numpy(zero_copy_only=False)


def partition_column(field: Any, value: Any, num_rows: int) -> np.ndarray:
    """Materialize a partition-key constant as a full column (typed fill for
    numerics, object array for strings)."""
    if field is not None and np.dtype(field.numpy_dtype).kind not in ('U', 'S', 'O'):
        return np.full(num_rows, np.dtype(field.numpy_dtype).type(value))
    return np.array([value] * num_rows, dtype=object)


# ------------------------------------------------------- ship-raw contract
# (docs/performance.md "Device-resident decode tail": fields named in
# make_reader(device_decode_fields=...) skip host decode — their kernels pass
# the codec payload through in a device-uploadable form, plus small auxiliary
# columns carrying per-cell metadata the device program needs)

#: auxiliary column suffix: ``(n, 2)`` int32 pre-padding (height, width) of a
#: raw-shipped DCT field (rows for null cells are ``(0, 0)``)
RAW_HW_SUFFIX = '__hw'
#: auxiliary column suffix: ``(n,)`` uint8 per-cell encoding of a raw-shipped
#: compressed-ndarray field (``RAW_ENC_*`` values)
RAW_ENC_SUFFIX = '__enc'

#: cell is a raw-deflate stream (inflate, then npy-unpack)
RAW_ENC_DEFLATE = 0
#: cell is stored ``.npy`` bytes (header + payload, no compression)
RAW_ENC_NPY = 1
#: cell is null (the frame entry is None)
RAW_ENC_NULL = 2


class ShipRawColumns:
    """Multi-column result of a ship-raw kernel: the field's raw payload column
    plus its auxiliary metadata columns, merged into the batch by
    :meth:`DecodePlan.execute` under their own names."""

    __slots__ = ('columns',)

    def __init__(self, columns: Columns) -> None:
        self.columns = columns


def validate_device_field(field: Any) -> None:
    """Raise ``ValueError`` unless ``field`` can ship raw to the device.

    Supported codecs: :class:`~petastorm_tpu.codecs.DctImageCodec` (coefficients
    ship, IDCT runs on device), :class:`~petastorm_tpu.codecs.NdarrayCodec`
    (``.npy`` bytes ship, unpack is a device bitcast) and
    :class:`~petastorm_tpu.codecs.CompressedNdarrayCodec` (raw deflate frames
    ship). ``CompressedImageCodec`` is deliberately unsupported: JPEG/PNG
    entropy decode is bit-serial host work — store images with
    ``DctImageCodec`` for the device decode tail (the exact-JPEG-vs-DCT-form
    trade is documented in docs/performance.md)."""
    codec = field.codec
    if type(codec) in (DctImageCodec, NdarrayCodec, CompressedNdarrayCodec):
        return
    raise ValueError(
        'Field {!r} has codec {} which cannot ship raw to the device; '
        'device_decode_fields supports DctImageCodec, NdarrayCodec and '
        'CompressedNdarrayCodec (store images as DctImageCodec for on-chip '
        'decode — exact JPEG entropy decode is host-only)'.format(
            field.name, type(codec).__name__ if codec is not None else None))


def _blob_view(blob: Any) -> np.ndarray:
    """One cell's bytes as a 1-D uint8 view (zero-copy for ndarray views and
    bytes alike)."""
    if isinstance(blob, np.ndarray):
        return blob
    return np.frombuffer(blob, dtype=np.uint8)


def _ship_raw_dct_kernel(name: str, field: Any) -> FieldKernel:
    """Ship-raw kernel for ``DctImageCodec``: strip the ``DCT1`` header, pass
    the int16 coefficient blocks through untransformed (ONE slab copy when
    shapes are uniform, the ragged list contract otherwise) and emit the
    per-cell pre-padding ``(h, w)`` as the ``__hw`` auxiliary column."""
    magic = DctImageCodec._MAGIC

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        blobs = _column_blobs(table.column(name))
        n = len(blobs)
        hw = np.zeros((n, 2), dtype=np.int32)
        header_cache: Dict[bytes, Any] = {}
        out: Optional[np.ndarray] = None
        cells: Optional[List[Any]] = None
        for i, blob in enumerate(blobs):
            arr: Optional[np.ndarray] = None
            if blob is not None:
                view = _blob_view(blob)
                if bytes(memoryview(view[:4])) != magic:
                    raise ValueError('field {!r} cell {} is not DCT-coded data'
                                     .format(name, i))
                head = bytes(memoryview(view[4:8]))
                hw[i, 0] = int.from_bytes(head[0:2], 'little')
                hw[i, 1] = int.from_bytes(head[2:4], 'little')
                # memoryview: _cached_npy_meta compares byte prefixes, which
                # an ndarray would broadcast instead of comparing
                payload = memoryview(view[8:])
                meta = _cached_npy_meta(payload, header_cache)
                if meta is None:
                    raise ValueError('field {!r} cell {} carries an unparseable '
                                     'coefficient payload'.format(name, i))
                shape, fortran, dtype, offset = meta
                if fortran or dtype.hasobject:
                    raise ValueError('field {!r} cell {} coefficient layout is '
                                     'not C-contiguous native'.format(name, i))
                count = int(np.prod(shape, dtype=np.int64))
                arr = np.frombuffer(payload, dtype=dtype, count=count,
                                    offset=offset).reshape(shape)
            if cells is None:
                if arr is not None:
                    if out is None and i == 0:
                        out = np.empty((n,) + arr.shape, dtype=arr.dtype)
                    if out is not None and arr.shape == out.shape[1:] \
                            and arr.dtype == out.dtype:
                        out[i] = arr
                        continue
                cells = [out[j] for j in range(i)] if out is not None else []
            cells.append(None if arr is None else arr.copy())
        column: Any = out if cells is None else cells
        return ShipRawColumns({name: column, name + RAW_HW_SUFFIX: hw})
    return kernel


def _ship_raw_npy_kernel(name: str, field: Any) -> FieldKernel:
    """Ship-raw kernel for ``NdarrayCodec``: the stored ``.npy`` blobs pass
    through byte-for-byte. Equal-length blobs with one shared header become a
    ``(n, blob_len)`` uint8 matrix (the device program strips the header with a
    static slice and bitcasts the payload); anything else stays a list of 1-D
    uint8 arrays for the loader's host fallback."""

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        blobs = _column_blobs(table.column(name))
        n = len(blobs)
        views = [None if b is None else _blob_view(b) for b in blobs]
        lengths = {len(v) for v in views if v is not None}
        if n and not any(v is None for v in views) and len(lengths) == 1:
            blob_len = lengths.pop()
            matrix = np.empty((n, blob_len), dtype=np.uint8)
            for i, view in enumerate(views):
                matrix[i] = view
            parsed = _cached_npy_meta(memoryview(matrix[0]), {})
            if parsed is not None:
                header_len = parsed[3]
                header = matrix[0, :header_len]
                if (matrix[:, :header_len] == header).all():
                    return matrix
        return [None if v is None else v.copy() for v in views]
    return kernel


def _ship_raw_deflate_kernel(name: str, field: Any) -> FieldKernel:
    """Ship-raw kernel for ``CompressedNdarrayCodec``: each cell's zip
    container is stripped to the raw member — a raw-deflate stream (enc 0) or
    stored ``.npy`` bytes (enc 1) — with the per-cell encoding in the ``__enc``
    auxiliary column. No inflate happens here: the loader's device tail
    inflates stored-block streams on chip and Huffman streams on its own host
    thread, off the contended worker CPU."""

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        blobs = _column_blobs(table.column(name))
        n = len(blobs)
        enc = np.full(n, RAW_ENC_NULL, dtype=np.uint8)
        frames: List[Any] = []
        for i, blob in enumerate(blobs):
            if blob is None:
                frames.append(None)
                continue
            parsed = _npz_raw_member(blob)
            if parsed is None:
                raise ValueError('field {!r} cell {} is not a '
                                 'savez_compressed container'.format(name, i))
            method, body = parsed
            enc[i] = RAW_ENC_NPY if method == 0 else RAW_ENC_DEFLATE
            frames.append(np.frombuffer(body, dtype=np.uint8).copy())
        return ShipRawColumns({name: frames, name + RAW_ENC_SUFFIX: enc})
    return kernel


def _ship_raw_kernel(name: str, field: Any) -> FieldKernel:
    """Dispatch the ship-raw kernel for ``field``'s codec (pre-validated by
    :func:`validate_device_field`)."""
    validate_device_field(field)
    codec_type = type(field.codec)
    if codec_type is DctImageCodec:
        return _ship_raw_dct_kernel(name, field)
    if codec_type is NdarrayCodec:
        return _ship_raw_npy_kernel(name, field)
    return _ship_raw_deflate_kernel(name, field)


# ----------------------------------------------------------- decode plans

class DecodePlan:
    """Compiled decode plan for one (schema, field set): an ordered list of
    whole-column kernels, one per output field, executed once per rowgroup.

    Kernels are chosen at compile time from the field's declaration (partition
    key / codec / declared shape / native column), so :meth:`execute` contains
    no per-field branching and no per-cell Python dispatch. Codec failures are
    wrapped in :class:`~petastorm_tpu.errors.DecodeFieldError` carrying the
    field name and fragment path."""

    __slots__ = ('_kernels', 'field_names')

    def __init__(self, kernels: List[Tuple[str, FieldKernel]]) -> None:
        self._kernels = kernels
        #: output field order, as compiled
        self.field_names = tuple(name for name, _ in kernels)

    def execute(self, table: Any, partition_keys: Optional[Mapping[str, Any]] = None,
                fragment_path: Optional[str] = None) -> Columns:
        """Run every kernel over ``table`` -> ``{name: ndarray-or-list}``."""
        from petastorm_tpu.telemetry import tracing as _tracing
        partition_keys = partition_keys or {}
        num_rows = table.num_rows
        columns: Columns = {}
        # per-field cost spans (telemetry/cost_model.py): only while the
        # flight recorder is armed — two clock reads per field per rowgroup,
        # zero cost otherwise
        traced = _tracing.trace_enabled()
        for name, kernel in self._kernels:
            try:
                start = time.perf_counter() if traced else 0.0
                result = kernel(table, partition_keys, num_rows)
                if traced:
                    _tracing.trace_complete(
                        'decode_field', start,
                        time.perf_counter() - start, args={'field': name})
            except Exception as exc:
                raise DecodeFieldError(
                    'Failed to decode field {!r} of fragment {!r}: {}'
                    .format(name, fragment_path, exc),
                    field_name=name, fragment_path=fragment_path) from exc
            if isinstance(result, ShipRawColumns):
                # ship-raw kernels emit the payload column plus auxiliary
                # metadata columns under their own (suffixed) names
                columns.update(result.columns)
            else:
                columns[name] = result
        return columns


def _codec_kernel(name: str, field: Any) -> FieldKernel:
    """Kernel: whole-column codec decode (stacked ndarray fast path or per-cell
    list), stacked to ``(n,) + shape`` when uniform."""
    codec = field.codec

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        decoded = codec.decode_arrow_column(field, table.column(name))
        if isinstance(decoded, np.ndarray):
            return decoded
        return stack_if_uniform(decoded, field)
    return kernel


def _shaped_pylist_kernel(name: str, field: Any) -> FieldKernel:
    """Kernel: codec-less tensor field — materialize python values and cast each
    row to the declared dtype (the batch-reader path for native list columns)."""
    dtype = field.numpy_dtype

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        values = table.column(name).to_pylist()
        decoded = [None if v is None else np.asarray(v, dtype=dtype) for v in values]
        return stack_if_uniform(decoded, field)
    return kernel


def _native_kernel(name: str) -> FieldKernel:
    """Kernel: native Arrow column -> numpy, no codec involved."""

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        return arrow_to_numpy(table.column(name))
    return kernel


def _partition_kernel(name: str, field: Any) -> FieldKernel:
    """Kernel: broadcast the fragment's partition-key value over the rowgroup."""

    def kernel(table: Any, partition_keys: Mapping[str, Any], num_rows: int) -> Any:
        return partition_column(field, partition_keys.get(name), num_rows)
    return kernel


def compile_decode_plan(schema: Any, field_names: Sequence[str],
                        partition_field_names: Any = (),
                        decode: bool = True,
                        device_decode_fields: Any = ()) -> DecodePlan:
    """Compile the per-field kernel chain for one output field set.

    Mirrors the worker's historical per-cell branch order exactly: partition
    keys fill constants; fields named in ``device_decode_fields`` get ship-raw
    kernels (payload passes through undecoded for the device decode tail —
    docs/performance.md); codec fields decode through the codec's whole-column
    kernel (when ``decode``); codec-less tensor fields materialize + cast;
    everything else converts natively."""
    partition_names = set(partition_field_names)
    device_names = set(device_decode_fields)
    kernels: List[Tuple[str, FieldKernel]] = []
    for name in field_names:
        field = schema.fields.get(name)
        if name in partition_names:
            kernels.append((name, _partition_kernel(name, field)))
        elif name in device_names and field is not None:
            kernels.append((name, _ship_raw_kernel(name, field)))
        elif field is not None and field.codec is not None and decode:
            kernels.append((name, _codec_kernel(name, field)))
        elif field is not None and field.shape != () and decode:
            kernels.append((name, _shaped_pylist_kernel(name, field)))
        else:
            kernels.append((name, _native_kernel(name)))
    return DecodePlan(kernels)


# ------------------------------------------------------ predicate pushdown

#: per-dtype-kind python value types ``pyarrow.compute.is_in`` matches with
#: exactly the same semantics as the per-row ``value in set`` path. The
#: families must AGREE: Arrow silently encodes str<->bytes across
#: string/binary columns (selecting rows the Python path rejects), and floats
#: widen — both stay on the decoded numpy mirror instead.
_EXACT_MATCH_TYPES_BY_KIND = {
    'i': (bool, int, np.integer, np.bool_),
    'u': (bool, int, np.integer, np.bool_),
    'b': (bool, int, np.integer, np.bool_),
    'U': (str,),
    'S': (bytes,),
}


class _LazyDecodedColumns:
    """Decode-on-demand view over the predicate table: a leaf that evaluates as
    an Arrow compute kernel never pays for decoding its column — only the
    numpy-mode leaves (and in-band arrow-cast fallbacks) pull values through
    their single-column plan, at most once each."""

    __slots__ = ('_plans', '_table', '_cache')

    def __init__(self, plans: Mapping[str, DecodePlan], table: Any) -> None:
        self._plans = plans
        self._table = table
        self._cache: Columns = {}

    def __getitem__(self, name: str) -> Any:
        if name not in self._cache:
            self._cache[name] = self._plans[name].execute(self._table)[name]
        return self._cache[name]


class CompiledPredicate:
    """A worker predicate lowered to a whole-rowgroup mask evaluator.

    :meth:`evaluate` produces the same boolean keep mask as looping
    ``predicate.do_include(row)`` over every row, but runs directly on the
    pre-decode Arrow predicate table: exact-match leaves evaluate as
    ``pyarrow.compute`` kernels with NO decode at all, and the remaining
    leaves decode only their own column through the compiled plan before
    running the predicate's vectorized array mode."""

    __slots__ = ('fields', '_mask_fn', '_decode_plans', 'description')

    def __init__(self, fields: Set[str], mask_fn: _MaskFn,
                 decode_plans: Mapping[str, DecodePlan], description: str) -> None:
        #: field names the predicate reads
        self.fields = fields
        self._mask_fn = mask_fn
        self._decode_plans = decode_plans
        #: compile summary, e.g. ``'is_in(label)'`` — shows up in debug logs
        self.description = description

    def evaluate(self, table: Any) -> np.ndarray:
        """Predicate table -> ``(n,)`` bool keep mask (bit-identical to the
        per-row Python path)."""
        decoded = _LazyDecodedColumns(self._decode_plans, table)
        mask = np.asarray(self._mask_fn(table, decoded), dtype=bool)
        if mask.shape != (table.num_rows,):
            raise ValueError('Compiled predicate {} produced mask of shape {}, '
                             'expected ({},)'.format(self.description, mask.shape,
                                                     table.num_rows))
        return mask


def _field_eligible(schema: Any, name: str, partition_field_names: Set[str]) -> bool:
    """Pushdown operates on scalar storage columns only: the field must exist,
    be declared scalar, carry at most a ScalarCodec, and not be a partition key
    (partition constants never reach the predicate table)."""
    if name in partition_field_names:
        return False
    field = schema.fields.get(name)
    if field is None or field.shape != ():
        return False
    return field.codec is None or isinstance(field.codec, ScalarCodec)


def _vectorized_leaf(predicate: PredicateBase, name: str) -> _MaskFn:
    """Leaf evaluated through the predicate's own vectorized array mode over the
    decoded column — exact equivalence by construction."""

    def mask_fn(table: Any, decoded: Any) -> np.ndarray:
        return np.asarray(predicate.do_include({name: decoded[name]}), dtype=bool)
    return mask_fn


def _in_set_leaf(predicate: in_set, name: str, use_arrow: bool) -> _MaskFn:
    """``in_set`` leaf: ``pyarrow.compute.is_in`` on the raw storage column when
    the match is exact under Arrow casting; the decoded ``np.isin`` array mode
    otherwise (floats, datetimes, mixed sets)."""
    values = sorted(predicate.inclusion_values, key=repr)

    def mask_fn(table: Any, decoded: Any) -> np.ndarray:
        if use_arrow:
            import pyarrow.compute as pc
            col = table.column(name)
            try:
                value_set = pa.array(values, type=col.type)
                mask = pc.fill_null(pc.is_in(col, value_set=value_set), False)
                return np.asarray(mask.to_numpy(zero_copy_only=False), dtype=bool)
            except (pa.ArrowInvalid, pa.ArrowTypeError,
                    pa.ArrowNotImplementedError, OverflowError):
                # value set not castable to the storage type (pa.array raises
                # OverflowError, not an Arrow error, for out-of-C-range ints):
                # the numpy mirror below gives the per-row answer
                # (everything-False included)
                logger.debug('is_in pushdown fell back to numpy for field %r',
                             name, exc_info=True)
        return np.asarray(predicate.do_include({name: decoded[name]}), dtype=bool)
    return mask_fn


def _compile_node(predicate: PredicateBase, schema: Any,
                  partition_field_names: Set[str],
                  numpy_fields: Set[str]) -> Optional[Tuple[_MaskFn, str]]:
    """Recursively lower one predicate node; None = not compilable (caller must
    use the per-row fallback for the WHOLE predicate)."""
    kind = type(predicate)
    if kind is in_negate:
        child = _compile_node(predicate.predicate, schema, partition_field_names,
                              numpy_fields)
        if child is None:
            return None
        child_fn, child_desc = child

        def negate_fn(table: Any, decoded: Any) -> np.ndarray:
            return ~child_fn(table, decoded)
        return negate_fn, 'not({})'.format(child_desc)
    if kind is in_reduce:
        if predicate.reduce_func not in (all, any):
            return None
        children = [_compile_node(p, schema, partition_field_names, numpy_fields)
                    for p in predicate.predicates]
        if any(c is None for c in children):
            return None
        child_fns = [fn for fn, _ in children if fn is not None]
        reducer = np.logical_and.reduce if predicate.reduce_func is all \
            else np.logical_or.reduce
        op_name = 'all' if predicate.reduce_func is all else 'any'

        def reduce_fn(table: Any, decoded: Any) -> np.ndarray:
            return np.asarray(reducer([fn(table, decoded) for fn in child_fns]),
                              dtype=bool)
        return reduce_fn, '{}({})'.format(
            op_name, ', '.join(desc for _, desc in children if desc))
    if kind is in_set:
        name = predicate.predicate_field
        if not _field_eligible(schema, name, partition_field_names):
            return None
        field = schema.fields[name]
        values = predicate.inclusion_values
        exact_types = _EXACT_MATCH_TYPES_BY_KIND.get(
            np.dtype(field.numpy_dtype).kind)
        use_arrow = (exact_types is not None and len(values) > 0
                     and all(isinstance(v, exact_types) for v in values))
        # decoded column always compiled in: it is the value source for the
        # numpy mode AND the in-band fallback when the arrow cast fails
        numpy_fields.add(name)
        return _in_set_leaf(predicate, name, use_arrow), 'is_in({})'.format(name)
    if kind is in_pseudorandom_split:
        name = predicate.predicate_field
        if not _field_eligible(schema, name, partition_field_names):
            return None
        numpy_fields.add(name)
        return _vectorized_leaf(predicate, name), 'split({})'.format(name)
    return None


def compile_predicate(predicate: PredicateBase, schema: Any,
                      partition_field_names: Any = (),
                      decode: bool = True) -> Optional[CompiledPredicate]:
    """Lower a worker predicate into a :class:`CompiledPredicate`, or None when
    any node is outside the compilable set (``in_lambda``, custom subclasses,
    non-scalar/partition fields, exotic reduce functions) — the caller then
    keeps the decoded per-row path, so unknown predicates always still work."""
    partition_names = set(partition_field_names)
    numpy_fields: Set[str] = set()
    compiled = _compile_node(predicate, schema, partition_names, numpy_fields)
    if compiled is None:
        return None
    mask_fn, description = compiled
    # single-column plans, decoded lazily: a numpy-mode leaf reads values
    # through the same kernels the row path uses (value equivalence by
    # construction); an arrow-mode leaf never touches them
    decode_plans = {name: compile_decode_plan(schema, [name],
                                              partition_field_names=(),
                                              decode=decode)
                    for name in numpy_fields}
    fields = {f for f in predicate.get_fields()}
    return CompiledPredicate(fields, mask_fn, decode_plans, description)


# ----------------------------------------------- vectorized row-mode masks

def _vectorizable(predicate: PredicateBase) -> bool:
    """True when this EXACT predicate type (no subclasses — they may override
    ``do_include`` semantics) implements the whole-column array mode."""
    kind = type(predicate)
    if kind is in_negate:
        return _vectorizable(predicate.predicate)
    if kind is in_reduce:
        return (predicate.reduce_func in (all, any)
                and all(_vectorizable(p) for p in predicate.predicates))
    return kind in (in_set, in_intersection, in_pseudorandom_split)


def evaluate_predicate_mask(predicate: PredicateBase, columns: Columns,
                            num_rows: int) -> np.ndarray:
    """Row-mode predicate evaluation over decoded columns, without the per-row
    dict loop where possible: the built-in predicate classes evaluate in ONE
    vectorized ``do_include`` call over the whole columns; anything else
    (``in_lambda``, custom subclasses, ragged list columns) falls back to a
    zip-driven row loop that builds each row dict from pre-extracted columns."""
    if _vectorizable(predicate) and columns and all(
            isinstance(c, np.ndarray) and c.ndim >= 1 for c in columns.values()):
        mask = np.asarray(predicate.do_include(dict(columns)), dtype=bool)
        if mask.shape != (num_rows,):
            raise ValueError('Vectorized predicate returned mask of shape {}, '
                             'expected ({},)'.format(mask.shape, num_rows))
        return mask
    names = list(columns)
    cols = [columns[name] for name in names]
    mask = np.zeros(num_rows, dtype=bool)
    if not cols:
        # field-less predicate (e.g. in_lambda([], ...)): still one call per
        # row — the function may be stateful (row-independent sampling)
        for i in range(num_rows):
            mask[i] = bool(predicate.do_include({}))
        return mask
    for i, row_values in enumerate(zip(*cols)):
        mask[i] = bool(predicate.do_include(dict(zip(names, row_values))))
    return mask
