"""Misc utilities (reference: petastorm/utils.py:30-47 run_in_subprocess)."""

import pickle


def _subprocess_entry(serialized, result_queue):
    import dill
    try:
        func, args, kwargs = dill.loads(serialized)
        result_queue.put(('ok', pickle.dumps(func(*args, **kwargs))))
    except Exception as exc:  # noqa: BLE001
        import traceback
        result_queue.put(('error', pickle.dumps((exc, traceback.format_exc()))))


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a freshly spawned interpreter and return its
    result (reference: petastorm/utils.py:30-47; spawn avoids fork-related breakage of
    JVM / accelerator runtimes)."""
    import multiprocessing as mp

    import dill
    ctx = mp.get_context('spawn')
    result_queue = ctx.Queue()
    serialized = dill.dumps((func, args, kwargs))
    process = ctx.Process(target=_subprocess_entry, args=(serialized, result_queue))
    process.start()
    try:
        # Poll so a child that dies without replying (OOM-kill, segfault, import crash
        # during spawn) surfaces immediately instead of a 10-minute queue.Empty.
        import queue as queue_mod
        import time
        deadline = time.monotonic() + 600
        while True:
            try:
                status, payload = result_queue.get(timeout=1)
                break
            except queue_mod.Empty:
                if not process.is_alive():
                    raise RuntimeError(
                        'Subprocess died with exit code {} before returning a result'
                        .format(process.exitcode)) from None
                if time.monotonic() > deadline:
                    raise TimeoutError('Subprocess produced no result within 600s')
    finally:
        process.join(timeout=30)
        if process.is_alive():
            process.kill()
    if status == 'error':
        exc, tb = pickle.loads(payload)
        raise RuntimeError('Subprocess failed:\n{}'.format(tb)) from exc
    return pickle.loads(payload)
