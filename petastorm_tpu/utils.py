"""Misc utilities (reference: petastorm/utils.py:30-47 run_in_subprocess)."""

import pickle


def _subprocess_entry(serialized, result_queue):
    import dill
    try:
        func, args, kwargs = dill.loads(serialized)
        result_queue.put(('ok', pickle.dumps(func(*args, **kwargs))))
    except Exception as exc:  # noqa: BLE001 - every failure must ship to the parent via the queue, not kill the child silently
        import traceback
        result_queue.put(('error', pickle.dumps((exc, traceback.format_exc()))))


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a freshly spawned interpreter and return its
    result (reference: petastorm/utils.py:30-47; spawn avoids fork-related breakage of
    JVM / accelerator runtimes)."""
    import multiprocessing as mp

    import dill
    ctx = mp.get_context('spawn')
    result_queue = ctx.Queue()
    serialized = dill.dumps((func, args, kwargs))
    process = ctx.Process(target=_subprocess_entry, args=(serialized, result_queue))
    process.start()
    try:
        # Poll so a child that dies without replying (OOM-kill, segfault, import crash
        # during spawn) surfaces immediately instead of a 10-minute queue.Empty.
        import queue as queue_mod
        import time
        deadline = time.monotonic() + 600
        while True:
            try:
                status, payload = result_queue.get(timeout=1)
                break
            except queue_mod.Empty:
                if not process.is_alive():
                    raise RuntimeError(
                        'Subprocess died with exit code {} before returning a result'
                        .format(process.exitcode)) from None
                if time.monotonic() > deadline:
                    raise TimeoutError('Subprocess produced no result within 600s')
    finally:
        process.join(timeout=30)
        if process.is_alive():
            process.kill()
    if status == 'error':
        exc, tb = pickle.loads(payload)
        raise RuntimeError('Subprocess failed:\n{}'.format(tb)) from exc
    return pickle.loads(payload)


def value_readback_gate(tree):
    """Force completion of every jax array in ``tree`` by pulling one element
    back to the host.

    ``jax.block_until_ready`` has been observed returning before the tunneled
    device's queue drains, so honest wall-clock timing (and "transfer
    finished" logging) must gate on a real value transfer — the project-wide
    convention (bench.py ``force_done``, ``benchmark.linkprobe``). Safe on
    multi-process meshes: reads from the ADDRESSABLE shards of each array
    (``jax.device_get`` on a global array spanning other processes raises).
    Gates on one element of EVERY addressable shard — not just the last — so a
    shard-blocked multi-device upload (inmem_loader's sharded ``_put_with_log``)
    cannot report done while transfers to other devices are still in flight
    (r4 advisor). Fetches are issued async first, so gating k shards costs ~one
    link round trip rather than k sequential ones.
    """
    import jax
    import numpy as np
    gates = []
    for leaf in jax.tree.leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        for shard in leaf.addressable_shards:
            gates.append(shard.data.reshape(-1)[-1:])
    for gate in gates:
        try:
            gate.copy_to_host_async()
        except AttributeError:  # older jax Array without the async hint
            pass
    for gate in gates:
        np.asarray(gate)
