"""Misc utilities (reference: petastorm/utils.py:30-47 run_in_subprocess)."""

import pickle


def _subprocess_entry(serialized, result_queue):
    import dill
    func, args, kwargs = dill.loads(serialized)
    try:
        result_queue.put(('ok', pickle.dumps(func(*args, **kwargs))))
    except Exception as exc:  # noqa: BLE001
        import traceback
        result_queue.put(('error', pickle.dumps((exc, traceback.format_exc()))))


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a freshly spawned interpreter and return its
    result (reference: petastorm/utils.py:30-47; spawn avoids fork-related breakage of
    JVM / accelerator runtimes)."""
    import multiprocessing as mp

    import dill
    ctx = mp.get_context('spawn')
    result_queue = ctx.Queue()
    serialized = dill.dumps((func, args, kwargs))
    process = ctx.Process(target=_subprocess_entry, args=(serialized, result_queue))
    process.start()
    try:
        status, payload = result_queue.get(timeout=600)
    finally:
        process.join(timeout=30)
        if process.is_alive():
            process.kill()
    if status == 'error':
        exc, tb = pickle.loads(payload)
        raise RuntimeError('Subprocess failed:\n{}'.format(tb)) from exc
    return pickle.loads(payload)
