"""TensorFlow adapters (reference: petastorm/tf_utils.py) — parity wrappers over the
core iterator; the JAX loader (petastorm_tpu.parallel) is the primary device path.

``make_petastorm_dataset(reader)`` — ``tf.data.Dataset`` over a reader (row, batch, or
NGram), the reference's tf_utils.py:336-405. ``tf_tensors(reader)`` — legacy graph-mode
tensors via ``tf.compat.v1.py_func`` (:269-318).
"""

import datetime
import logging
from decimal import Decimal

import numpy as np

logger = logging.getLogger(__name__)

# Well-known graph op name carrying the shuffling queue's current size — monitoring
# code fetches it with graph.get_tensor_by_name(RANDOM_SHUFFLING_QUEUE_SIZE + ':0')
# (reference: tf_utils.py:45-47, same name for drop-in diagnostics compatibility).
RANDOM_SHUFFLING_QUEUE_SIZE = 'random_shuffling_queue_size'

# numpy -> tf dtype sanitization map (reference: tf_utils.py:27-96): TF has no uint16/32
# kernels for most ops and no Decimal/datetime; strings pass through as tf.string.
_PROMOTIONS = {
    'uint16': np.int32,
    'uint32': np.int64,
    'int8': np.int8,
    'bool': np.bool_,
}


def _sanitize_field_value(value):
    if isinstance(value, Decimal):
        return str(value)
    if isinstance(value, (datetime.date, datetime.datetime, np.datetime64)):
        return np.datetime64(value).astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.ndarray):
        if value.dtype == np.uint16:
            return value.astype(np.int32)
        if value.dtype == np.uint32:
            return value.astype(np.int64)
        if value.dtype.kind == 'M':
            return value.astype('datetime64[ns]').astype(np.int64)
    if isinstance(value, np.uint16):
        return np.int32(value)
    if isinstance(value, np.uint32):
        return np.int64(value)
    return value


def _tf_dtype_for_field(field):
    """TF dtype render of a Unischema field (reference: tf_utils.py:27-43)."""
    import tensorflow as tf
    if field.numpy_dtype is Decimal:
        return tf.string
    dtype = np.dtype(field.numpy_dtype)
    if dtype.kind in ('U', 'S', 'O'):
        return tf.string
    if dtype == np.uint16:
        return tf.int32
    if dtype == np.uint32:
        return tf.int64
    if dtype.kind == 'M':
        return tf.int64
    return tf.as_dtype(dtype)


def _output_signature(schema, batched):
    """Namedtuple-of-TensorSpecs so dataset elements support ``row.field`` access and
    keep a stable nest type across generator re-creation (reference's cached-namedtuple
    contract for tf.data type equality: unischema.py:88-111)."""
    import tensorflow as tf
    signature = {}
    for name, field in schema.fields.items():
        shape = tuple(field.shape)
        if batched:
            shape = (None,) + shape
        tf_shape = tf.TensorShape([None if d is None else d for d in shape])
        signature[name] = tf.TensorSpec(shape=tf_shape, dtype=_tf_dtype_for_field(field))
    return schema.namedtuple(**signature)


def make_petastorm_dataset(reader):
    """``tf.data.Dataset`` over a reader (reference: tf_utils.py:336-405). Row readers
    yield dicts of scalars/tensors; batch readers yield dicts of batched tensors; NGram
    readers yield {offset: dict} nested structures. Re-creating the generator after full
    consumption resets the reader (reference :328-333,371-394)."""
    import tensorflow as tf

    ngram = getattr(reader, 'ngram', None)
    batched = getattr(reader, 'is_batched_reader', False)

    if ngram is not None:
        signature = {offset: _output_signature(
            ngram.get_schema_at_timestep(reader.result_schema, offset), False)
            for offset in ngram.fields}
        # tf.nest matches namedtuples by type name + fields: re-wrap worker rows into the
        # exact classes used in the signature.
        step_types = {offset: type(spec) for offset, spec in signature.items()}
    else:
        signature = _output_signature(reader.result_schema, batched)
        row_type = type(signature)

    def generator():
        if getattr(reader, 'last_row_consumed', False):
            logger.warning('Dataset generator re-created after consumption: resetting '
                           'the reader (reference: tf_utils.py:328-333)')
            reader.reset()
        for item in reader:
            if ngram is not None:
                yield {offset: step_types[offset](
                    **{k: _sanitize_field_value(v) for k, v in step._asdict().items()})
                    for offset, step in item.items()}
            else:
                yield row_type(**{k: _sanitize_field_value(v)
                                  for k, v in item._asdict().items()})

    return tf.data.Dataset.from_generator(generator, output_signature=signature)


def tf_tensors(reader, shuffling_queue_capacity=0, min_after_dequeue=0):
    """Legacy graph-mode tensors (reference: tf_utils.py:269-318): a ``py_func`` wrapping
    ``next(reader)``, optionally through a ``RandomShuffleQueue``. Returns a namedtuple
    of tensors, or ``{offset: namedtuple}`` for NGram readers (the window is flattened
    to one tuple through the graph boundary and unflattened after — reference:
    tf_utils.py:107-120,254-266,408-438)."""
    if getattr(reader, 'is_batched_reader', False) and shuffling_queue_capacity > 0:
        raise ValueError('Shuffling queue is not supported with batched readers '
                         '(reference: tf_utils.py:307-311)')
    if getattr(reader, 'ngram', None) is not None:
        return _tf_tensors_ngram(reader, shuffling_queue_capacity, min_after_dequeue)

    schema = reader.result_schema
    field_names = list(schema.fields)
    fields = [schema.fields[n] for n in field_names]

    def _next_sample():
        row = next(reader)
        return [np.asarray(_sanitize_field_value(v)) for v in row]

    values = _flat_graph_values(_next_sample, fields, shuffling_queue_capacity,
                                min_after_dequeue, op_name='petastorm_tpu_next_sample')
    return schema.namedtuple(**dict(zip(field_names, values)))


def _tf_tensors_ngram(reader, shuffling_queue_capacity, min_after_dequeue):
    """NGram variant: flatten ``{offset: namedtuple}`` into one flat tensor tuple across
    the py_func/queue boundary, rebuild the per-offset namedtuples after (reference:
    tf_utils.py:107-120,140-182,408-438)."""
    ngram = reader.ngram
    schema = reader.result_schema
    # The emission plan IS the flattening order: (offset, row_position, names, cls) per
    # timestep, exactly matching what the reader's window reader emits.
    plan = ngram.window_plan(schema.fields)
    flat_fields = [schema.fields[name] for _, _, names, _ in plan for name in names]

    def _next_window():
        window = next(reader)
        out = []
        for key, _, names, _ in plan:
            step = window[key]
            for name in names:
                out.append(np.asarray(_sanitize_field_value(getattr(step, name))))
        return out

    values = _flat_graph_values(_next_window, flat_fields, shuffling_queue_capacity,
                                min_after_dequeue, op_name='petastorm_tpu_next_window')
    result = {}
    index = 0
    for key, _, names, cls in plan:
        result[key] = cls._make(values[index:index + len(names)])
        index += len(names)
    return result


def _flat_graph_values(next_fn, fields, shuffling_queue_capacity, min_after_dequeue,
                       op_name):
    """py_func over ``next_fn`` -> optional RandomShuffleQueue -> list of tensors with
    static shapes assigned from ``fields`` (reference: tf_utils.py:185-219)."""
    import tensorflow as tf

    dtypes = [_tf_dtype_for_field(field) for field in fields]

    def _set_shapes(values):
        for value, field in zip(values, fields):
            if not any(d is None for d in field.shape):
                value.set_shape(field.shape)

    values = tf.compat.v1.py_func(next_fn, [], dtypes, name=op_name)
    _set_shapes(values)

    if shuffling_queue_capacity > 0:
        queue = tf.queue.RandomShuffleQueue(shuffling_queue_capacity, min_after_dequeue,
                                            dtypes,
                                            name='petastorm_tpu_shuffling_queue')
        enqueue = queue.enqueue(values)
        runner = tf.compat.v1.train.QueueRunner(queue, [enqueue])
        tf.compat.v1.train.add_queue_runner(runner)
        # Well-known op name so queue depth is observable (reference: tf_utils.py:45-47).
        tf.identity(queue.size(), name=RANDOM_SHUFFLING_QUEUE_SIZE)
        values = queue.dequeue()
        if len(fields) == 1:
            # dequeue() returns a lone Tensor (not a list) for single-component queues.
            values = [values]
        _set_shapes(values)

    return list(values)
