"""Deterministic fault injection for resilience tests: a pyarrow-FS wrapper that fails,
delays, or kills the calling worker on a schedule.

Every recovery behavior in docs/robustness.md (retry, skip-with-quarantine, worker
respawn) is tested against this filesystem rather than against real network flakiness:
the schedule is explicit and the trigger state lives in ``state_dir`` as atomically
created marker files, so "fail the first N opens of path X" means the first N opens
**globally** — across every thread pool worker, every spawned process-pool worker, and
every respawned replacement — regardless of interleaving. That is what makes
fail-once-then-succeed deterministic on all three pools.

Usage::

    schedule = FaultSchedule(state_dir, [
        FaultRule('part_0', times=1, kind='fail'),          # first open of part_0 fails
        FaultRule('part_1', kind='latency', latency_s=0.2), # every open is slow
        FaultRule('part_2', kind='kill'),                   # SIGKILL the opening process
        FaultRule('part_3', kind='hang', times=1),          # opener sleeps "forever"
        FaultRule('part_4', kind='corrupt', times=1),       # bit-flip the file first
        FaultRule('part_5', kind='latency', latency_s=0.01, # p99-style tail: every
                  tail_latency_s=0.5, tail_every_n=10),     # 10th open/read stalls
    ])
    fs = fault_injecting_filesystem(schedule)               # wraps LocalFileSystem
    make_reader('file:///data', filesystem=fs, on_error='retry', ...)

``kind='hang'`` models the two real hang shapes the watchdog distinguishes
(docs/robustness.md): ``hang_mode='sleep'`` blocks only the opening thread
(GIL released — heartbeats keep flowing; only the per-item deadline catches
it), ``hang_mode='stop'`` SIGSTOPs the whole process (heartbeats stall — the
staleness reap catches it; the watchdog's SIGKILL terminates a stopped
process). ``kind='corrupt'`` damages the target FILE in place before the open
proceeds (``corrupt_mode='flip'`` bit-flips the middle byte,
``'truncate'`` halves it) — deterministic bit-rot for self-heal tests.

``kind='latency'`` with ``tail_every_n > 0`` models a latency *distribution*
rather than a constant: every matching open AND every read on the opened file
claims a marker-file sequence number and sleeps ``latency_s``, with
``tail_latency_s`` added on every ``tail_every_n``-th event globally. That is
a reproducible p99 tail — the storage engine's hedging tests
(docs/performance.md "Object-store ingest engine") assert that hedged fetches
beat it deterministically. ``tail_every_n == 0`` (the default) preserves the
original open-only constant sleep exactly.

The wrapper is picklable (ships to process-pool workers through the dill bootstrap) and
rebuilds its wrapped filesystem on unpickle.
"""

import os
import time

import pyarrow.fs as pafs

from petastorm_tpu.errors import TransientIOError

_FAULT_KINDS = ('fail', 'latency', 'kill', 'hang', 'corrupt')
_HANG_MODES = ('sleep', 'stop')
_CORRUPT_MODES = ('flip', 'truncate')


class FaultRule(object):
    """One injection rule, matched against the path of every intercepted open.

    :param path_substring: rule applies to paths containing this substring.
    :param kind: ``'fail'`` raises ``exception_type``; ``'latency'`` sleeps
        ``latency_s`` then proceeds; ``'kill'`` SIGKILLs the calling process (worker
        respawn tests — only ever schedule this against process-pool workers).
    :param times: trigger at most this many times globally (None = every time).
    :param after: skip the first ``after`` matching opens before triggering
        (``after=n-1, times=1`` = classic fail-Nth-open).
    :param latency_s: sleep duration for ``'latency'``.
    :param tail_latency_s: for ``'latency'``: extra sleep added on every
        ``tail_every_n``-th matching event (opens and reads share one global
        counter), turning the constant delay into a distribution with a
        deterministic tail.
    :param tail_every_n: for ``'latency'``: 0 (default) keeps the original
        open-only constant sleep; N > 0 also intercepts reads on the opened
        file and fires the tail on every N-th event.
    :param exception_type: exception class for ``'fail'`` — default
        :class:`TransientIOError` (retryable); pass e.g. ``ValueError`` to model a
        permanent fault.
    :param hang_mode: for ``'hang'``: ``'sleep'`` (block only the opening thread
        for ``hang_s`` — a GIL-releasing stall, caught by the per-item deadline)
        or ``'stop'`` (SIGSTOP the whole process — a process-wide wedge, caught
        by heartbeat staleness).
    :param hang_s: sleep duration for ``hang_mode='sleep'`` (default: effectively
        forever relative to any test deadline).
    :param corrupt_mode: for ``'corrupt'``: ``'flip'`` (XOR the middle byte of
        the target file) or ``'truncate'`` (halve it) before the open proceeds.
    """

    def __init__(self, path_substring, kind='fail', times=None, after=0,
                 latency_s=0.0, exception_type=TransientIOError,
                 hang_mode='sleep', hang_s=3600.0, corrupt_mode='flip',
                 tail_latency_s=0.0, tail_every_n=0):
        if kind not in _FAULT_KINDS:
            raise ValueError('kind must be one of {}, got {!r}'.format(_FAULT_KINDS, kind))
        if times is not None and times < 1:
            raise ValueError('times must be >= 1 or None')
        if after < 0:
            raise ValueError('after must be >= 0')
        if hang_mode not in _HANG_MODES:
            raise ValueError('hang_mode must be one of {}, got {!r}'
                             .format(_HANG_MODES, hang_mode))
        if corrupt_mode not in _CORRUPT_MODES:
            raise ValueError('corrupt_mode must be one of {}, got {!r}'
                             .format(_CORRUPT_MODES, corrupt_mode))
        if tail_every_n < 0:
            raise ValueError('tail_every_n must be >= 0')
        if tail_latency_s < 0:
            raise ValueError('tail_latency_s must be >= 0')
        self.path_substring = path_substring
        self.kind = kind
        self.times = times
        self.after = after
        self.latency_s = latency_s
        self.exception_type = exception_type
        self.hang_mode = hang_mode
        self.hang_s = hang_s
        self.corrupt_mode = corrupt_mode
        self.tail_latency_s = tail_latency_s
        self.tail_every_n = tail_every_n

    def matches(self, path):
        return self.path_substring in path


class FaultSchedule(object):
    """Ordered rules plus the shared trigger state. ``state_dir`` must be a local
    directory reachable by every worker process; marker files created with
    ``O_CREAT|O_EXCL`` make each trigger decision an atomic, once-only global event."""

    def __init__(self, state_dir, rules):
        self.state_dir = str(state_dir)
        self.rules = list(rules)
        os.makedirs(self.state_dir, exist_ok=True)

    def _claim(self, prefix):
        """Atomically claim the next slot for ``prefix``; returns the 0-based global
        sequence number this caller won (creation races retry on the next slot)."""
        index = 0
        while True:
            marker = os.path.join(self.state_dir, '{}.{}'.format(prefix, index))
            try:
                fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                index += 1
                continue
            os.close(fd)
            return index

    def on_open(self, path):
        """Run every matching rule for one open call; raises / sleeps / kills per the
        schedule. Called by the wrapper before delegating to the real filesystem."""
        for rule_index, rule in enumerate(self.rules):
            if not rule.matches(path):
                continue
            seq = self._claim('calls-{}'.format(rule_index))
            if seq < rule.after:
                continue
            if rule.times is not None and seq >= rule.after + rule.times:
                continue
            if rule.kind == 'latency':
                self._latency_sleep(rule, seq)
            elif rule.kind == 'kill':
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            elif rule.kind == 'hang':
                if rule.hang_mode == 'stop':
                    import signal
                    # process-wide wedge: every thread (heartbeat included)
                    # freezes; only the watchdog's SIGKILL ends it
                    os.kill(os.getpid(), signal.SIGSTOP)
                else:
                    time.sleep(rule.hang_s)
            elif rule.kind == 'corrupt':
                corrupt_file(path, rule.corrupt_mode)
            else:
                raise rule.exception_type(
                    'injected fault #{} for {!r} (rule {}: open of {})'
                    .format(seq + 1, rule.path_substring, rule_index, path))

    @staticmethod
    def _latency_sleep(rule, seq):
        """Sleep per the rule's latency distribution: the base delay always,
        plus the tail on every ``tail_every_n``-th global event (1-based, so
        ``tail_every_n=10`` stalls events 10, 20, ...)."""
        delay = rule.latency_s
        if rule.tail_every_n and (seq + 1) % rule.tail_every_n == 0:
            delay += rule.tail_latency_s
        if delay > 0:
            time.sleep(delay)

    def wants_read_latency(self, path):
        """True when some latency rule with a tail distribution matches ``path``
        — the wrapper then intercepts reads on the opened file too."""
        return any(rule.kind == 'latency' and rule.tail_every_n and
                   rule.matches(path) for rule in self.rules)

    def on_read(self, path):
        """Run the read-side of every tail-distribution latency rule for one
        read call. Reads claim from the SAME marker prefix as opens, so the
        every-N-th-event tail is global across both — what makes the injected
        p99 reproducible regardless of open/read interleaving."""
        for rule_index, rule in enumerate(self.rules):
            if rule.kind != 'latency' or not rule.tail_every_n:
                continue
            if not rule.matches(path):
                continue
            seq = self._claim('calls-{}'.format(rule_index))
            if seq < rule.after:
                continue
            if rule.times is not None and seq >= rule.after + rule.times:
                continue
            self._latency_sleep(rule, seq)

    def trigger_count(self, rule_index=None):
        """Opens observed so far (for a single rule, or summed) — lets tests assert the
        schedule actually fired."""
        counts = []
        for index in range(len(self.rules)):
            count = 0
            while os.path.exists(os.path.join(self.state_dir,
                                              'calls-{}.{}'.format(index, count))):
                count += 1
            counts.append(count)
        return counts[rule_index] if rule_index is not None else sum(counts)


def corrupt_file(path, corrupt_mode='flip'):
    """THE repo-wide file-damage model (rule ``kind='corrupt'``, and called
    directly by corruption tests so every self-heal test exercises identical
    damage): ``'flip'`` XORs the middle byte in place, ``'truncate'`` halves
    the file but never below 24 bytes — a leading magic/header stays intact, so
    the damage lands in the BODY that only a checksum can defend. Local paths
    only (the wrapper normalizes them before the base open)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return  # nothing to corrupt; let the real open report the miss
    if size == 0:
        return
    with open(path, 'r+b') as f:
        if corrupt_mode == 'truncate':
            f.truncate(max(24, size // 2))
        else:
            f.seek(size // 2)
            byte = f.read(1)
            f.seek(size // 2)
            f.write(bytes([byte[0] ^ 0xFF]))


class _TailLatencyFile(object):
    """File-like over an opened NativeFile that runs the schedule's read-side
    latency distribution before every read — the injected "slow GET" the
    storage engine's hedging races against. Wrapped in ``pa.PythonFile`` by
    the handler so pyarrow sees a normal random-access input file."""

    def __init__(self, raw, schedule, path):
        self._raw = raw
        self._schedule = schedule
        self._path = path

    def read(self, nbytes=None):
        self._schedule.on_read(self._path)
        if nbytes is None:
            return self._raw.read()
        return self._raw.read(nbytes)

    def seek(self, position, whence=0):
        return self._raw.seek(position, whence)

    def tell(self):
        return self._raw.tell()

    def readable(self):
        return True

    def seekable(self):
        return True

    def writable(self):
        return False

    def flush(self):
        pass

    def close(self):
        self._raw.close()

    @property
    def closed(self):
        return self._raw.closed


class FaultInjectingHandler(pafs.FileSystemHandler):
    """pyarrow FileSystemHandler delegating everything to a wrapped C++ filesystem,
    with the schedule's faults injected on input opens (the calls Parquet reads make)."""

    def __init__(self, schedule, base_filesystem=None):
        self._schedule = schedule
        self._base = base_filesystem if base_filesystem is not None \
            else pafs.LocalFileSystem()

    # -------------------------------------------------------------- intercepted
    def open_input_file(self, path):
        self._schedule.on_open(path)
        raw = self._base.open_input_file(path)
        if self._schedule.wants_read_latency(path):
            import pyarrow as pa
            return pa.PythonFile(_TailLatencyFile(raw, self._schedule, path),
                                 mode='r')
        return raw

    def open_input_stream(self, path):
        self._schedule.on_open(path)
        return self._base.open_input_stream(path)

    # -------------------------------------------------------------- delegation
    def get_type_name(self):
        return 'fault-injecting+{}'.format(self._base.type_name)

    def get_file_info(self, paths):
        return self._base.get_file_info(paths)

    def get_file_info_selector(self, selector):
        return self._base.get_file_info(selector)

    def create_dir(self, path, recursive):
        self._base.create_dir(path, recursive=recursive)

    def delete_dir(self, path):
        self._base.delete_dir(path)

    def delete_dir_contents(self, path, missing_dir_ok=False):
        self._base.delete_dir_contents(path, missing_dir_ok=missing_dir_ok)

    def delete_root_dir_contents(self):
        self._base.delete_dir_contents('/', accept_root_dir=True)

    def delete_file(self, path):
        self._base.delete_file(path)

    def move(self, src, dest):
        self._base.move(src, dest)

    def copy_file(self, src, dest):
        self._base.copy_file(src, dest)

    def open_output_stream(self, path, metadata):
        return self._base.open_output_stream(path, metadata=metadata)

    def open_append_stream(self, path, metadata):
        return self._base.open_append_stream(path, metadata=metadata)

    def normalize_path(self, path):
        return self._base.normalize_path(path)

    def __eq__(self, other):
        return isinstance(other, FaultInjectingHandler) and \
            self._schedule is other._schedule

    def __ne__(self, other):
        return not self.__eq__(other)


def fault_injecting_filesystem(schedule, base_filesystem=None):
    """A ``pyarrow.fs.FileSystem`` (PyFileSystem-wrapped) that injects ``schedule``'s
    faults in front of ``base_filesystem`` (default: LocalFileSystem). Feed it to
    ``make_reader(..., filesystem=...)``."""
    return pafs.PyFileSystem(FaultInjectingHandler(schedule, base_filesystem))


class FaultInjectingFilesystemFactory(object):
    """Picklable zero-arg factory (the shape worker processes ship, mirroring
    ``fs_utils.FilesystemFactory``): rebuilds the fault-injecting filesystem from the
    schedule inside each worker. The schedule's file-based state keeps trigger counts
    global across the processes that rebuild it."""

    def __init__(self, schedule, base_filesystem=None):
        self._schedule = schedule
        self._base = base_filesystem

    def __call__(self):
        return fault_injecting_filesystem(self._schedule, self._base)
