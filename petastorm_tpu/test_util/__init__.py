"""Test scaffolding shipped with the package (reference: petastorm/test_util/)."""

from petastorm_tpu.test_util.reader_mock import ReaderMock  # noqa: F401
